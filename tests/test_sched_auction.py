"""Auction kernel: invariants + optimality vs the scipy Hungarian oracle."""

import numpy as np
import pytest

from tpu_faas.sched.auction import auction_placement
from tpu_faas.sched.oracle import optimal_assignment
from tpu_faas.sched.problem import PlacementProblem, check_assignment


def _run(sizes, speeds, free, live, max_slots=4, eps=1e-4):
    p = PlacementProblem.build(sizes, speeds, free, live, T=len(sizes) and None)
    res = auction_placement(
        p.task_size, p.task_valid, p.worker_speed, p.worker_free,
        p.worker_live, max_slots=max_slots, eps=eps,
    )
    return p, np.asarray(res.assignment), int(res.n_rounds)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_auction_invariants_random(seed):
    rng = np.random.default_rng(seed)
    sizes = rng.uniform(0.5, 5.0, 60).astype(np.float32)
    speeds = rng.uniform(0.5, 4.0, 16).astype(np.float32)
    free = rng.integers(0, 5, 16).astype(np.int32)
    live = rng.random(16) > 0.2
    p, a, rounds = _run(sizes, speeds, free, live)
    check_assignment(
        a, np.asarray(p.task_valid), np.asarray(p.worker_free),
        np.asarray(p.worker_live),
    )
    cap = int(np.minimum(free, 4)[live].sum())
    assert (a >= 0).sum() == min(len(sizes), cap)
    assert rounds > 0


def test_auction_matches_hungarian_total_cost():
    """Near-optimality: total cost within n*eps of the exact assignment."""
    rng = np.random.default_rng(7)
    n_tasks, n_workers, max_slots = 40, 12, 4
    sizes = rng.uniform(0.5, 8.0, n_tasks).astype(np.float32)
    speeds = rng.uniform(0.5, 4.0, n_workers).astype(np.float32)
    free = np.full(n_workers, max_slots, dtype=np.int32)
    live = np.ones(n_workers, dtype=bool)
    eps = 1e-4

    _, a, _ = _run(sizes, speeds, free, live, max_slots=max_slots, eps=eps)
    placed = a[: n_tasks] >= 0
    assert placed.all()
    cost_auction = float(np.sum(sizes[placed] / speeds[a[:n_tasks][placed]]))

    _, cost_opt = optimal_assignment(sizes, speeds, free, live, max_slots)
    assert cost_auction <= cost_opt + n_tasks * eps * 10 + 1e-3


def test_auction_single_best_worker():
    # one fast worker with capacity for everything -> all tasks land there
    _, a, _ = _run([1.0, 2.0, 3.0], [10.0, 0.1], [4, 4], [True, True],
                   max_slots=4)
    assert (a[:3] == 0).all()


def test_auction_excess_tasks_admitted_by_arrival():
    # 2 slots, 4 tasks: the two earliest-arrival tasks get placed
    _, a, _ = _run([5.0, 4.0, 3.0, 2.0], [1.0], [2], [True], max_slots=2)
    assert (a[:2] >= 0).all()
    assert (a[2:4] == -1).all()


def test_auction_no_capacity():
    _, a, _ = _run([1.0, 1.0], [1.0, 1.0], [0, 0], [True, True])
    assert (a == -1).all()


def test_auction_warm_start_converges_faster_and_stays_optimal():
    """Steady-state dispatcher model: consecutive ticks solve similar
    problems; warm prices (and the analytic rank-dual cold seed) must cut
    rounds sharply vs the classic eps-ladder without costing optimality
    (the n*eps bound holds for any initial prices)."""
    rng = np.random.default_rng(11)
    n_tasks, n_workers, max_slots, eps = 48, 12, 4, 1e-4
    speeds = rng.uniform(0.5, 4.0, n_workers).astype(np.float32)
    free = np.full(n_workers, max_slots, dtype=np.int32)
    live = np.ones(n_workers, dtype=bool)
    sizes = rng.uniform(0.5, 8.0, n_tasks).astype(np.float32)

    p0 = PlacementProblem.build(sizes, speeds, free, live)
    ladder = auction_placement(
        p0.task_size, p0.task_valid, p0.worker_speed, p0.worker_free,
        p0.worker_live, max_slots=max_slots, eps=eps, seed_from_rank=False,
    )
    ladder_rounds = int(ladder.n_rounds)
    res0 = auction_placement(
        p0.task_size, p0.task_valid, p0.worker_speed, p0.worker_free,
        p0.worker_live, max_slots=max_slots, eps=eps,
    )
    seeded_rounds = int(res0.n_rounds)
    # the analytic dual seed replaces the whole phase ladder's climb
    assert seeded_rounds < ladder_rounds, (seeded_rounds, ladder_rounds)
    a0 = np.asarray(res0.assignment)
    cost_seed = float(np.sum(sizes / speeds[a0[:n_tasks]]))
    _, cost_opt0 = optimal_assignment(sizes, speeds, free, live, max_slots)
    assert cost_seed <= cost_opt0 + n_tasks * eps * 10 + 1e-3

    # next tick: same fleet, slightly perturbed task sizes (a realistic
    # tick-over-tick delta), warm-started from last tick's prices
    sizes2 = (sizes * (1.0 + rng.uniform(-0.01, 0.01, n_tasks))).astype(
        np.float32
    )
    p1 = PlacementProblem.build(sizes2, speeds, free, live)
    res1 = auction_placement(
        p1.task_size, p1.task_valid, p1.worker_speed, p1.worker_free,
        p1.worker_live, max_slots=max_slots, eps=eps,
        init_price=res0.prices,
    )
    a1 = np.asarray(res1.assignment)
    warm_rounds = int(res1.n_rounds)
    check_assignment(
        a1, np.asarray(p1.task_valid), np.asarray(p1.worker_free),
        np.asarray(p1.worker_live),
    )
    placed = a1[:n_tasks] >= 0
    # the rank spill closes any budget-exhausted tail IN-TICK: the warm
    # tick's placement is always complete
    assert placed.all()
    assert not bool(res1.stranded)
    if int(res1.n_spilled) == 0:
        # fully converged warm bidding: the n*eps optimality bound holds
        cost_warm = float(np.sum(sizes2[placed] / speeds[a1[:n_tasks]][placed]))
        _, cost_opt = optimal_assignment(
            sizes2, speeds, free, live, max_slots
        )
        assert cost_warm <= cost_opt + n_tasks * eps * 10 + 1e-3
    assert warm_rounds < ladder_rounds, (warm_rounds, ladder_rounds)


def test_auction_warm_stale_prices_complete_same_tick():
    """Adversarial (stale) starting prices exhaust the warm round budget;
    the rank spill must still complete the placement IN THE SAME TICK,
    keep it legal, and — when the spilled tail is large — raise `refresh`
    so the caller re-solves cold next tick (round-3 verdict item 10)."""
    rng = np.random.default_rng(13)
    sizes = rng.uniform(0.5, 5.0, 30).astype(np.float32)
    speeds = rng.uniform(0.5, 4.0, 8).astype(np.float32)
    free = np.full(8, 4, dtype=np.int32)
    live = np.ones(8, dtype=bool)
    p = PlacementProblem.build(sizes, speeds, free, live)
    S = p.worker_speed.shape[0] * 4
    garbage = np.asarray(rng.uniform(0.0, 50.0, S), dtype=np.float32)
    import jax.numpy as jnp

    res = auction_placement(
        p.task_size, p.task_valid, p.worker_speed, p.worker_free,
        p.worker_live, max_slots=4, eps=1e-4, warm_rounds=2,
        init_price=jnp.asarray(garbage),
    )
    a = np.asarray(res.assignment)
    check_assignment(
        a, np.asarray(p.task_valid), np.asarray(p.worker_free),
        np.asarray(p.worker_live),
    )
    # complete placement despite the stale prices and the tiny budget
    assert (a >= 0).sum() == min(30, int(free.sum()))
    assert not bool(res.stranded)
    if int(res.n_spilled) > 8 and int(res.n_spilled) * 20 > 30:
        assert bool(res.refresh)
    # the cold re-solve the refresh flag triggers completes cleanly
    cold = auction_placement(
        p.task_size, p.task_valid, p.worker_speed, p.worker_free,
        p.worker_live, max_slots=4, eps=1e-4,
    )
    ac = np.asarray(cold.assignment)
    assert (ac >= 0).sum() == min(30, int(free.sum()))
    assert not bool(cold.stranded)


def test_auction_small_spilled_tail_keeps_warm_prices():
    """A budget-exhausted tick whose spilled tail is SMALL must not raise
    `refresh`: near-equilibrium prices with a near-tied remainder are the
    warm start's home turf (round-3 advisor finding: the old single flag
    made such workloads re-solve cold every tick)."""
    # uniform sizes/speeds: the seeded cold path assigns the bulk in the
    # opening rounds and any remainder is pure tie-breaking
    n_tasks, n_workers = 64, 8
    sizes = np.full(n_tasks, 2.0, dtype=np.float32)
    speeds = np.full(n_workers, 1.0, dtype=np.float32)
    free = np.full(n_workers, 8, dtype=np.int32)
    live = np.ones(n_workers, dtype=bool)
    p = PlacementProblem.build(sizes, speeds, free, live)
    res = auction_placement(
        p.task_size, p.task_valid, p.worker_speed, p.worker_free,
        p.worker_live, max_slots=8, eps=1e-3,
    )
    a = np.asarray(res.assignment)
    assert (a >= 0).sum() == min(n_tasks, int(free.sum()))
    assert not bool(res.stranded)
    # complete or near-complete bidding on this degenerate case: whatever
    # tail spilled must be under the refresh threshold
    assert not bool(res.refresh), int(res.n_spilled)


def test_scheduler_arrays_resets_prices_after_refresh(monkeypatch):
    """Product path: a warm tick that flagged `refresh` (stale prices)
    makes the NEXT tick re-solve cold (init_price=None). A spy on the
    packed-tick entry records the price argument each tick actually ran
    with — asserting on attributes alone could not detect a removed
    reset, since every auction tick repopulates them."""
    import jax.numpy as jnp

    from tpu_faas.sched import state as state_mod
    from tpu_faas.sched.state import SchedulerArrays

    price_args = []
    real = state_mod._packed_tick

    def spy(packed, n_valid, ws, wa, pl, iw, tte, prio, price, **kw):
        price_args.append(price)
        return real(packed, n_valid, ws, wa, pl, iw, tte, prio, price, **kw)

    monkeypatch.setattr(state_mod, "_packed_tick", spy)

    rng = np.random.default_rng(19)
    arr = SchedulerArrays(
        max_workers=8, max_pending=64, max_slots=4, placement="auction",
        clock=lambda: 100.0,
    )
    for i in range(6):
        arr.register(b"w%d" % i, 4, speed=float(1.0 + i % 3))
    sizes = rng.uniform(0.5, 5.0, 24).astype(np.float32)
    arr.tick(sizes)  # cold: seeds warm prices
    assert price_args[0] is None
    # force the refresh flag (as a warm tick with stale prices would)
    arr._d_auction_refresh = jnp.asarray(True)
    out = arr.tick(sizes)
    # the reset must have made THIS tick cold again
    assert price_args[1] is None
    a = np.asarray(out.assignment)
    assert (a >= 0).sum() == min(24, 6 * 4)
    # and a non-refreshing tick warm-starts from the previous prices
    arr.tick(sizes)
    assert price_args[2] is not None


def test_scheduler_arrays_auction_carries_prices_across_ticks():
    """The product path: SchedulerArrays(placement='auction') feeds each
    tick's prices into the next (device-resident warm start)."""
    from tpu_faas.sched.state import SchedulerArrays

    rng = np.random.default_rng(17)
    arr = SchedulerArrays(
        max_workers=8, max_pending=64, max_slots=4, placement="auction",
        clock=lambda: 100.0,
    )
    for i in range(6):
        arr.register(b"w%d" % i, 4, speed=float(1.0 + i % 3))
    assert arr._d_auction_price is None
    sizes = rng.uniform(0.5, 5.0, 40).astype(np.float32)
    out1 = arr.tick(sizes)
    assert arr._d_auction_price is not None
    a1 = np.asarray(out1.assignment)
    assert (a1 >= 0).sum() == min(40, 6 * 4)
    # second tick warm-starts; placement stays legal and complete
    out2 = arr.tick(sizes * 1.01)
    a2 = np.asarray(out2.assignment)
    assert (a2 >= 0).sum() == min(40, 6 * 4)
    used, counts = np.unique(a2[a2 >= 0], return_counts=True)
    assert (counts <= 4).all() and (used < 6).all()


def test_auction_spill_cost_near_converged():
    """Bounded rounds + rank spill vs the fully-converged eps-ladder on a
    heterogeneous problem: placement must be complete and the total-cost
    delta small (the spilled tail is near-indifferent by construction)."""
    rng = np.random.default_rng(23)
    n_tasks, n_workers, max_slots = 600, 60, 4
    speeds = rng.uniform(0.5, 4.0, n_workers).astype(np.float32)
    free = np.full(n_workers, max_slots, dtype=np.int32)
    live = np.ones(n_workers, dtype=bool)
    sizes = rng.lognormal(0.0, 1.0, n_tasks).astype(np.float32)
    p = PlacementProblem.build(sizes, speeds, free, live)

    seeded = auction_placement(
        p.task_size, p.task_valid, p.worker_speed, p.worker_free,
        p.worker_live, max_slots=max_slots, eps=1e-3,
    )
    ladder = auction_placement(
        p.task_size, p.task_valid, p.worker_speed, p.worker_free,
        p.worker_live, max_slots=max_slots, eps=1e-3,
        seed_from_rank=False, max_rounds=20000,
    )

    def total_cost(res):
        a = np.asarray(res.assignment)[:n_tasks]
        placed = a >= 0
        assert placed.sum() == min(n_tasks, int(free.sum()))
        return float(np.sum(sizes[placed] / speeds[a[placed]]))

    c_seed, c_ladder = total_cost(seeded), total_cost(ladder)
    assert c_seed <= c_ladder * 1.01, (c_seed, c_ladder)
    # and the seeded path did a fraction of the ladder's rounds
    assert int(seeded.n_rounds) < int(ladder.n_rounds) / 2
