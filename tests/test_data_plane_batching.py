"""The pipelined host data plane: batched intake, coalesced RUNNING
writes, batched result path — and their outage semantics.

The tentpole claim: at the headline shape the host acts on a ~1 ms device
decision with a BOUNDED number of pipelined store rounds per tick, not one
round trip per task. These tests pin the counter that proves it, and inject
a store outage into the middle of each pipelined flush to show the batched
forms keep the old per-task guarantees: no task lost, no double dispatch,
deferred-result order preserved.
"""

from __future__ import annotations

import time

import pytest

from tpu_faas.core.task import FIELD_LEASE_AT, FIELD_STATUS
from tpu_faas.dispatch.base import PendingQueue, PendingTask, TaskDispatcher
from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
from tpu_faas.store import MemoryStore
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.worker import messages as m


class FlakyStore:
    """TaskStore wrapper that fails selected calls once with a
    ConnectionError (the STORE_OUTAGE_ERRORS family), then recovers —
    the injection point for mid-pipelined-flush outages."""

    def __init__(self, inner):
        self.inner = inner
        self._fail: set[str] = set()
        self._fail_until_cleared: set[str] = set()
        self.calls: dict[str, int] = {}

    def fail_once(self, method: str) -> None:
        self._fail.add(method)

    def fail_on(self, method: str) -> None:
        """Persistent outage for ``method`` until clear() — for paths where
        the number of batched flushes isn't deterministic (e.g. results
        arriving across several socket drains)."""
        self._fail_until_cleared.add(method)

    def clear(self, method: str) -> None:
        self._fail_until_cleared.discard(method)

    def _gate(self, name: str) -> None:
        self.calls[name] = self.calls.get(name, 0) + 1
        if name in self._fail_until_cleared:
            raise ConnectionError(f"injected outage in {name}")
        if name in self._fail:
            self._fail.discard(name)
            raise ConnectionError(f"injected outage in {name}")

    def hgetall_many(self, keys):
        self._gate("hgetall_many")
        return self.inner.hgetall_many(keys)

    def set_status_many(self, status, items):
        self._gate("set_status_many")
        return self.inner.set_status_many(status, items)

    def finish_task_many(self, items, inline_max: int = 0):
        self._gate("finish_task_many")
        return self.inner.finish_task_many(items, inline_max=inline_max)

    def __getattr__(self, name):
        return getattr(self.inner, name)


def _tpu_dispatcher(store, **kw):
    defaults = dict(
        ip="127.0.0.1",
        port=0,
        store=store,
        max_workers=8,
        max_pending=64,
        max_inflight=128,
        recover_queued=False,
        time_to_expire=30.0,
    )
    defaults.update(kw)
    return TpuPushDispatcher(**defaults)


# -- the acceptance counter: bounded pipelined rounds per tick ---------------


def test_round_trips_per_tick_bounded_at_batch_intake():
    """200 announced tasks dispatch in ONE tick over a real RESP server
    with a BOUNDED number of store rounds (the reference pattern pays one
    hgetall per announce + one status write per dispatch = 400+). The
    ≤5 bound is the ISSUE's acceptance criterion, excluding the result
    drain; the actual count today is 2 (intake fetch + RUNNING flush)."""
    handle = start_store_thread()
    store = make_store(handle.url)
    feeder = make_store(handle.url)
    disp = _tpu_dispatcher(store, max_workers=64, max_pending=256, max_inflight=512)
    try:
        for i in range(64):
            disp._handle(f"w{i}".encode(), m.REGISTER, {"num_processes": 4})
        disp.tick()  # compile the device step before counting
        feeder.create_tasks([(f"t{i}", "F", "P") for i in range(200)])
        rt0 = store.n_round_trips
        sent = disp.tick()
        delta = store.n_round_trips - rt0
        assert sent == 200
        assert delta <= 5, f"act phase paid {delta} store rounds for 200 tasks"
        # the per-tick counter surfaces the same number in /stats
        stats = disp.stats()
        assert stats["store_round_trips_last_tick"] == delta
        assert stats["batched_write_sizes"]["intake"] == 200
        assert stats["batched_write_sizes"]["mark_running"] == 200
        # the intake/act phases joined device_tick in the tracer
        assert stats["intake_phase"]["count"] >= 1
        assert stats["act_phase"]["count"] >= 1
        # the coalesced RUNNING flush still stamps every ownership lease
        statuses = feeder.hget_many([f"t{i}" for i in range(200)], FIELD_STATUS)
        assert statuses == ["RUNNING"] * 200
        assert feeder.hget("t0", FIELD_LEASE_AT) is not None
        # serve-loop shape: _intake OUTSIDE the tick (start() drains the
        # bus itself, then ticks with intake=False) — those intake rounds
        # must carry into the next tick's counter, not vanish
        feeder.create_tasks([(f"s{i}", "F", "P") for i in range(30)])
        rt0 = store.n_round_trips
        disp._intake()
        assert disp.tick(intake=False) == 30
        delta = store.n_round_trips - rt0
        assert delta <= 5
        assert disp.stats()["store_round_trips_last_tick"] == delta
    finally:
        disp.socket.close(linger=0)
        disp.close()
        feeder.close()
        handle.stop()


# -- outage injected mid-pipelined-flush -------------------------------------


def test_outage_mid_running_flush_loses_nothing_and_never_doubles():
    """The coalesced RUNNING flush hits an outage AFTER the sends: the
    tick must not raise (degrade contract of mark_running_safe), every
    task stays tracked in flight (no loss), and no later tick dispatches
    them again (no double dispatch). The terminal result write supersedes
    the missing RUNNING mark, exactly as on the per-task path."""
    s = FlakyStore(MemoryStore())
    disp = _tpu_dispatcher(s)
    try:
        disp._handle(b"w0", m.REGISTER, {"num_processes": 4})
        for i in range(3):
            s.create_task(f"t{i}", "F", "P", "tasks")
        s.fail_once("set_status_many")
        assert disp.tick() == 3  # degraded, not raised
        # marks skipped: records still read QUEUED, but the tasks are on
        # the wire and tracked — nothing may re-dispatch them
        for i in range(3):
            assert s.get_status(f"t{i}") == "QUEUED"
            assert disp.arrays.inflight_owner(f"t{i}") is not None
        assert len(disp.pending) == 0
        assert disp.tick() == 0  # no double dispatch
        # results land through the ordinary path and supersede the marks
        for i in range(3):
            disp._handle(
                b"w0",
                m.RESULT,
                {"task_id": f"t{i}", "status": "COMPLETED", "result": "R"},
            )
        for i in range(3):
            assert s.get_result(f"t{i}") == ("COMPLETED", "R")
        assert disp.tick() == 0
    finally:
        disp.socket.close(linger=0)


def test_outage_mid_result_flush_defers_all_in_order():
    """finish_task_many dies mid-flush: every item of the batch parks in
    deferred_results in arrival order, and the replay (also pipelined)
    restores them in that order once the store is back — first_wins flags
    ride along untouched."""
    s = FlakyStore(MemoryStore())
    disp = _tpu_dispatcher(s)
    try:
        for i in range(4):
            s.create_task(f"t{i}", "F", "P", "tasks")
        items = [
            ("t0", "COMPLETED", "r0", False),
            ("t1", "FAILED", "r1", False),
            ("t2", "COMPLETED", "r2", True),
            ("t3", "COMPLETED", "r3", False),
        ]
        s.fail_once("finish_task_many")
        assert disp.record_results_safe(items) == 0
        assert list(disp.deferred_results) == items  # order preserved
        # store untouched during the outage window (MemoryStore inner was
        # never reached): everything still QUEUED
        assert s.get_status("t0") == "QUEUED"
        # store back: one batched replay drains the queue in order
        assert disp.flush_deferred_results() == 4
        assert not disp.deferred_results
        assert s.get_result("t0") == ("COMPLETED", "r0")
        assert s.get_result("t1") == ("FAILED", "r1")
        assert s.get_result("t2") == ("COMPLETED", "r2")
        assert s.get_result("t3") == ("COMPLETED", "r3")
    finally:
        disp.socket.close(linger=0)


def test_outage_mid_intake_fetch_parks_every_announce():
    """The single pipelined record fetch fails: every drained announce —
    its bus copy is spent — parks back at the head of the backlog in
    order, and the next poll delivers each task exactly once."""
    s = FlakyStore(MemoryStore())
    d = TaskDispatcher(store=s)
    for i in range(5):
        s.create_task(f"t{i}", "fn", "p", "tasks")
    s.fail_once("hgetall_many")
    with pytest.raises(ConnectionError):
        d.poll_tasks(10)
    assert d.stats()["announce_backlog"] == 5
    got = d.poll_tasks(10)
    assert [t.task_id for t in got] == [f"t{i}" for i in range(5)]
    assert d.stats()["announce_backlog"] == 0
    assert d.poll_tasks(10) == []  # delivered exactly once


def test_batched_drain_flushes_results_in_one_round(tmp_path):
    """The serve loop's drain wrapper: RESULT messages arriving over the
    real ROUTER socket are bookkept per message but their terminal writes
    flush as one finish_task_many batch; an injected outage defers them
    and the next loop iteration replays."""
    import zmq

    s = FlakyStore(MemoryStore())
    disp = _tpu_dispatcher(s)
    dealer = None
    try:
        ctx = zmq.Context.instance()
        dealer = ctx.socket(zmq.DEALER)
        dealer.connect(f"tcp://127.0.0.1:{disp.port}")
        dealer.send(m.encode(m.REGISTER, num_processes=2))
        # condition waits throughout, with load-proof deadlines: under
        # full-suite load the ZMQ delivery and the GIL can stretch any
        # single step by seconds — the asserts are about WHAT happens
        # (registration, dispatch, deferral, replay), never how fast
        deadline = time.monotonic() + 60
        while not disp.arrays.worker_ids and time.monotonic() < deadline:
            if dict(disp.poller.poll(100)):
                disp.drain_results_batched()
        assert disp.arrays.worker_ids
        s.create_task("a", "F", "P", "tasks")
        s.create_task("b", "F", "P", "tasks")
        dispatched = disp.tick()
        deadline = time.monotonic() + 60
        while dispatched < 2 and time.monotonic() < deadline:
            dispatched += disp.tick()
        assert dispatched == 2
        for _ in range(2):
            parts = dealer.recv_multipart()
            msg_type, data = m.decode(parts[-1])
            assert msg_type == m.TASK
            dealer.send(
                m.encode(
                    m.RESULT,
                    task_id=data["task_id"],
                    status="COMPLETED",
                    result="R",
                )
            )
        # persistent outage: the two results may arrive across SEPARATE
        # drains (each with its own flush), so every flush must defer
        s.fail_on("finish_task_many")
        deadline = time.monotonic() + 60
        while len(disp.deferred_results) < 2 and time.monotonic() < deadline:
            if dict(disp.poller.poll(100)):
                disp.drain_results_batched()
        assert disp.n_results == 2
        # every flush hit the injected outage: both writes deferred, in
        # arrival order
        assert [item[0] for item in disp.deferred_results] == ["a", "b"]
        s.clear("finish_task_many")
        assert disp.flush_deferred_results() == 2
        assert s.get_result("a") == ("COMPLETED", "R")
        assert s.get_result("b") == ("COMPLETED", "R")
        assert disp.stats()["batched_write_sizes"]["results"] == 2
    finally:
        if dealer is not None:
            dealer.close(linger=0)
        disp.socket.close(linger=0)


def test_outage_mid_intake_reparks_unclaimed_batch():
    """Tasks popped OFF the _unclaimed deque into the intake batch must be
    re-parked when the pipelined record fetch raises — their announces are
    long spent, so dropping them with the aborted batch would lose tasks."""
    s = FlakyStore(MemoryStore())
    disp = _tpu_dispatcher(s)
    try:
        disp._unclaimed.append(PendingTask("u1", "F", "P"))
        disp._unclaimed.append(PendingTask("u2", "F", "P"))
        s.create_task("t0", "F", "P", "tasks")
        s.fail_once("hgetall_many")
        with pytest.raises(ConnectionError):
            disp._intake()
        assert [t.task_id for t in disp._unclaimed] == ["u1", "u2"]
        # store back: everything dispatches exactly once
        disp._handle(b"w0", m.REGISTER, {"num_processes": 4})
        assert disp.tick() == 3
        assert disp.tick() == 0
    finally:
        disp.socket.close(linger=0)


# -- the persistent pending-id index -----------------------------------------


def test_pending_queue_membership_tracks_enqueue_dequeue():
    q = PendingQueue()
    t1 = PendingTask("a", "f", "p")
    t2 = PendingTask("b", "f", "p")
    q.append(t1)
    q.appendleft(t2)
    assert "a" in q and "b" in q and "c" not in q
    assert len(q) == 2 and q.task_ids() == {"a", "b"}
    assert q.popleft() is t2
    assert "b" not in q and "a" in q
    # multiset semantics: a double-append survives one pop
    q.append(PendingTask("a", "f", "p"))
    q.popleft()
    assert "a" in q
    q.popleft()
    assert "a" not in q and len(q) == 0


def test_intake_dedup_uses_persistent_index():
    """A task adopted into pending (rescan path) whose announce is still
    buffered must not enter twice — now via the maintained id index, not a
    per-tick seen-set rebuild."""
    s = MemoryStore()
    disp = _tpu_dispatcher(s)
    try:
        s.create_task("dup", "F", "P", "tasks")
        # simulate a rescan adoption landing before the announce drains
        disp.pending.append(PendingTask("dup", "F", "P"))
        disp._intake()
        assert len(disp.pending) == 1
    finally:
        disp.socket.close(linger=0)
