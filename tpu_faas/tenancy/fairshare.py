"""The in-tick weighted-fair admission kernels.

Where they run: :func:`tpu_faas.sched.state.scheduler_tick_impl` calls the
``_impl`` forms directly, so the SAME traced ops serve the jitted XLA
tick, the mesh tick, AND the fused Pallas resident kernel (which traces
``scheduler_tick_impl`` inside one ``pallas_call`` — a pjit primitive
would not lower there, hence the un-jitted twins, exactly like the
solver stack's ``_impl`` split in PR 11). Parity between the two resident
backends with tenant state in play is pinned by tests/test_tenancy.py.

Policy (start-time fair queuing over the admission lane):

- every pending task gets a **virtual position**
  ``v = (j + 1 - deficit[t]) / share[t]`` where ``j`` is its FCFS rank
  WITHIN its tenant's backlog; admission under contention follows
  ascending ``v`` — so two backlogged tenants with shares 2:1 are
  admitted ~2:1 in any prefix, while an idle tenant consumes nothing and
  its capacity spills to whoever is backlogged (**work-conserving**, the
  property a hard per-tick quota mask lacks: the bench's heavy tenant
  must still saturate the fleet when the light tenant naps);
- **per-tenant inflight caps** are the one HARD mask: a tenant whose
  dispatched-but-unreturned count reached its cap has its surplus rows
  masked out of ``task_valid`` right where placement happens — they stay
  QUEUED on device and retry next tick. Caps are isolation, deliberately
  not work-conservation;
- **deficit counters** carry under-service across ticks: after placement
  each backlogged tenant's deficit moves by (its share-weighted
  entitlement of the work actually placed) minus (what it got), clamped
  to [0, cap]; a tenant with nothing eligible resets to 0 (classic DRR —
  credit is for waiting work, not for absence). The deficit shifts the
  tenant's whole queue earlier in virtual time, and past
  ``starve_deficit`` it boosts the tenant's tasks by ``starve_boost``
  priority classes — the starvation guard riding the EXISTING priority
  lane (rank placement's admission key), not a second mechanism;
- client ``priority`` hints still dominate: the admission order is
  (effective priority desc, virtual position asc, arrival asc). Equal-
  priority traffic is exactly weighted-fair; a priority class is still a
  hard class.

Shape note: the within-tenant rank sorts an i32 key ``tenant * T + row``,
so ``(max_tenants + 1) * max_pending`` must stay inside int32 — at the
default 32 tenants that allows ~65M pending rows, two orders past the
500k headline shape.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp

#: deficit clamp (tasks): bounds the catch-up burst a long-starved tenant
#: can claim at once, and with it the virtual-time shift
DEFAULT_DEFICIT_CAP = 4096.0
#: deficit at which the starvation guard engages
DEFAULT_STARVE_DEFICIT = 1024.0
#: priority classes a starving tenant's tasks are boosted by
DEFAULT_STARVE_BOOST = 1


def tenant_fair_admission_impl(
    task_valid: jnp.ndarray,  # bool[T]
    task_tenant: jnp.ndarray,  # i32[T] dense tenant row per task
    task_priority: jnp.ndarray | None,  # i32[T] client hints (None = all 0)
    tenant_share: jnp.ndarray,  # f32[N] positive weights
    tenant_deficit: jnp.ndarray,  # f32[N] carried under-service
    tenant_ahead: jnp.ndarray,  # i32[N] dispatched-but-unreturned per row
    tenant_cap: jnp.ndarray,  # i32[N] inflight ceilings (0 = uncapped)
    starve_deficit: float = DEFAULT_STARVE_DEFICIT,
    starve_boost: int = DEFAULT_STARVE_BOOST,
):
    """Returns ``(eligible bool[T], adm_rank i32[T], demand bool[N])``.

    ``eligible`` is ``task_valid`` minus the rows past their tenant's
    inflight-cap allowance; ``adm_rank`` is each task's position in the
    full admission order (eligible tasks occupy ranks ``0..n_eligible-1``)
    for the rank placement's admission cut; ``demand`` marks tenants with
    at least one eligible task this tick (the deficit update's DRR gate).
    """
    T = task_valid.shape[0]
    N = tenant_share.shape[0]
    t = jnp.clip(task_tenant, 0, N - 1)
    idx = jnp.arange(T, dtype=jnp.int32)

    # -- FCFS rank within each tenant's valid backlog ----------------------
    # one stable sort groups rows by tenant (invalid sink to segment N);
    # within a segment, rank = position minus the segment start
    seg = jnp.where(task_valid, t, N)
    order = jnp.argsort(seg * T + idx)
    seg_sorted = seg[order]
    is_start = jnp.concatenate(
        [jnp.ones(1, dtype=bool), seg_sorted[1:] != seg_sorted[:-1]]
    )
    start = jax.lax.cummax(jnp.where(is_start, idx, 0))
    j = jnp.zeros(T, dtype=jnp.int32).at[order].set(idx - start)

    # -- hard eligibility: per-tenant inflight caps ------------------------
    allowance = jnp.where(
        tenant_cap > 0,
        jnp.maximum(tenant_cap - tenant_ahead, 0),
        jnp.int32(T),
    )
    eligible = task_valid & (j < allowance[t])
    demand = (
        jnp.zeros(N, dtype=bool)
        .at[jnp.where(eligible, t, N)]
        .set(True, mode="drop")
    )

    # -- the admission order -----------------------------------------------
    share = jnp.maximum(tenant_share, 1e-6)
    v = (j.astype(jnp.float32) + 1.0 - tenant_deficit[t]) / share[t]
    prio = (
        jnp.zeros(T, dtype=jnp.int32)
        if task_priority is None
        else task_priority.astype(jnp.int32)
    )
    boost = jnp.where(
        tenant_deficit[t] >= jnp.float32(starve_deficit),
        jnp.int32(starve_boost),
        0,
    )
    eff_prio = prio + boost
    # lexsort: LAST key is primary — eligible first, then priority desc,
    # then virtual position asc, then arrival asc (the stable tie-break)
    adm_order = jnp.lexsort(
        (idx, v, -eff_prio, (~eligible).astype(jnp.int32))
    )
    adm_rank = jnp.zeros(T, dtype=jnp.int32).at[adm_order].set(idx)
    return eligible, adm_rank, demand


def tenant_deficit_update_impl(
    assignment: jnp.ndarray,  # i32[T] worker per task, -1 = stayed queued
    task_tenant: jnp.ndarray,  # i32[T]
    demand: jnp.ndarray,  # bool[N] from the admission pass
    tenant_share: jnp.ndarray,  # f32[N]
    tenant_deficit: jnp.ndarray,  # f32[N] carried in
    deficit_cap: float = DEFAULT_DEFICIT_CAP,
) -> jnp.ndarray:
    """The post-placement deficit carry: each backlogged tenant is
    entitled to its share-weighted fraction (normalized over backlogged
    tenants only — idle shares don't dilute) of the placements the tick
    actually made; under-service accumulates, service repays it, and a
    tenant with no eligible work resets (DRR). Clamped to
    ``[0, deficit_cap]``."""
    N = tenant_share.shape[0]
    t = jnp.clip(task_tenant, 0, N - 1)
    placed = (
        jnp.zeros(N, dtype=jnp.float32)
        .at[jnp.where(assignment >= 0, t, N)]
        .add(1.0, mode="drop")
    )
    total = placed.sum()
    w = jnp.where(demand, jnp.maximum(tenant_share, 1e-6), 0.0)
    entitled = w / jnp.maximum(w.sum(), 1e-9) * total
    new = jnp.clip(
        tenant_deficit + entitled - placed, 0.0, jnp.float32(deficit_cap)
    )
    return jnp.where(demand, new, 0.0)


#: jitted forms for host-side callers (tests, standalone use); the tick
#: paths trace the _impl twins directly.
tenant_fair_admission = partial(
    jax.jit, static_argnames=("starve_deficit", "starve_boost")
)(tenant_fair_admission_impl)
tenant_deficit_update = partial(jax.jit, static_argnames=("deficit_cap",))(
    tenant_deficit_update_impl
)
