"""Multi-tenant fairness plane (ROADMAP item 5).

Admission (tpu_faas/admission) protects the SYSTEM from overload; nothing
before this package protected tenants from EACH OTHER once admitted — one
user's 50k-task burst sat ahead of every other user's traffic in plain
FCFS order, so the light tenant's p99 tracked the heavy tenant's backlog.

The fix lives where placement decisions are made — inside the device tick
(Sparrow's lesson: fair sharing belongs at the scheduling decision, not
the admission edge):

- :mod:`tpu_faas.tenancy.config` — tenant vocabulary, share-vector /
  inflight-cap parsing (``--tenant-shares``/``--tenant-caps``), the
  hot-reload protocol over the ``fleet:tenant_conf`` store hash, and the
  host-side :class:`TenantTable` bookkeeping (row registry, per-tenant
  inflight counts, bounded metric-label vocabulary);
- :mod:`tpu_faas.tenancy.fairshare` — the in-tick kernels: start-time
  weighted-fair admission ranking (work-conserving — an idle tenant's
  share spills to backlogged ones), per-tenant inflight-cap eligibility
  masking, deficit-counter carry with a starvation age-boost riding the
  existing priority lane. Un-jitted ``_impl`` twins are traced by BOTH
  the XLA oracle and the fused Pallas resident kernel, so the two tick
  backends cannot drift (tenant state is one more aliased VMEM ref).
"""

from tpu_faas.tenancy.config import (  # noqa: F401
    DEFAULT_TENANT,
    TenantTable,
    parse_caps,
    parse_shares,
    valid_tenant,
)
