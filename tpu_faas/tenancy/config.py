"""Tenant vocabulary, share/cap config, and host-side bookkeeping.

A tenant is a short operator-facing name riding every task record
(``FIELD_TENANT``, stamped by the gateway from ``X-Tenant-Id``). The
device tick works on dense ROW INDICES instead: :class:`TenantTable` maps
names to rows (row 0 is always the default tenant, where every legacy /
header-less task lands), hands the tick its share / cap / inflight
vectors, and keeps the metrics-label vocabulary BOUNDED — only tenants
named in the operator's share config get their own label value; every
dynamically-discovered tenant aggregates under ``"other"`` so a client
minting random tenant names cannot explode series cardinality.

Config surface:

- ``--tenant-shares "a=3,b=1"`` — positive weights; tenants not listed
  (the default tenant included) weigh ``1.0``. Shares are RELATIVE: under
  contention, admitted work per backlogged tenant tracks the weights.
- ``--tenant-caps "a=100"`` — hard per-tenant inflight ceilings enforced
  where placement happens (a tenant at its cap keeps its surplus QUEUED
  on device; capacity spills to other tenants). Unlisted = uncapped.
- Hot reload: the same two spec strings live in the ``fleet:tenant_conf``
  store hash (store/base.py TENANT_CONF_KEY), stamped so the freshest
  publication wins on sharded stacks; dispatchers poll at ~1 Hz and
  apply in place — no restart, no tick-kernel recompile (the vectors are
  VALUES, only ``max_tenants`` is a static).
"""

from __future__ import annotations

import re
import time

import numpy as np

from tpu_faas.store.base import TENANT_CONF_KEY  # noqa: F401  (re-export)

#: Row 0 of every tenant table; where header-less / legacy traffic lands.
DEFAULT_TENANT = "default"

#: The metrics-label bucket for tenants outside the configured vocabulary.
OTHER_LABEL = "other"

#: Tenant names become store-hash content, share-table keys, and candidate
#: metric labels: short, printable, no spec/merge delimiters (":" is the
#: conf-stamp separator, "," and "=" the spec separators).
_TENANT_RE = re.compile(r"^[A-Za-z0-9][A-Za-z0-9._-]{0,63}$")


def valid_tenant(name: object) -> bool:
    return isinstance(name, str) and bool(_TENANT_RE.match(name))


def _parse_spec(spec: str, what: str, lo: float) -> dict[str, float]:
    out: dict[str, float] = {}
    for part in (spec or "").split(","):
        part = part.strip()
        if not part:
            continue
        name, sep, raw = part.partition("=")
        name = name.strip()
        if not sep or not valid_tenant(name):
            raise ValueError(f"malformed {what} entry {part!r}")
        try:
            value = float(raw)
        except ValueError:
            raise ValueError(f"{what} for {name!r} must be a number") from None
        if not (value > lo) or value != value or value == float("inf"):
            raise ValueError(f"{what} for {name!r} must be finite and > {lo:g}")
        if name in out:
            raise ValueError(f"duplicate tenant {name!r} in {what} spec")
        out[name] = value
    return out


def parse_shares(spec: str) -> dict[str, float]:
    """``"a=3,b=1"`` -> {"a": 3.0, "b": 1.0}. Raises ValueError with a
    operator-facing message on malformed input (fail at flag parse, not at
    the first device tick)."""
    return _parse_spec(spec, "share", 0.0)


def parse_caps(spec: str) -> dict[str, int]:
    """``"a=100"`` -> {"a": 100}; caps are whole inflight-slot counts.
    Fractional values are rejected rather than truncated: ``a=0.5`` would
    silently become 0 — which the table defines as UNCAPPED, the exact
    inverse of the operator's tightest-possible ask."""
    out = {}
    for name, value in _parse_spec(spec, "cap", 0.0).items():
        if value != int(value):
            raise ValueError(
                f"cap for {name!r} must be a whole slot count, got {value:g}"
            )
        out[name] = int(value)
    return out


def encode_conf(spec: str, now: float | None = None) -> str:
    """A conf-hash field value: ``<spec>:<wall stamp>`` (the stamp drives
    the sharded store's freshest-wins fleet-hash merge)."""
    stamp = time.time() if now is None else now
    return f"{spec}:{stamp!r}"


def decode_conf(value: str | None) -> tuple[str, float] | None:
    """(spec, stamp) off a conf-hash field, or None for absent/garbled."""
    if not value:
        return None
    spec, _sep, raw = value.rpartition(":")
    try:
        return spec, float(raw)
    except ValueError:
        return None


class TenantTable:
    """Host mirror of the tick's tenant dimension: name<->row registry,
    share/cap vectors, live inflight counts, and the bounded label map.

    ``max_tenants`` is a STATIC of the compiled tick (the vectors' padded
    length), defaulting far above any sane simultaneous-tenant count on
    one dispatcher. When more distinct names than rows appear, the
    overflow accounts to the default row — fairness degrades gracefully
    to "everyone unnamed shares one bucket" instead of failing dispatch.
    """

    def __init__(
        self,
        shares: dict[str, float] | None = None,
        caps: dict[str, int] | None = None,
        max_tenants: int = 32,
    ) -> None:
        if max_tenants < 1:
            raise ValueError("max_tenants must be >= 1")
        self.max_tenants = int(max_tenants)
        self._rows: dict[str, int] = {DEFAULT_TENANT: 0}
        self._names: list[str] = [DEFAULT_TENANT]
        self.share = np.ones(self.max_tenants, dtype=np.float32)
        self.cap = np.zeros(self.max_tenants, dtype=np.int32)  # 0 = uncapped
        self.inflight = np.zeros(self.max_tenants, dtype=np.int32)
        #: tasks handed to workers per row since start (host counter — the
        #: /stats tenancy block and the bench's share-ratio leg read it)
        self.dispatched = np.zeros(self.max_tenants, dtype=np.int64)
        self.overflowed = 0  # distinct names that didn't fit a row
        self._shares_spec: str | None = None
        self._caps_spec: str | None = None
        #: label vocabulary = configured names only (bounded by the
        #: operator); grows only via apply_shares/apply_caps
        self._labelled: set[str] = set()
        if shares:
            self._apply_shares(shares)
        if caps:
            self._apply_caps(caps)

    # -- rows ---------------------------------------------------------------
    def row_for(self, name: str | None, register: bool = True) -> int:
        """The dense row of a tenant name (None/invalid -> default row 0).
        Unknown names register a fresh row while capacity lasts; past
        ``max_tenants`` they account to the default row (counted)."""
        if not name or name == DEFAULT_TENANT:
            return 0
        row = self._rows.get(name)
        if row is not None:
            return row
        if not register or not valid_tenant(name):
            return 0
        if len(self._names) >= self.max_tenants:
            self.overflowed += 1
            return 0
        row = len(self._names)
        self._rows[name] = row
        self._names.append(name)
        return row

    def name_of(self, row: int) -> str:
        return self._names[row] if 0 <= row < len(self._names) else DEFAULT_TENANT

    def label_for(self, name: str | None) -> str:
        """Bounded metric-label value: the name itself when the operator's
        config vocabulary contains it, ``default`` for header-less
        traffic, ``other`` for everything dynamically discovered."""
        if not name or name == DEFAULT_TENANT:
            return DEFAULT_TENANT
        return name if name in self._labelled else OTHER_LABEL

    @property
    def n_tenants(self) -> int:
        return len(self._names)

    @property
    def labels(self) -> list[str]:
        """Full label vocabulary (pre-register metric children so the
        families render with stable series from the first scrape)."""
        return [DEFAULT_TENANT, OTHER_LABEL, *sorted(self._labelled)]

    # -- config -------------------------------------------------------------
    def _config_row(self, name: str) -> int | None:
        """The row a CONFIG entry applies to, or None when the table is
        full and the name couldn't be placed: writing an unplaceable
        tenant's share/cap onto the returned default row would silently
        retune every header-less client instead. (``default`` itself is
        legitimately configurable and returns row 0.)"""
        row = self.row_for(name)
        if row == 0 and name != DEFAULT_TENANT:
            return None
        return row

    def _apply_shares(self, shares: dict[str, float]) -> None:
        self.share[:] = 1.0
        for name, weight in shares.items():
            row = self._config_row(name)
            if row is None:
                continue  # overflowed (counted by row_for); config skipped
            self.share[row] = np.float32(weight)
            self._labelled.add(name)

    def _apply_caps(self, caps: dict[str, int]) -> None:
        self.cap[:] = 0
        for name, ceiling in caps.items():
            row = self._config_row(name)
            if row is None:
                continue
            self.cap[row] = np.int32(max(ceiling, 0))
            self._labelled.add(name)

    def apply_specs(
        self, shares_spec: str | None, caps_spec: str | None
    ) -> bool:
        """Apply spec STRINGS (CLI flags or the conf hash); no-op (False)
        when both match what is already applied. Raises ValueError on a
        malformed spec — hot-reload callers catch and keep the old table,
        CLI callers fail startup. BOTH specs parse before EITHER applies:
        a retune pairing valid shares with a typo'd caps spec must fail
        whole, not leave new shares silently live beside old caps."""
        new_shares = (
            parse_shares(shares_spec)
            if shares_spec is not None and shares_spec != self._shares_spec
            else None
        )
        new_caps = (
            parse_caps(caps_spec)
            if caps_spec is not None and caps_spec != self._caps_spec
            else None
        )
        changed = False
        if new_shares is not None:
            self._apply_shares(new_shares)
            self._shares_spec = shares_spec
            changed = True
        if new_caps is not None:
            self._apply_caps(new_caps)
            self._caps_spec = caps_spec
            changed = True
        return changed

    def publish(self, store, now: float | None = None) -> None:
        """Write this table's spec strings to the fleet conf hash (the
        hot-reload source of truth); one tiny hash write."""
        fields = {}
        if self._shares_spec is not None:
            fields["shares"] = encode_conf(self._shares_spec, now)
        if self._caps_spec is not None:
            fields["caps"] = encode_conf(self._caps_spec, now)
        if fields:
            store.hset(TENANT_CONF_KEY, fields)

    def maybe_reload(self, store) -> bool:
        """Pull the conf hash and apply any newer spec; True when the
        table changed. Malformed published specs are ignored (the fleet
        keeps serving on the last good config). Raises only on a store
        outage — callers share the serve loop's outage handling."""
        fields = store.hgetall(TENANT_CONF_KEY)
        shares = decode_conf(fields.get("shares"))
        caps = decode_conf(fields.get("caps"))
        try:
            return self.apply_specs(
                shares[0] if shares else None, caps[0] if caps else None
            )
        except ValueError:
            return False

    # -- inflight accounting (enforced in-tick via the `ahead` vector) -----
    def note_dispatched(self, row: int) -> None:
        if 0 <= row < self.max_tenants:
            self.inflight[row] += 1
            self.dispatched[row] += 1

    def note_done(self, row: int) -> None:
        if 0 <= row < self.max_tenants and self.inflight[row] > 0:
            self.inflight[row] -= 1

    # -- observability ------------------------------------------------------
    def stats(self, deficits: np.ndarray | None = None) -> dict:
        """The /stats tenancy block: per-tenant share / cap / inflight /
        dispatched (+ device deficit when the caller read one back)."""
        rows = {}
        for row, name in enumerate(self._names):
            rows[name] = {
                "share": float(self.share[row]),
                "cap": int(self.cap[row]) or None,
                "inflight": int(self.inflight[row]),
                "dispatched": int(self.dispatched[row]),
            }
            if deficits is not None and row < len(deficits):
                rows[name]["deficit"] = round(float(deficits[row]), 3)
        return {
            "tenants": rows,
            "max_tenants": self.max_tenants,
            "overflowed": self.overflowed,
        }
