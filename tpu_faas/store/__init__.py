"""Task store + announce bus.

The reference uses Redis db=1 as both the durable per-task hash store and the
announce bus (pub/sub channel "tasks") — reference task_dispatcher.py:30-36 and
the gateway contract in SURVEY §0.1. This package provides the same capability
behind a thin interface with three interchangeable backends:

- :class:`tpu_faas.store.memory.MemoryStore` — in-process, for tests, the
  local dispatcher, and the simulated fleets;
- :class:`tpu_faas.store.client.RespStore` — a client speaking a RESP2 subset
  over TCP, usable against either of the two servers below (or a real Redis);
- servers: ``tpu_faas.store.server`` (Python asyncio, fallback) and the native
  C++ server under ``native/`` (the performance path).
"""

from tpu_faas.store.base import TaskStore, Subscription
from tpu_faas.store.memory import MemoryStore

__all__ = ["TaskStore", "Subscription", "MemoryStore"]
