"""RESP2 wire protocol: encoder + incremental decoder.

The store servers (Python asyncio fallback and the native C++ server) and the
:class:`tpu_faas.store.client.RespStore` client speak the Redis Serialization
Protocol v2 — the same wire format the reference's redis-py dependency uses —
so the framework's store is drop-in swappable with a real Redis and vice
versa. Only the types the store needs are implemented: simple strings,
errors, integers, bulk strings (incl. nil), and arrays.

This module is pure (no IO): `encode_command` builds client->server request
arrays; `RespParser` is a push parser fed raw bytes and yielding decoded
replies, usable from both asyncio and blocking-socket code.
"""

from __future__ import annotations

CRLF = b"\r\n"


class RespError(Exception):
    """Server-reported error reply (`-ERR ...`)."""


class ProtocolError(Exception):
    """Malformed RESP bytes on the wire; the connection should be dropped."""


def encode_command(*parts: str | bytes | int) -> bytes:
    """Encode a command as a RESP array of bulk strings."""
    out = [b"*%d\r\n" % len(parts)]
    for p in parts:
        if isinstance(p, int):
            p = str(p).encode()
        elif isinstance(p, str):
            p = p.encode("utf-8")
        out.append(b"$%d\r\n" % len(p))
        out.append(p)
        out.append(CRLF)
    return b"".join(out)


def encode_simple(s: str) -> bytes:
    return b"+" + s.encode() + CRLF


def encode_error(msg: str) -> bytes:
    return b"-ERR " + msg.encode() + CRLF


def encode_integer(n: int) -> bytes:
    return b":%d\r\n" % n


def encode_bulk(s: str | bytes | None) -> bytes:
    if s is None:
        return b"$-1\r\n"
    if isinstance(s, str):
        s = s.encode("utf-8")
    return b"$%d\r\n" % len(s) + s + CRLF


def encode_array(items: list[bytes]) -> bytes:
    """Encode an array whose elements are already RESP-encoded."""
    return b"*%d\r\n" % len(items) + b"".join(items)


class RespParser:
    """Incremental RESP parser: feed() bytes, pop complete replies.

    Decoded values: simple string -> str, integer -> int, bulk -> str | None,
    array -> list (recursively decoded), error -> RespError instance (returned,
    not raised, so callers decide).

    ``pop(raw=True)`` returns bulk strings as ``bytes`` instead of decoding
    them to ``str`` — the binary-batch fast path (MHGETALL/MFINISH, see
    store/client.py) reads whole record payloads without a per-field utf-8
    round trip; simple strings, errors, and integers decode identically in
    both modes, so control replies are mode-agnostic.
    """

    def __init__(self) -> None:
        self._buf = bytearray()

    def feed(self, data: bytes) -> None:
        self._buf.extend(data)

    def pop(self, raw: bool = False):
        """Return the next complete decoded reply, or the NEED_MORE sentinel
        when the buffer holds only a partial reply.

        Raises :class:`ProtocolError` on malformed bytes; the buffer is
        cleared first so a poisoned connection fails once, not forever."""
        try:
            result, consumed = _parse(self._buf, 0, raw=raw)
        except (ValueError, ProtocolError) as exc:
            self._buf.clear()
            raise ProtocolError(f"malformed RESP input: {exc}") from exc
        if result is NEED_MORE:
            return NEED_MORE
        del self._buf[:consumed]
        return result

    def pending(self) -> int:
        """Bytes buffered but not yet parsed into a complete reply."""
        return len(self._buf)

    def pop_all(self, raw: bool = False) -> list:
        out = []
        while True:
            item = self.pop(raw=raw)
            if item is NEED_MORE:
                return out
            out.append(item)


class _NeedMore:
    __slots__ = ()

    def __repr__(self) -> str:
        return "<NEED_MORE>"


NEED_MORE = _NeedMore()


def _find_crlf(buf: bytearray, start: int) -> int:
    return buf.find(CRLF, start)


def _parse(buf: bytearray, pos: int, raw: bool = False):
    """Parse one value at pos. Return (value | NEED_MORE, end_pos).

    ``raw=True`` leaves bulk strings as bytes (no utf-8 decode) — the
    binary-batch reply path; every other reply type is unaffected."""
    if pos >= len(buf):
        return NEED_MORE, pos
    kind = buf[pos : pos + 1]
    line_end = _find_crlf(buf, pos + 1)
    if line_end < 0:
        return NEED_MORE, pos
    line = bytes(buf[pos + 1 : line_end])
    body_start = line_end + 2
    if kind == b"+":
        return line.decode("utf-8"), body_start
    if kind == b"-":
        return RespError(line.decode("utf-8")), body_start
    if kind == b":":
        return int(line), body_start
    if kind == b"$":
        n = int(line)
        if n == -1:
            return None, body_start
        end = body_start + n + 2
        if len(buf) < end:
            return NEED_MORE, pos
        body = bytes(buf[body_start : body_start + n])
        return (body if raw else body.decode("utf-8")), end
    if kind == b"*":
        n = int(line)
        if n == -1:
            return None, body_start
        items = []
        cur = body_start
        for _ in range(n):
            item, cur = _parse(buf, cur, raw=raw)
            if item is NEED_MORE:
                return NEED_MORE, pos
            items.append(item)
        return items, cur
    raise ProtocolError(f"bad RESP type byte {kind!r}")
