"""Store factory + in-thread server launcher.

URL scheme used across CLIs and configs:

- ``memory://``            in-process MemoryStore (single-process modes/tests)
- ``resp://host:port``     TCP client to any RESP store server (ours or Redis)
- ``resp://h1:p1,h2:p2``   ordered FAILOVER endpoint list (primary first,
  replicas after): the client settles on whichever endpoint holds the
  writable primary role and follows promotions (store/replication.py)
- ``resp://h1:p1;h2:p2``   SHARDED control plane (store/sharding.py): a
  ``;``-separated shard list builds a ShardedStore routing the task
  keyspace over the shards by consistent hashing. Each shard may itself
  be a ``,``-separated failover ring (``resp://p1:1,r1:2;p2:3,r2:4`` =
  two shards, each a primary+replica pair), so per-shard HA composes.
  ``memory://fresh;fresh`` shards over private in-process stores (tests).

`start_store_thread` runs the Python asyncio server inside a daemon thread and
returns a handle — used by tests and by single-machine deployments that don't
want a separate store process. Production path is the native C++ server
(tpu_faas.store.native) or any Redis-compatible endpoint.
"""

from __future__ import annotations

import asyncio
import threading
from dataclasses import dataclass
from urllib.parse import urlparse

from tpu_faas.store.base import TaskStore
from tpu_faas.store.client import RespStore
from tpu_faas.store.memory import MemoryStore
from tpu_faas.store.server import StoreServer

_SHARED_MEMORY_STORE: MemoryStore | None = None
_SHARED_LOCK = threading.Lock()


def make_store(
    url: str, owned_shards: list[int] | None = None, binbatch: bool = False
) -> TaskStore:
    """Create a TaskStore from a URL.

    ``memory://`` returns a process-wide shared MemoryStore (so a gateway and
    dispatcher running in one process see the same tasks); ``memory://fresh``
    returns a private instance.

    A ``;`` in the URL selects the sharded form (see module docstring):
    ``owned_shards`` then scopes the handle's consumption surface —
    announce subscriptions, rescans, announce replay — to those shard
    indices (a dispatcher owning a slice of the fleet); ``None`` consumes
    every shard (gateways, clients).

    ``binbatch`` (the dispatcher's ``--store-binbatch`` knob) asks RESP
    clients to negotiate the binary-batch command surface per connection
    (store/client.py); off sends zero extra bytes, and non-RESP backends
    ignore it entirely.
    """
    if ";" in url:
        from tpu_faas.store.sharding import ShardedStore

        scheme, sep, rest = url.partition("://")
        if not sep:
            raise ValueError(f"unknown store url scheme: {url!r}")
        groups = [g for g in rest.split(";") if g]
        if len(groups) < 2:
            raise ValueError(
                f"sharded store url needs >= 2 ';'-separated shards: {url!r}"
            )
        if scheme == "memory":
            # sharding over ONE shared dict would be no sharding at all:
            # every memory shard is a private instance
            stores: list[TaskStore] = [
                MemoryStore() for _ in groups
            ]
        else:
            stores = [
                make_store(f"{scheme}://{group}", binbatch=binbatch)
                for group in groups
            ]
        return ShardedStore(stores, owned_shards=owned_shards)
    if owned_shards is not None:
        raise ValueError(
            "owned_shards needs a sharded (';'-separated) store url"
        )
    parsed = urlparse(url)
    if parsed.scheme == "memory":
        if parsed.netloc == "fresh" or parsed.path == "/fresh":
            return MemoryStore()
        global _SHARED_MEMORY_STORE
        with _SHARED_LOCK:
            if _SHARED_MEMORY_STORE is None:
                _SHARED_MEMORY_STORE = MemoryStore()
            return _SHARED_MEMORY_STORE
    if parsed.scheme in ("resp", "redis", "tcp"):
        if "," in parsed.netloc:
            # ordered failover list: "h1:p1,h2:p2[,...]" — urlparse can't
            # digest the comma form, so split it by hand
            from tpu_faas.store.replication import parse_endpoint

            endpoints = [
                parse_endpoint(spec)
                for spec in parsed.netloc.split(",")
                if spec
            ]
            return RespStore(endpoints=endpoints, binbatch=binbatch)
        host = parsed.hostname or "127.0.0.1"
        port = parsed.port or 6380
        return RespStore(host, port, binbatch=binbatch)
    raise ValueError(f"unknown store url scheme: {url!r}")


@dataclass
class StoreServerHandle:
    server: StoreServer
    thread: threading.Thread
    loop: asyncio.AbstractEventLoop

    @property
    def port(self) -> int:
        return self.server.port

    @property
    def url(self) -> str:
        return f"resp://{self.server.host}:{self.server.port}"

    def stop(self) -> None:
        if self.loop.is_closed():  # idempotent: already stopped
            return

        async def _stop() -> None:
            await self.server.stop()

        try:
            fut = asyncio.run_coroutine_threadsafe(_stop(), self.loop)
            fut.result(timeout=5)
        except Exception:
            pass
        self.thread.join(timeout=5)


def start_store_thread(
    host: str = "127.0.0.1",
    port: int = 0,
    snapshot_path: str | None = None,
    autosave_interval: float = 0.0,
    replica_of: tuple[str, int] | str | None = None,
    epoch: int = 0,
    health_port: int | None = None,
) -> StoreServerHandle:
    """Start the Python store server in a daemon thread; returns once bound.
    ``replica_of`` starts it as a read-only replica tailing that primary
    (promote with ``RespStore.promote()``); ``epoch`` seeds the fencing
    epoch for restarts of previously-promoted stores; ``health_port``
    serves the HTTP /healthz //readyz probe pair (0 picks a free port,
    resolved on ``handle.server.health_port``)."""
    server = StoreServer(
        host,
        port,
        snapshot_path=snapshot_path,
        autosave_interval=autosave_interval,
        replica_of=replica_of,
        epoch=epoch,
        health_port=health_port,
    )
    started = threading.Event()
    loop_holder: dict[str, asyncio.AbstractEventLoop] = {}

    def run() -> None:
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(loop)
        loop_holder["loop"] = loop

        async def main() -> None:
            await server.start()
            started.set()
            await server.serve_forever()

        try:
            loop.run_until_complete(main())
        finally:
            loop.close()

    thread = threading.Thread(target=run, name="tpu-faas-store", daemon=True)
    thread.start()
    if not started.wait(timeout=10):
        raise RuntimeError("store server failed to start")
    return StoreServerHandle(server=server, thread=thread, loop=loop_holder["loop"])


def find_redis_server() -> str | None:
    """Locate a real redis-server binary for the drop-in-Redis interop
    leg: $PATH first, then the checksum-pinned local build produced by
    ``native/build_redis.sh`` (environments without egress drop the
    pinned tarball and build once). One helper shared by
    tests/test_redis_compat.py and bench.py's ``redis_interop`` artifact
    field, so the two can never disagree about whether the real leg
    runs."""
    import os
    import shutil

    from tpu_faas.store.native import NATIVE_DIR

    found = shutil.which("redis-server")
    if found:
        return found
    local = os.path.join(NATIVE_DIR, "redis-server")
    return local if os.access(local, os.X_OK) else None
