"""Streaming replication for the RESP store servers: primary -> replica
command streaming, replica promotion, and epoch fencing.

The store is the last single point of failure on the control path: the
circuit breaker (tpu_faas/admission) makes a store outage *fast*, but
every admitted task is stranded until the primary comes back. This module
makes a store outage *survivable* — a replica tails the primary's write
stream and can be promoted to accept writes, clients fail over to it, and
the dispatcher re-arms via adopt-by-rescan plus an announce-replay round.

Design, riding machinery that already exists:

- **Full sync IS the snapshot format.** On connect a replica sends
  ``REPLSYNC`` and receives ``[epoch, offset, snapshot]`` where the
  snapshot is the replayable RESP command log of tpu_faas/store/snapshot.py
  (now DEL/HDEL-capable) — no second serialization scheme.
- **The stream IS the wire protocol.** After the sync the primary forwards
  every mutating command (HSET/HSETNX/HDEL/DEL/PUBLISH/FLUSHDB) verbatim,
  in execution order, down the same connection; the replica parses them
  with the ordinary RespParser and applies them. Each mutating command
  advances a monotonic **replication offset** shared by both ends; the
  replica acknowledges progress with reply-less ``REPLACK <offset>``
  messages, which is what the primary's lag introspection reports.
- **PUBLISH replication + announce ring.** Replicated PUBLISHes fan out
  to the replica's local subscribers AND land in a bounded in-memory
  announce ring on both ends. After a failover, dispatchers call
  ``REPLAY <offset>`` on the promoted replica to re-discover announces
  that were published on the dead primary but never drained — the
  re-arm half of zero-loss failover (the rescan covers the rest).
- **Promotion is explicit.** A replica refuses mutating commands
  (``-ERR READONLY``) until an operator (or a failover controller) sends
  ``PROMOTE``; promotion stops the replication link, takes the primary
  role, and bumps the **epoch**.
- **Epoch fencing.** Clients declare the highest epoch they have seen
  with ``FENCE <epoch>`` when (and only when) they connect with a
  multi-endpoint configuration. A primary that receives a declaration
  GREATER than its own epoch learns it has been superseded — a
  resurrected old primary — and permanently fences itself: every
  mutating command is refused (``-ERR FENCED``) for every client,
  including epoch-oblivious legacy ones, so stale traffic cannot land
  on a store the fleet has already failed away from.

Single-store deployments never touch any of this: replication is opt-in
(``--replica-of``), single-endpoint clients send no FENCE/ROLE handshake,
and the reference/redis-compat wire surface is unchanged.
"""

from __future__ import annotations

import asyncio
from collections import deque
from dataclasses import dataclass, field

from tpu_faas.store import resp, snapshot
from tpu_faas.utils.backoff import Backoff, BackoffPolicy

#: Commands that mutate store state — the set a replica refuses from
#: ordinary clients, a fenced primary refuses from everyone, and a live
#: primary forwards down its replication streams.
MUTATING_COMMANDS = frozenset(
    {"HSET", "HSETNX", "HINCRBY", "HDEL", "DEL", "PUBLISH", "FLUSHDB"}
)

#: Error prefixes clients can match on (encode_error prepends "-ERR ").
READONLY_ERR = "READONLY replica; send PROMOTE before writing"
FENCED_ERR = "FENCED stale primary (superseded by a higher epoch)"

#: Default bound on the announce ring: enough for any realistic
#: failover window (announces are ~40-byte task ids), small enough that
#: a worst-case REPLAY reply stays far under a megabyte.
ANNOUNCE_RING_SIZE = 10_000

#: How often the replica link acks its applied offset back to the
#: primary (seconds); also the reconnect backoff floor after a lost link.
ACK_PERIOD = 0.5

#: Reconnect schedule after a lost link: starts at the ack cadence and
#: grows to a short cap — a replica hammering a dead primary every
#: 0.5 s forever is wasted log noise, but the cap stays small so
#: promotion-window resyncs (tests wait ~5 s) are never starved. The
#: counter resets after any successful full sync, so a fresh outage on
#: a previously-healthy link retries fast.
RECONNECT_BACKOFF = BackoffPolicy(
    floor_s=ACK_PERIOD, factor=2.0, cap_s=2.0, jitter_lo=0.9, jitter_hi=1.2
)


class AnnounceRing:
    """Bounded ring of ``(offset, channel, payload)`` PUBLISH records.

    The replay backstop for the fire-and-forget announce bus: after a
    failover, announces published on the dead primary but never drained
    by a dispatcher are re-discoverable from the promoted replica's copy
    of the ring (PUBLISH is replicated like any other mutating command).
    """

    def __init__(self, maxlen: int = ANNOUNCE_RING_SIZE) -> None:
        self._ring: deque[tuple[int, str, str]] = deque(maxlen=maxlen)
        self.tail = 0  # offset of the newest entry (0 = nothing yet)

    def append(self, offset: int, channel: str, payload: str) -> None:
        self._ring.append((offset, channel, payload))
        self.tail = offset

    def since(self, after: int) -> list[tuple[int, str, str]]:
        """Entries with offset strictly greater than ``after``, oldest
        first. ``after`` below the ring's head silently returns the whole
        ring — the truncation is the documented bound, and duplicate
        announces are deduped at dispatcher intake anyway."""
        return [e for e in self._ring if e[0] > after]

    def clear(self) -> None:
        self._ring.clear()

    def __len__(self) -> int:
        return len(self._ring)


@dataclass
class ReplicationState:
    """One server's replication-facing state (primary and replica alike)."""

    #: "primary" | "replica"; promotion flips replica -> primary
    role: str = "primary"
    #: monotonic failover generation; a promotion bumps it by one. The
    #: fencing comparator: a server seeing a FENCE declaration above its
    #: own epoch knows it has been superseded.
    epoch: int = 0
    #: count of mutating commands applied (primary: executed; replica:
    #: replayed) — the replication offset both ends track in lockstep
    offset: int = 0
    #: True once a FENCE declaration proved this server superseded;
    #: permanent for the process lifetime (restart to clear — by then the
    #: operator has re-pointed it or wiped it)
    fenced: bool = False
    #: live replica stream targets: writer -> last REPLACK'd offset
    replicas: dict[asyncio.StreamWriter, int] = field(default_factory=dict)
    ring: AnnounceRing = field(default_factory=AnnounceRing)

    def min_acked(self) -> int:
        """The slowest attached replica's acknowledged offset (our own
        offset when no replica is attached — lag 0 by definition)."""
        if not self.replicas:
            return self.offset
        return min(self.replicas.values())

    def lag(self) -> int:
        return max(0, self.offset - self.min_acked())


class ReplicaLink:
    """The replica side of the stream: an asyncio task that connects to
    the primary, full-syncs, then applies the live command stream.

    Reconnects with a short backoff on any link loss (each reconnect is a
    fresh full sync — offsets make partial resync *observable*, not
    implemented; snapshots are cheap at this store's scale). Stops for
    good on promotion or server shutdown.
    """

    def __init__(self, server, host: str, port: int) -> None:
        self.server = server
        self.host = host
        self.port = port
        self._task: asyncio.Task | None = None
        self._stopped = False
        #: True after the first successful full sync (INFO introspection)
        self.synced = False

    def start(self) -> None:
        self._task = asyncio.create_task(self.run())

    def stop(self) -> None:
        self._stopped = True
        if self._task is not None:
            self._task.cancel()

    async def run(self) -> None:
        bo = Backoff(RECONNECT_BACKOFF)
        while not self._stopped:
            try:
                await self._sync_and_tail()
            except asyncio.CancelledError:
                return
            except (
                OSError,
                ConnectionError,
                resp.ProtocolError,
                resp.RespError,  # an -ERR REPLSYNC reply (plain Redis /
                # pre-HA server as the target) must retry-and-log, not
                # silently kill the link task forever
            ) as exc:
                if self.synced:
                    # the link WAS up: this is a fresh outage, not one
                    # more failure in a streak — retry fast again
                    bo.reset()
                self.synced = False
                self.server.note_link_down(exc)
            if self._stopped:
                return
            await asyncio.sleep(bo.next())

    async def _sync_and_tail(self) -> None:
        reader, writer = await asyncio.open_connection(self.host, self.port)
        try:
            writer.write(resp.encode_command("REPLSYNC"))
            await writer.drain()
            parser = resp.RespParser()
            header = await self._read_reply(reader, parser)
            if (
                not isinstance(header, list)
                or len(header) != 3
                or not isinstance(header[0], int)
                or not isinstance(header[1], int)
                or not isinstance(header[2], str)
            ):
                raise resp.ProtocolError(f"bad REPLSYNC reply: {header!r}")
            epoch, offset, snap = header
            self.server.load_replicated_snapshot(
                snapshot.load_hashes(snap.encode("utf-8")), epoch, offset
            )
            self.synced = True
            writer.write(resp.encode_command("REPLACK", offset))
            await writer.drain()
            # -- tail the live stream -----------------------------------
            last_ack = asyncio.get_running_loop().time()
            while not self._stopped:
                item = parser.pop()
                while item is not resp.NEED_MORE:
                    if isinstance(item, list) and item:
                        self.server.apply_replicated(item)
                    item = parser.pop()
                now = asyncio.get_running_loop().time()
                if now - last_ack >= ACK_PERIOD:
                    writer.write(
                        resp.encode_command(
                            "REPLACK", self.server.repl.offset
                        )
                    )
                    await writer.drain()
                    last_ack = now
                try:
                    data = await asyncio.wait_for(
                        reader.read(65536), timeout=ACK_PERIOD
                    )
                except asyncio.TimeoutError:
                    continue  # idle primary: ack timer still ticks above
                if not data:
                    raise ConnectionError("replication stream closed")
                parser.feed(data)
        finally:
            writer.close()

    @staticmethod
    async def _read_reply(reader: asyncio.StreamReader, parser):
        while True:
            item = parser.pop()
            if item is not resp.NEED_MORE:
                if isinstance(item, resp.RespError):
                    raise item
                return item
            data = await reader.read(65536)
            if not data:
                raise ConnectionError("connection closed during REPLSYNC")
            parser.feed(data)


def parse_endpoint(spec: str, default_port: int = 6380) -> tuple[str, int]:
    """``host[:port]`` -> (host, port); shared by --replica-of and the
    multi-endpoint store URL parser."""
    host, sep, port_s = spec.rpartition(":")
    if not sep:
        return spec, default_port
    return host, int(port_s)
