"""Launcher for the native C++ store server (native/store_server.cpp).

Builds on demand via the Makefile (g++ is the only requirement) and runs the
binary as a subprocess. Interface mirrors
:func:`tpu_faas.store.launch.start_store_thread`, so call sites can swap the
Python and native backends freely; both speak the identical RESP subset.
"""

from __future__ import annotations

import os
import socket
import subprocess
import time
from dataclasses import dataclass

REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)
NATIVE_DIR = os.path.join(REPO_ROOT, "native")
BINARY = os.path.join(NATIVE_DIR, "build", "tpu-faas-store")


class NativeStoreUnavailable(RuntimeError):
    pass


def build_native_store(force: bool = False) -> str:
    """Compile the server if needed; returns the binary path."""
    src = os.path.join(NATIVE_DIR, "store_server.cpp")
    if (
        not force
        and os.path.exists(BINARY)
        and os.path.getmtime(BINARY) >= os.path.getmtime(src)
    ):
        return BINARY
    try:
        subprocess.run(
            ["make", "-C", NATIVE_DIR],
            check=True,
            capture_output=True,
            text=True,
        )
    except (subprocess.CalledProcessError, FileNotFoundError) as exc:
        detail = getattr(exc, "stderr", "") or str(exc)
        raise NativeStoreUnavailable(
            f"could not build native store: {detail}"
        ) from exc
    return BINARY


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


@dataclass
class NativeStoreHandle:
    process: subprocess.Popen
    host: str
    port: int

    @property
    def url(self) -> str:
        return f"resp://{self.host}:{self.port}"

    def stop(self) -> None:
        if self.process.poll() is None:
            self.process.terminate()
            try:
                self.process.wait(timeout=5)
            except subprocess.TimeoutExpired:
                self.process.kill()
                self.process.wait()


def start_native_store(
    host: str = "127.0.0.1",
    port: int = 0,
    timeout: float = 10.0,
    snapshot_path: str | None = None,
    autosave_interval: float = 0.0,
    replica_of: str | None = None,
) -> NativeStoreHandle:
    """Build (if needed) and launch the native store; blocks until it accepts
    connections.

    ``replica_of`` is the HA launch hook matching the Python server's
    ``--replica-of`` (store/replication.py). The C++ server does not
    implement the replication stream yet, so requesting it here fails
    fast with a pointer at the Python server instead of launching a
    store that silently is not a replica."""
    if replica_of is not None:
        raise NativeStoreUnavailable(
            "the native store does not implement the replication stream "
            "(REPLSYNC) yet; run BOTH ends of an HA pair as "
            "`python -m tpu_faas.store.server` (the replica as "
            f"`--replica-of {replica_of}`)"
        )
    binary = build_native_store()
    if port == 0:
        port = _free_port()
    argv = [binary, "--host", host, "--port", str(port)]
    if snapshot_path is not None:
        argv += ["--snapshot", snapshot_path]
    if autosave_interval > 0:
        argv += ["--autosave", str(autosave_interval)]
    proc = subprocess.Popen(
        argv,
        stdout=subprocess.PIPE,
        stderr=subprocess.STDOUT,
        text=True,
    )
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if proc.poll() is not None:
            out = proc.stdout.read() if proc.stdout else ""
            raise NativeStoreUnavailable(
                f"native store exited at startup: {out}"
            )
        try:
            with socket.create_connection((host, port), timeout=0.2):
                return NativeStoreHandle(proc, host, port)
        except OSError:
            time.sleep(0.02)
    proc.kill()
    raise NativeStoreUnavailable("native store did not start in time")
