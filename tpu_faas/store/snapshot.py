"""Store snapshot format: checkpoint/resume for the task store.

The reference has no durability story at all — a restarted Redis (or a
restarted store) loses every task hash, and SURVEY §5.4 records
checkpoint/resume as absent. Here the store can checkpoint its entire
hash table to a file and reload it at startup, so task statuses and
results survive a store restart.

Format: the snapshot file is a plain sequence of RESP-encoded commands —
``HSET key field value [field value ...]`` for live state plus ``DEL
key [key ...]`` / ``HDEL key field [field ...]`` deletion records — i.e.
a replayable command log, like a one-shot Redis AOF, applied strictly in
order. Because it *is* the wire protocol, the identical format is written
and read by the Python asyncio server (tpu_faas/store/server.py), the
native C++ server (native/store_server.cpp), the in-proc MemoryStore,
AND the replication full-sync payload (tpu_faas/store/replication.py),
with no second serialization scheme to keep in sync. Writes are atomic
(tmp-file + rename), so a crash mid-save leaves the previous snapshot
intact.

Why deletion records: a pure HSET dump cannot *express* a deletion, so
any consumer that merges or replays logs (concatenated snapshots, a
snapshot followed by a replicated command stream) would resurrect
GC'd blobs and deleted live-index entries. The servers track keys
deleted since their last checkpoint and write them as ``DEL`` records,
making every snapshot explicit about what is known-gone, and making the
replication stream's DEL/HDEL traffic representable in the one shared
format.
"""

from __future__ import annotations

import os
from typing import Iterable, Mapping

from tpu_faas.store import resp


def dump_hashes(
    hashes: Mapping[str, Mapping[str, str]],
    deleted: Iterable[str] = (),
) -> bytes:
    """Serialize a dict-of-hashes as replayable RESP HSET commands,
    followed by one ``DEL`` record for the ``deleted`` keys (keys removed
    since the last checkpoint — see module docstring)."""
    out: list[bytes] = []
    for key, fields in hashes.items():
        if not fields:
            continue  # HSET needs >=1 pair; empty hashes are unreachable anyway
        flat: list[str] = []
        for f, v in fields.items():
            flat.extend((f, v))
        out.append(resp.encode_command("HSET", key, *flat))
    # deletions AFTER the state dump: replay order must leave a key that
    # is both dumped and tombstoned (a caller bug) absent, never revived
    gone = [k for k in deleted if k not in hashes or not hashes[k]]
    if gone:
        out.append(resp.encode_command("DEL", *gone))
    return b"".join(out)


def load_hashes(data: bytes) -> dict[str, dict[str, str]]:
    """Replay a snapshot byte string into a dict-of-hashes, applying
    HSET / DEL / HDEL records strictly in order (so a log that is a state
    dump plus appended mutations — e.g. a replicated command stream —
    replays to the correct end state, deletions included).

    Raises :class:`resp.ProtocolError` on malformed bytes or any other
    command — a corrupt snapshot should fail loudly at startup, not load
    half a database silently.
    """
    parser = resp.RespParser()
    parser.feed(data)
    hashes: dict[str, dict[str, str]] = {}
    while True:
        item = parser.pop()
        if item is resp.NEED_MORE:
            if parser.pending():
                raise resp.ProtocolError(
                    f"snapshot ends with {parser.pending()} trailing bytes "
                    "(truncated entry)"
                )
            break
        if not isinstance(item, list) or not item:
            raise resp.ProtocolError(f"snapshot contains non-command entry: {item!r}")
        name = item[0].upper() if isinstance(item[0], str) else None
        if name == "HSET" and len(item) >= 4 and len(item) % 2 == 0:
            h = hashes.setdefault(item[1], {})
            for f, v in zip(item[2::2], item[3::2]):
                h[f] = v
        elif name == "DEL" and len(item) >= 2:
            for key in item[1:]:
                hashes.pop(key, None)
        elif name == "HDEL" and len(item) >= 3:
            h = hashes.get(item[1])
            if h is not None:
                for f in item[2:]:
                    h.pop(f, None)
                if not h:  # Redis semantics: empty hash = absent key
                    hashes.pop(item[1], None)
        else:
            raise resp.ProtocolError(
                f"snapshot contains unsupported entry: {item!r}"
            )
    return hashes


def save_file(
    path: str,
    hashes: Mapping[str, Mapping[str, str]],
    deleted: Iterable[str] = (),
) -> None:
    """Atomically write a snapshot: write tmp in the same dir, fsync, rename."""
    tmp = f"{path}.tmp.{os.getpid()}"
    data = dump_hashes(hashes, deleted)
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def load_file(path: str) -> dict[str, dict[str, str]]:
    """Load a snapshot file; a missing file is an empty store (first boot)."""
    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except FileNotFoundError:
        return {}
    return load_hashes(data)
