"""Store snapshot format: checkpoint/resume for the task store.

The reference has no durability story at all — a restarted Redis (or a
restarted store) loses every task hash, and SURVEY §5.4 records
checkpoint/resume as absent. Here the store can checkpoint its entire
hash table to a file and reload it at startup, so task statuses and
results survive a store restart.

Format: the snapshot file is a plain sequence of RESP-encoded
``HSET key field value [field value ...]`` commands — i.e. a replayable
command log, like a one-shot Redis AOF. Because it *is* the wire
protocol, the identical file is written and read by the Python asyncio
server (tpu_faas/store/server.py), the native C++ server
(native/store_server.cpp), and the in-proc MemoryStore, with no second
serialization scheme to keep in sync. Writes are atomic
(tmp-file + rename), so a crash mid-save leaves the previous snapshot
intact.
"""

from __future__ import annotations

import os
from typing import Mapping

from tpu_faas.store import resp


def dump_hashes(hashes: Mapping[str, Mapping[str, str]]) -> bytes:
    """Serialize a dict-of-hashes as replayable RESP HSET commands."""
    out: list[bytes] = []
    for key, fields in hashes.items():
        if not fields:
            continue  # HSET needs >=1 pair; empty hashes are unreachable anyway
        flat: list[str] = []
        for f, v in fields.items():
            flat.extend((f, v))
        out.append(resp.encode_command("HSET", key, *flat))
    return b"".join(out)


def load_hashes(data: bytes) -> dict[str, dict[str, str]]:
    """Replay a snapshot byte string into a dict-of-hashes.

    Raises :class:`resp.ProtocolError` on malformed bytes or non-HSET
    commands — a corrupt snapshot should fail loudly at startup, not load
    half a database silently.
    """
    parser = resp.RespParser()
    parser.feed(data)
    hashes: dict[str, dict[str, str]] = {}
    while True:
        item = parser.pop()
        if item is resp.NEED_MORE:
            if parser.pending():
                raise resp.ProtocolError(
                    f"snapshot ends with {parser.pending()} trailing bytes "
                    "(truncated entry)"
                )
            break
        if (
            not isinstance(item, list)
            or len(item) < 4
            or len(item) % 2 != 0
            or item[0].upper() != "HSET"
        ):
            raise resp.ProtocolError(f"snapshot contains non-HSET entry: {item!r}")
        h = hashes.setdefault(item[1], {})
        for f, v in zip(item[2::2], item[3::2]):
            h[f] = v
    return hashes


def save_file(path: str, hashes: Mapping[str, Mapping[str, str]]) -> None:
    """Atomically write a snapshot: write tmp in the same dir, fsync, rename."""
    tmp = f"{path}.tmp.{os.getpid()}"
    data = dump_hashes(hashes)
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def load_file(path: str) -> dict[str, dict[str, str]]:
    """Load a snapshot file; a missing file is an empty store (first boot)."""
    try:
        with open(path, "rb") as fh:
            data = fh.read()
    except FileNotFoundError:
        return {}
    return load_hashes(data)
