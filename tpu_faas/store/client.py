"""Blocking TCP client for the RESP store servers (TaskStore implementation).

Works against the Python asyncio server (store/server.py), the native C++
server (native/store_server.cpp), or a real Redis. Mirrors the structure the
reference gets from redis-py: one connection for commands, one dedicated
connection per pub/sub subscription with a non-blocking ``get_message()``
(reference task_dispatcher.py:34-36, 75).

Thread-safety: command calls are serialized by a lock, so one RespStore can be
shared across gateway/dispatcher threads; each Subscription owns its socket.

High availability: construct with an ordered ``endpoints`` list (or a
``resp://h1:p1,h2:p2`` URL through ``make_store``) and the client fails
over — every (re)connect walks the list from the active endpoint, runs a
FENCE/ROLE handshake against each candidate, and settles on the first
endpoint that reports the writable ``primary`` role. Unpromoted replicas
and fenced stale primaries are skipped; the highest epoch ever seen is
re-declared on every handshake, which is what fences a resurrected old
primary (store/replication.py). Single-endpoint clients send NO handshake
— the wire surface toward a plain Redis is byte-identical to before.
"""

from __future__ import annotations

import select
import socket
import threading
import time
from typing import Mapping

from tpu_faas.obs import REGISTRY
from tpu_faas.store import resp
from tpu_faas.store.base import (
    BLOB_AT_FIELD,
    BLOB_DATA_FIELD,
    LIVE_INDEX_KEY,
    RESULTS_CHANNEL,
    TASKS_CHANNEL,
    Subscription,
    TaskStore,
    blob_key,
    encode_result_announce,
)

#: Process-wide round-trip counter, one series per store role: the scrape
#: analog of each handle's ``n_round_trips`` (one pipelined batch = 1).
#: A per-handle instance counter can't be scraped after the handle dies;
#: the registry series is the durable process total.
_ROUND_TRIPS_TOTAL = REGISTRY.counter(
    "tpu_faas_store_round_trips_total",
    "Store wire round trips paid by this process (pipelined batch = 1)",
    ("backend",),
)
#: Command bytes put on the store wire by this process — the payload
#: plane's primary win is measured here (a digest task record is ~100
#: bytes where the inline form carried the whole function body), so the
#: bench lane and operators need it as a first-class series, not a
#: tcpdump session.
_BYTES_SENT_TOTAL = REGISTRY.counter(
    "tpu_faas_store_bytes_sent_total",
    "Encoded command bytes sent to the store by this process",
    ("backend",),
)

#: Store failovers this process's clients performed: an endpoint rotation
#: that SETTLED on a different endpoint than the previous commands used.
#: Process-global like the round-trip counter — the operator-facing
#: "how often did we fail over" series.
_FAILOVERS_TOTAL = REGISTRY.counter(
    "tpu_faas_store_failovers_total",
    "Store endpoint failovers performed by this process's clients "
    "(reconnects that settled on a different endpoint)",
    ("backend",),
)

#: Commands that must not be replayed after an ambiguous connection loss —
#: replaying a PUBLISH announces (and therefore dispatches) a task twice, and
#: both servers apply SHUTDOWN then close without replying, so a retry would
#: shut down the supervisor-restarted replacement too.
_NON_IDEMPOTENT = frozenset({"PUBLISH", "SHUTDOWN"})


class _Conn:
    """One blocking RESP connection."""

    def __init__(self, host: str, port: int, timeout: float = 10.0) -> None:
        self.sock = socket.create_connection((host, port), timeout=timeout)
        self.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
        self.parser = resp.RespParser()
        #: True once THIS connection negotiated the binary-batch command
        #: surface (CAPS advertised "binbatch") — per-connection exactly
        #: like CAP_BIN on the worker wire: a reconnect (possibly to a
        #: plain Redis after failover) re-negotiates from scratch
        self.binbatch = False

    def send(self, *parts: str | bytes | int) -> int:
        data = resp.encode_command(*parts)
        self.sock.sendall(data)
        return len(data)

    def recv_reply(self, raw: bool = False):
        while True:
            item = self.parser.pop(raw=raw)
            if item is not resp.NEED_MORE:
                if isinstance(item, resp.RespError):
                    raise item
                return item
            data = self.sock.recv(65536)
            if not data:
                raise ConnectionError("store connection closed")
            self.parser.feed(data)

    def send_many(self, commands) -> int:
        """RESP pipelining: every command in one write; replies follow in
        order. Returns bytes written."""
        data = b"".join(resp.encode_command(*c) for c in commands)
        self.sock.sendall(data)
        return len(data)

    def command(self, *parts: str | bytes | int):
        self.send(*parts)
        return self.recv_reply()

    def close(self) -> None:
        try:
            self.sock.close()
        except OSError:
            pass


class _RespSubscription(Subscription):
    """Dedicated connection subscribed to one channel.

    Survives a store restart: on connection loss the next ``get_message``
    reconnects and resubscribes. Messages published while disconnected are
    lost — exactly the fire-and-forget pub/sub contract the dispatchers
    already handle (reference SURVEY §5.4: stranded announcements).

    Failover: when built by a multi-endpoint RespStore, the subscription
    follows the store's ACTIVE endpoint — the one the command path's
    FENCE/ROLE handshake settled on — so after a promotion the bus
    reattaches to the endpoint actually receiving the writes (announces
    published to a fenced old primary's bus would never arrive). A
    generation check on every drain forces the reattach even while the
    old socket still looks healthy."""

    def __init__(
        self, host: str, port: int, channel: str, store: "RespStore | None" = None
    ) -> None:
        self._host = host
        self._port = port
        self._store = store
        self._channel = channel
        self._conn: _Conn | None = None
        self._gen = -1
        self._closed = False
        self._connect()  # initial failure propagates: caller wants a live bus

    def _endpoint(self) -> tuple[str, int, int]:
        if self._store is not None:
            # one-attribute read: endpoint and generation arrive together
            # (separate host/port/generation reads could tear against a
            # concurrent failover and pin this sub to the old endpoint
            # while recording the new generation)
            return self._store._sub_target
        return self._host, self._port, 0

    def _connect(self) -> None:
        host, port, gen = self._endpoint()
        self._conn = _Conn(host, port)
        self._gen = gen
        reply = self._conn.command("SUBSCRIBE", self._channel)
        if not (isinstance(reply, list) and reply[0] == "subscribe"):
            raise resp.RespError(f"unexpected SUBSCRIBE reply: {reply!r}")

    def _reconnect(self) -> bool:
        if self._closed:
            return False
        if self._conn is not None:
            self._conn.close()
            self._conn = None
        try:
            self._connect()
            return True
        except OSError:
            return False

    def get_message(self, timeout: float = 0.0) -> str | None:
        if self._closed:
            return None
        if (
            self._conn is not None
            and self._store is not None
            and self._store.failover_generation != self._gen
        ):
            # the command path failed over: this socket may point at a
            # dead (or fenced — silently announce-less) endpoint. Any
            # frames still buffered on the old connection are drained
            # first; announces published to the old endpoint after the
            # failover are the bus's documented fire-and-forget loss,
            # covered by the dispatcher's replay + rescan re-arm.
            drained = self._drain_buffered()
            if drained is not None:
                return drained
            self._conn.close()
            self._conn = None
        if self._conn is None and not self._reconnect():
            return None
        try:
            return self._get_message(timeout)
        except (ConnectionError, OSError):
            if self._conn is not None:
                self._conn.close()
                self._conn = None  # reconnect on the next call
            return None

    def _drain_buffered(self) -> str | None:
        """Pop the next already-parsed push message without touching the
        socket (the failover handoff's no-loss drain of the old conn)."""
        item = self._conn.parser.pop()
        while item is not resp.NEED_MORE:
            payload = self._decode_push(item)
            if payload is not None:
                return payload
            item = self._conn.parser.pop()
        return None

    def _get_message(self, timeout: float) -> str | None:
        # First drain anything already parsed/buffered.
        payload = self._drain_buffered()
        if payload is not None:
            return payload
        # Then poll the socket.
        deadline = None if timeout <= 0 else timeout
        while True:
            ready, _, _ = select.select([self._conn.sock], [], [], deadline or 0)
            if not ready:
                return None
            data = self._conn.sock.recv(65536)
            if not data:
                raise ConnectionError("subscription connection closed")
            self._conn.parser.feed(data)
            item = self._conn.parser.pop()
            while item is not resp.NEED_MORE:
                payload = self._decode_push(item)
                if payload is not None:
                    return payload
                item = self._conn.parser.pop()
            # Partial message: keep waiting within the same timeout window.
            # (Simplification: we don't decrement the deadline; pub/sub frames
            # are tiny so a partial read resolves on the next recv.)

    def fileno(self) -> int | None:
        """Readability fd of the live subscription socket (None while
        disconnected) — lets an event-driven serve loop park in one poll()
        over workers AND the bus. The fd changes on reconnect/failover;
        pollers re-check each iteration (Subscription.fileno contract).
        NOTE: messages already parsed into the buffer don't show as
        readability — consumers drain to empty each wake, and their
        periodic fallback covers the rest."""
        if self._closed or self._conn is None:
            return None
        try:
            return self._conn.sock.fileno()
        except OSError:
            return None

    @staticmethod
    def _decode_push(item) -> str | None:
        if (
            isinstance(item, list)
            and len(item) == 3
            and item[0] == "message"
        ):
            return item[2]
        return None  # subscribe/unsubscribe confirmations etc.

    def close(self) -> None:
        # mark closed FIRST: a dispatch loop mid-get_message on another
        # thread would otherwise resurrect the subscription (reconnect +
        # re-SUBSCRIBE) after its owner already closed it
        self._closed = True
        if self._conn is not None:
            self._conn.close()


class RespStore(TaskStore):
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 6380,
        endpoints: list[tuple[str, int]] | None = None,
        binbatch: bool = False,
    ) -> None:
        #: ordered failover ring; [(host, port)] in the classic
        #: single-endpoint form
        self.endpoints: list[tuple[str, int]] = (
            list(endpoints) if endpoints else [(host, port)]
        )
        #: where the next connect STARTS walking the ring (rotate_endpoint
        #: advances it); distinct from _settled_idx, the endpoint the last
        #: successful connection actually landed on
        self._active_idx = 0
        self._settled_idx = 0
        #: bumped every time commands SETTLE on a different endpoint than
        #: before; dispatchers watch it to trigger their failover re-arm
        #: (announce replay + rescan) and subscriptions to reattach
        self.failover_generation = 0
        #: highest fencing epoch any handshake reported; re-declared via
        #: FENCE on every multi-endpoint connect (never sent with a
        #: single endpoint — plain-Redis wire compatibility)
        self.known_epoch = 0
        #: (host, port, failover_generation) the subscriptions follow —
        #: one tuple attribute, written whole on settle, so subscription
        #: threads read endpoint and generation consistently lock-free
        self._sub_target: tuple[str, int, int] = (*self.endpoints[0], 0)
        self._lock = threading.Lock()
        self._closed = False
        #: wire round trips paid by this handle (TaskStore.n_round_trips
        #: contract: one pipelined batch = one). Written under the command
        #: lock; read lock-free by stats pollers (a torn read of an int is
        #: impossible in CPython, and the counter is observability only).
        self.n_round_trips = 0
        #: command bytes this handle put on the wire (same lock-free read
        #: contract as n_round_trips) — the bench lane's bytes-per-task
        #: measurement is a delta over this
        self.n_bytes_sent = 0
        self._rt_series = _ROUND_TRIPS_TOTAL.labels(backend="resp")
        self._bytes_series = _BYTES_SENT_TOTAL.labels(backend="resp")
        self._failover_series = _FAILOVERS_TOTAL.labels(backend="resp")
        #: binary-batch knob (``--store-binbatch``): when True, every fresh
        #: connection probes CAPS once and — iff the server advertises
        #: "binbatch" — hgetall_many/finish_task_many collapse into the
        #: MHGETALL/MFINISH aggregate commands with raw-bytes reply
        #: parsing. Off (the default) sends ZERO extra bytes: the wire
        #: toward a plain Redis is byte-identical to before (the same
        #: contract as the single-endpoint no-handshake rule above).
        self._binbatch = bool(binbatch)
        #: fault-injection seam (tpu_faas/chaos): None when
        #: TPU_FAAS_CHAOS is unset — one identity check per round trip,
        #: wire and exposition surfaces byte-identical
        from tpu_faas import chaos as _chaos

        _plan = _chaos.from_env()
        self._chaos = _plan.store() if _plan is not None else None
        self._conn: _Conn | None = self._connect()

    @property
    def host(self) -> str:
        return self.endpoints[self._settled_idx][0]

    @property
    def port(self) -> int:
        return self.endpoints[self._settled_idx][1]

    def _connect(self) -> _Conn:
        """Connect to the first WRITABLE endpoint, walking the ring from
        the active index. Single-endpoint: a plain connect, no handshake
        bytes — the classic (plain-Redis-compatible) wire surface.
        Multi-endpoint: each TCP-reachable candidate gets a pipelined
        FENCE(known_epoch) + ROLE handshake; unpromoted replicas and
        fenced stale primaries are skipped (the FENCE declaration is what
        fences a resurrected old primary — see store/replication.py).
        Settling on a different endpoint than the previous connection
        bumps ``failover_generation`` and the failovers counter. Raises
        ConnectionError when no endpoint is writable — the same outage
        family the breaker and the dispatchers already handle."""
        n = len(self.endpoints)
        if n == 1:
            return self._negotiate(_Conn(*self.endpoints[0]))
        # discovery sweep: handshake EVERY reachable endpoint before
        # settling, so the highest epoch in the fleet is known first — a
        # fresh process (known_epoch 0) must not settle on a resurrected
        # stale primary while the true (higher-epoch) primary is also
        # reachable, and the stale one gets actively fenced below
        last_err: Exception | None = None
        candidates: list[tuple[int, _Conn, int, str | None]] = []
        for step in range(n):
            idx = (self._active_idx + step) % n
            host, port = self.endpoints[idx]
            try:
                conn = _Conn(host, port)
            except OSError as exc:
                last_err = exc
                continue
            try:
                conn.send_many(
                    [("FENCE", self.known_epoch), ("ROLE",)]
                )
                srv_epoch = conn.recv_reply()
                role_reply = conn.recv_reply()
            except (OSError, ConnectionError, resp.RespError) as exc:
                # RespError too: an endpoint that can't speak the HA
                # handshake (a plain Redis slipped into a multi-endpoint
                # ring) is not failover-safe to write through
                conn.close()
                last_err = exc
                continue
            epoch = srv_epoch if isinstance(srv_epoch, int) else -1
            self.known_epoch = max(self.known_epoch, epoch)
            role = role_reply[0] if isinstance(role_reply, list) and role_reply else None
            candidates.append((idx, conn, epoch, role))
        # the true primary is the one carrying the fleet's highest epoch;
        # a "primary" below it is a resurrected stale one — never settle
        # there (its writes are doomed to -ERR FENCED anyway)
        best: tuple[int, _Conn, int, str | None] | None = None
        for cand in candidates:
            if cand[3] == "primary" and cand[2] >= self.known_epoch:
                if best is None or cand[2] > best[2]:
                    best = cand
        for idx, conn, epoch, role in candidates:
            if best is not None and conn is best[1]:
                continue
            if role == "primary" and epoch < self.known_epoch:
                # actively fence the stale primary: our first handshake may
                # have declared a lower epoch than the sweep ended up with
                try:
                    conn.send_many([("FENCE", self.known_epoch)])
                    conn.recv_reply()
                except (OSError, ConnectionError, resp.RespError):
                    pass
            if role != "primary":
                last_err = ConnectionError(
                    f"store {self.endpoints[idx][0]}:{self.endpoints[idx][1]} "
                    f"is {role or 'unknown'}, not primary"
                )
            conn.close()
        if best is None:
            raise ConnectionError(
                f"no writable store endpoint among {self.endpoints}"
                + (f" (last: {last_err})" if last_err else "")
            )
        idx, conn, _epoch, _role = best
        self._active_idx = idx
        if idx != self._settled_idx:
            self._settled_idx = idx
            self.failover_generation += 1
            self._failover_series.inc()
        # one atomic tuple for the subscription threads: endpoint and
        # generation must be read together (a torn host/port-vs-generation
        # read would pin a subscription to the old endpoint while marking
        # it current, silencing the bus until an unrelated socket error)
        host, port = self.endpoints[idx]
        self._sub_target = (host, port, self.failover_generation)
        return self._negotiate(conn)

    def _negotiate(self, conn: _Conn) -> _Conn:
        """Binary-batch capability probe on a fresh connection: one CAPS
        round trip, sent ONLY when the knob is on (off = zero extra bytes,
        the byte-identical plain-Redis surface). A backend without CAPS
        (real Redis, native server) answers -ERR unknown command — read as
        no capabilities, never an error: the slow paths keep working and
        the negotiation result is pinned per-connection like CAP_BIN."""
        if self._binbatch:
            try:
                reply = conn.command("CAPS")
                conn.binbatch = isinstance(reply, list) and "binbatch" in reply
            except resp.RespError:
                conn.binbatch = False
        return conn

    def rotate_endpoint(self) -> bool:
        """Advance the ring so the NEXT connect starts at the following
        endpoint — the circuit breaker's half-open hook: a probe that
        failed against a dead-but-black-holing primary (slow connect
        timeout) immediately probes the replica instead of retrying the
        same endpoint or waiting out another open window. Returns False
        on single-endpoint handles (nothing to rotate to)."""
        if len(self.endpoints) < 2:
            return False
        with self._lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None
            self._active_idx = (self._active_idx + 1) % len(self.endpoints)
        return True

    def _command(self, *parts: str | bytes | int, _raw: bool = False):
        """Run one command; transparently reconnect once if the server
        restarted (matches redis-py's retry-on-ConnectionError the reference
        relies on — without it a store restart would permanently wedge every
        gateway/dispatcher holding a connection).

        Retry is restricted to idempotent commands. A ConnectionError is
        ambiguous too (the server may have applied the command and died
        before replying), and replaying a PUBLISH would announce the same
        task twice — dispatching it to two workers. Hash writes replay to the
        same end state, so they retry; PUBLISH raises to the caller, whose
        announce is at-most-once (a stranded QUEUED task is recoverable — the
        tpu-push dispatcher rescans for stranded tasks at startup and every
        ``rescan_period`` seconds while serving; double execution is not).

        ``self._conn`` is None between a failed reconnect and the next call:
        if the replacement connection can't be made immediately (server still
        restarting), the client must not keep using the CLOSED old socket —
        that would turn every later ConnectionError into a plain
        EBADF OSError that nothing retries, wedging the client forever.
        Instead the broken connection is dropped and each subsequent call
        retries the connect lazily until the server is back."""
        with self._lock:
            if self._closed:
                # a serve thread racing close() must not resurrect the
                # connection (same guard as _RespSubscription.close)
                raise ConnectionError("store client is closed")
            if self._conn is None:
                # previous reconnect failed; retry it now (raises if the
                # server is still down, leaving _conn None for next time)
                self._conn = self._connect()
            if self._chaos is not None:
                # may sleep (latency) or raise ConnectionError (outage
                # window) BEFORE the socket is touched — the injected
                # outage must look like an unreachable store, not a
                # desynchronized connection
                self._chaos.before(str(parts[0]))
            try:
                # deliberate I/O under lock: this lock EXISTS to serialize
                # use of the one connection (RESP replies are positional)
                self.n_round_trips += 1
                self._rt_series.inc()
                sent = self._conn.send(*parts)  # faas: allow(locks.blocking-call-under-lock)
                self.n_bytes_sent += sent
                self._bytes_series.inc(sent)
                return self._conn.recv_reply(raw=_raw)  # faas: allow(locks.blocking-call-under-lock)
            except (ConnectionError, TimeoutError):
                # TimeoutError too: the reply may still arrive later, so the
                # old connection is DESYNCHRONIZED (a future command would
                # read the stale reply as its own) — it must be dropped, and
                # any retry must go through a fresh connection
                self._conn.close()
                self._conn = None
                conn = self._connect()  # may raise: _conn stays None
                self._conn = conn
                if str(parts[0]).upper() in _NON_IDEMPOTENT:
                    raise
                # same serialized-connection justification as above
                self.n_round_trips += 1
                self._rt_series.inc()  # the retry is a second round trip
                sent = conn.send(*parts)  # faas: allow(locks.blocking-call-under-lock)
                self.n_bytes_sent += sent
                self._bytes_series.inc(sent)
                return conn.recv_reply(raw=_raw)  # faas: allow(locks.blocking-call-under-lock)

    def pipeline(self, commands: list[tuple], _raw: bool = False) -> list:
        """Run many commands over one round trip (RESP pipelining) and
        return their replies in order; error replies come back as
        :class:`resp.RespError` values in place rather than raising, so one
        bad command cannot mask the other N-1 results.

        ``_raw`` reads every reply in raw mode (bulk strings stay bytes) —
        the binary-batch fast paths' pipelined MHGETALL reads.

        No automatic retry: after a mid-pipeline connection loss there is no
        telling which commands were applied, so the connection is dropped
        and the outage surfaces to the caller."""
        if not commands:
            return []
        with self._lock:
            if self._closed:
                raise ConnectionError("store client is closed")
            if self._conn is None:
                self._conn = self._connect()
            if self._chaos is not None:
                self._chaos.before("PIPELINE")
            conn = self._conn
            try:
                # deliberate I/O under lock (see _command): one connection,
                # positional replies — interleaved pipelines would desync
                self.n_round_trips += 1
                self._rt_series.inc()  # N commands, one round trip
                sent = conn.send_many(commands)  # faas: allow(locks.blocking-call-under-lock)
                self.n_bytes_sent += sent
                self._bytes_series.inc(sent)
                out: list = []
                for _ in commands:
                    try:
                        out.append(conn.recv_reply(raw=_raw))  # faas: allow(locks.blocking-call-under-lock)
                    except resp.RespError as exc:
                        out.append(exc)
                if self._chaos is not None and self._chaos.torn():
                    # torn pipeline: every command APPLIED (replies were
                    # read), but the caller sees the connection die before
                    # learning so — the applied-but-reply-lost ambiguity
                    # the no-retry contract above exists for. The handler
                    # below tears the connection down for real.
                    raise ConnectionError(
                        "chaos: torn pipeline (commands applied, reply "
                        "lost)"
                    )
                return out
            except (ConnectionError, TimeoutError):
                conn.close()
                self._conn = None
                raise

    # -- raw hash ops ------------------------------------------------------
    def hset(self, key: str, fields: Mapping[str, str]) -> None:
        flat: list[str] = []
        for f, v in fields.items():
            flat.extend((f, v))
        self._command("HSET", key, *flat)

    def hget(self, key: str, field: str) -> str | None:
        return self._command("HGET", key, field)

    def hgetall(self, key: str) -> dict[str, str]:
        flat = self._command("HGETALL", key)
        return dict(zip(flat[0::2], flat[1::2]))

    def hmget(self, key: str, fields: list[str]) -> list[str | None]:
        return self._command("HMGET", key, *fields)

    @staticmethod
    def _finish_cmds(
        task_id: str,
        status,
        result: str,
        now: str,
        inline_max: int = 0,
        result_digest: str | None = None,
        result_size: int = 0,
    ) -> list[tuple]:
        """The terminal-write command triple shared by finish_task and
        finish_task_many — ONE builder, so the single and batched forms can
        never desynchronize on the record contract. ``inline_max`` > 0
        (express lane) puts the status + result inline on the announce —
        SAME pipelined round, record write still first, so durability and
        ordering are unchanged. ``result_digest`` (result-blob plane)
        appends the digest-form fields to the same HSET and switches the
        announce to the digest form; None keeps the legacy commands byte
        for byte."""
        from tpu_faas.core.task import (
            FIELD_FINAL_AT,
            FIELD_FINAL_STATUS,
            FIELD_FINISHED_AT,
            FIELD_RESULT,
            FIELD_RESULT_DIGEST,
            FIELD_RESULT_SIZE,
            FIELD_STATUS,
        )

        hset: tuple = (
            "HSET", task_id,
            FIELD_STATUS, str(status),
            # redundant stamps powering cancel_task's clobber repair
            # (base.finish_task writes the same fields)
            FIELD_FINAL_STATUS, str(status),
            FIELD_FINAL_AT, now,
            FIELD_RESULT, result,
            FIELD_FINISHED_AT, now,
        )
        if result_digest:
            hset = hset + (
                FIELD_RESULT_DIGEST, result_digest,
                FIELD_RESULT_SIZE, str(int(result_size)),
            )
        return [
            hset,
            ("HDEL", LIVE_INDEX_KEY, task_id),  # drop from the live index
            (
                "PUBLISH", RESULTS_CHANNEL,
                encode_result_announce(
                    task_id, str(status), result, inline_max,
                    result_digest=result_digest, result_size=result_size,
                ),
            ),
        ]

    def finish_task(
        self,
        task_id: str,
        status,
        result: str,
        first_wins: bool = False,
        inline_max: int = 0,
        result_digest: str | None = None,
        result_size: int = 0,
    ) -> None:
        """Base semantics (terminal write + RESULTS_CHANNEL announce), but
        the write and the announce ride ONE pipelined round trip — the
        result path is the dispatcher's per-task hot path and must not grow
        a second RTT for the wake-up feature."""
        if first_wins and self._result_frozen(task_id):
            return
        cmds = self._finish_cmds(
            task_id, status, result, repr(time.time()), inline_max,
            result_digest=result_digest, result_size=result_size,
        )
        try:
            replies = self.pipeline(cmds)
        except (ConnectionError, TimeoutError):
            # retry once on a fresh connection (pipeline() dropped the dead
            # one), preserving the transparent reconnect result writes had
            # via _command before pipelining. Unlike the task-announce
            # PUBLISH (non-idempotent: a replay dispatches a task twice),
            # replaying THIS pair is safe — HSET lands the same end state
            # and a duplicate RESULTS_CHANNEL publish is just a spurious
            # wake the gateway handlers tolerate by design.
            replies = self.pipeline(cmds)
        errors = [r for r in replies if isinstance(r, resp.RespError)]
        if errors:
            raise errors[0]

    def hdel(self, key: str, *fields: str) -> None:
        if fields:
            self._command("HDEL", key, *fields)

    def delete(self, key: str) -> None:
        self._command("DEL", key)

    def delete_many(self, keys: list[str]) -> None:
        if keys:
            self._command("DEL", *keys)  # one round trip, variadic DEL

    def hset_many(self, items) -> None:
        """Pipelined multi-hash HSET: the lease-renewal path touches every
        in-flight task once per period — one round trip, not one per task."""
        if not items:
            return
        cmds = [
            ("HSET", key, *(p for kv in fields.items() for p in kv))
            for key, fields in items
        ]
        replies = self.pipeline(cmds)
        errors = [r for r in replies if isinstance(r, resp.RespError)]
        if errors:
            raise errors[0]

    def hexists(self, key: str, field: str) -> bool:
        return bool(self._command("HEXISTS", key, field))

    def hincrby(self, key: str, field: str, delta: int) -> int:
        # atomic at the single-threaded server (real Redis's HINCRBY has
        # the same contract) — the dependency plane's pending-count
        # decrement must not lose updates between concurrent dispatchers
        return int(self._command("HINCRBY", key, field, int(delta)))

    def hincrby_many(self, items: list[tuple[str, str, int]]) -> list[int]:
        """Pipelined HINCRBY: the promotion plane decrements every child of
        a finished parent batch in ONE round trip."""
        if not items:
            return []
        replies = self.pipeline(
            [("HINCRBY", key, field, int(delta)) for key, field, delta in items]
        )
        errors = [r for r in replies if isinstance(r, resp.RespError)]
        if errors:
            raise errors[0]
        return [int(r) for r in replies]

    def setnx_field(
        self, key: str, field: str, value: str
    ) -> tuple[bool, str]:
        # HSETNX is atomic at the single-threaded server; the HGET read-back
        # is correct even if another command interleaves, because a claimed
        # field is write-once (the winner's full-record HSET repeats the
        # same value and nothing else ever mutates it)
        created, current = self.pipeline(
            [("HSETNX", key, field, value), ("HGET", key, field)]
        )
        return created == 1, current

    def setnx_fields(
        self, items: list[tuple[str, str]], field: str
    ) -> list[tuple[bool, str]]:
        if not items:
            return []
        cmds: list[tuple] = []
        for key, value in items:
            cmds.append(("HSETNX", key, field, value))
            cmds.append(("HGET", key, field))
        replies = self.pipeline(cmds)
        return [
            (replies[2 * i] == 1, replies[2 * i + 1])
            for i in range(len(items))
        ]

    def hsetnx_many(self, items) -> list[bool]:
        """Pipelined HSETNX over (key, field, value) triples: the span
        plane's first-write-wins flush pays one round trip per flush, not
        one per span. An error reply on one item (foreign WRONGTYPE key)
        degrades to created=False for that item instead of poisoning the
        batch — spans are telemetry, the healthy writes must land."""
        if not items:
            return []
        replies = self.pipeline(
            [("HSETNX", key, field, value) for key, field, value in items]
        )
        return [r == 1 for r in replies]

    # -- pipelined batch ops ----------------------------------------------
    def hget_many(self, keys, field: str):
        return self.pipeline([("HGET", k, field) for k in keys])

    #: keys per MHGETALL command on the binary-batch path. The stream
    #: parser (store/resp.py) re-parses a partial nested array from its
    #: start each time more bytes arrive, so one monolithic MHGETALL over
    #: a whole intake batch (potentially MBs, dozens of recv chunks) costs
    #: quadratic parse work — measured as the dominant intake cost at the
    #: 20k-task bench shape. Bounded chunks pipelined over ONE round trip
    #: keep replies inside a couple of recv buffers (parse stays ~linear)
    #: and bound the server-side reply buffer too.
    _MHGETALL_CHUNK = 256

    def _binbatch_on(self) -> bool:
        """Whether the CURRENT connection negotiated the binary-batch
        command surface. Lock-free read by design (attribute reads are
        atomic in CPython); a reconnect racing the check is handled by the
        fast paths themselves — an MHGETALL/MFINISH landing on a freshly
        non-capable connection comes back as RespError and the caller
        falls through to the slow path."""
        conn = self._conn
        return self._binbatch and conn is not None and conn.binbatch

    def hgetall_many(self, keys):
        """Pipelined HGETALL over many keys — the batched-intake read: one
        round trip fetches every announced task's record. A per-key error
        reply (a WRONGTYPE key some foreign producer wrote) degrades to {}
        for THAT key — the same shape as a missing record, which intake
        skips with a warning — instead of raising and poisoning the whole
        batch: one bad key must never wedge the other N-1 announces (or,
        parked and re-drained, wedge intake forever).

        On a negotiated binary-batch connection the N pipelined HGETALLs
        collapse into pipelined MHGETALL commands of bounded chunks (one
        round trip, same reply shape per key; see ``_MHGETALL_CHUNK``)."""
        if not keys:
            return []
        if self._binbatch_on():
            reply = self._mhgetall_chunked(keys, raw_mode=False)
            if reply is not None:
                return [
                    dict(zip(flat[0::2], flat[1::2]))
                    if isinstance(flat, list)
                    else {}
                    for flat in reply
                ]
        out: list[dict[str, str]] = []
        for flat in self.pipeline([("HGETALL", k) for k in keys]):
            if isinstance(flat, resp.RespError):
                out.append({})
                continue
            out.append(dict(zip(flat[0::2], flat[1::2])))
        return out

    def hgetall_many_raw(self, keys) -> list[list]:
        """Base semantics (flat [field, value, ...] per key), but on a
        negotiated binary-batch connection the whole fetch is ONE MHGETALL
        with the reply parsed in RAW mode — bulk strings stay ``bytes``,
        no per-field utf-8 decode, no per-record dict. The columnar intake
        (dispatch/base.py) parses these flat lists straight into arena
        columns. Fallback (knob off / plain Redis / mid-failover): the
        pipelined HGETALL path with ``str`` elements — callers handle
        both element types by contract."""
        if not keys:
            return []
        if self._binbatch_on():
            reply = self._mhgetall_chunked(keys, raw_mode=True)
            if reply is not None:
                return [
                    flat if isinstance(flat, list) else [] for flat in reply
                ]
        out: list[list] = []
        for flat in self.pipeline([("HGETALL", k) for k in keys]):
            out.append([] if isinstance(flat, resp.RespError) else flat)
        return out

    def _mhgetall_chunked(self, keys, raw_mode: bool):
        """Fetch ``keys`` as pipelined bounded-chunk MHGETALLs (one round
        trip). Returns the per-key reply list, or None when any chunk came
        back non-conforming (peer changed under us mid-failover) — the
        caller falls through to the plain pipelined-HGETALL path."""
        chunk = self._MHGETALL_CHUNK
        cmds = [
            ("MHGETALL", *keys[lo : lo + chunk])
            for lo in range(0, len(keys), chunk)
        ]
        try:
            replies = self.pipeline(cmds, _raw=raw_mode)
        except resp.RespError:
            return None
        out: list = []
        for cmd, reply in zip(cmds, replies):
            if not isinstance(reply, list) or len(reply) != len(cmd) - 1:
                return None
            out.extend(reply)
        return out

    def set_status_many(self, status, items) -> None:
        """Pipelined multi-task status write (base semantics: one shared
        status, per-item extra fields) — the dispatcher's coalesced
        RUNNING flush pays one round trip per tick, not one per task."""
        from tpu_faas.core.task import FIELD_STATUS

        if not items:
            return
        cmds = []
        for task_id, extra in items:
            fields = {FIELD_STATUS: str(status), **(extra or {})}
            cmds.append(
                ("HSET", task_id, *(p for kv in fields.items() for p in kv))
            )
        errors = [
            r for r in self.pipeline(cmds) if isinstance(r, resp.RespError)
        ]
        if errors:
            raise errors[0]

    def finish_task_many(self, items, inline_max: int = 0) -> None:
        """Batch finish_task in a bounded number of round trips: one
        pipelined status pre-read for the first_wins slice (the frozen
        probe ``_result_frozen`` pays per task on the loop default), then
        every surviving write+index-drop+announce in ONE pipelined round —
        each task's announce still follows its own record write (RESP
        pipelines execute in order). Intra-batch first_wins is preserved
        by tracking ids already written earlier in the batch.

        Like the single finish_task, a connection loss retries the whole
        round once on a fresh connection: HSET replays to the same end
        state and duplicate RESULTS_CHANNEL publishes are tolerated
        spurious wakes.

        On a negotiated binary-batch connection the whole batch — pre-read
        included — is ONE MFINISH command: the server evaluates the
        first_wins freeze set against its own state (identical semantics,
        pinned by tests/test_store_resp.py), saving both the pre-read
        round trip and the 3N-command pipeline build. MFINISH replays to
        the same end state (re-applied fw items are frozen by their own
        first write), so _command's idempotent reconnect-retry applies."""
        from tpu_faas.core.task import FIELD_STATUS, TaskStatus

        if not items:
            return
        # digest-form items (result-blob plane, 6-tuples with a digest)
        # carry fields the MFINISH wire has no slots for: the batch then
        # takes the pipelined slow path below, which shares _finish_cmds
        # with the single write. Legacy 4-tuple batches keep the one-command
        # fast path untouched.
        any_digest = any(len(it) > 4 and it[4] for it in items)
        if self._binbatch_on() and not any_digest:
            flat: list[str] = []
            for task_id, status, result, fw in (it[:4] for it in items):
                flat += [task_id, str(status), result, "1" if fw else "0"]
            try:
                self._command(
                    "MFINISH", repr(time.time()), int(inline_max),
                    len(items), *flat,
                )
                return
            except resp.RespError:
                pass  # peer changed under us: slow path below
        fw_ids = list(
            dict.fromkeys(it[0] for it in items if it[3])
        )
        frozen: set[str] = set()
        if fw_ids:
            for t_id, status in zip(fw_ids, self.hget_many(fw_ids, FIELD_STATUS)):
                if isinstance(status, resp.RespError):
                    status = None  # unparseable: freeze (never overwrite)
                if status == str(TaskStatus.CANCELLED):
                    continue  # a late real result lawfully overwrites
                if TaskStatus.terminal_str(status, unknown=True):
                    frozen.add(t_id)
        now = repr(time.time())
        cmds: list[tuple] = []
        written: set[str] = set()
        for item in items:
            task_id, status, result, first_wins = item[:4]
            if first_wins and (task_id in written or task_id in frozen):
                continue
            cmds.extend(
                self._finish_cmds(
                    task_id, status, result, now, inline_max,
                    result_digest=item[4] if len(item) > 4 else None,
                    result_size=int(item[5]) if len(item) > 5 else 0,
                )
            )
            written.add(task_id)
        if not cmds:
            return
        try:
            replies = self.pipeline(cmds)
        except (ConnectionError, TimeoutError):
            replies = self.pipeline(cmds)  # same rationale as finish_task
        errors = [r for r in replies if isinstance(r, resp.RespError)]
        if errors:
            raise errors[0]

    def put_blob(self, digest: str, data: str) -> bool:
        """Base semantics (setnx'd data + TTL-stamp refresh) in ONE
        pipelined round trip — the gateway pays this on every function
        registration, not per task."""
        key = blob_key(digest)
        replies = self.pipeline(
            [
                ("HSETNX", key, BLOB_DATA_FIELD, data),
                ("HSET", key, BLOB_AT_FIELD, repr(time.time())),
            ]
        )
        errors = [r for r in replies if isinstance(r, resp.RespError)]
        if errors:
            raise errors[0]
        return replies[0] == 1

    def create_tasks(
        self, tasks, channel: str = TASKS_CHANNEL, status=None
    ) -> None:
        from tpu_faas.core.task import (
            FIELD_FN,
            FIELD_PARAMS,
            FIELD_RESULT,
            FIELD_STATUS,
            TaskStatus,
        )

        if status is None:
            status = TaskStatus.QUEUED
        commands: list[tuple] = []
        if tasks:
            # live-index entries first (same ordering rationale as
            # base.create_task), all ids in one variadic HSET
            commands.append(
                (
                    "HSET", LIVE_INDEX_KEY,
                    *(p for task in tasks for p in (task[0], "1")),
                )
            )
        for task in tasks:
            task_id, fn_payload, param_payload = task[:3]
            extra = task[3] if len(task) > 3 else None
            extra_args: list[str] = []
            for k, v in (extra or {}).items():
                extra_args += [k, v]
            commands.append(
                (
                    "HSET", task_id,
                    *extra_args,
                    FIELD_STATUS, str(status),
                    FIELD_FN, fn_payload,
                    FIELD_PARAMS, param_payload,
                    FIELD_RESULT, "None",
                )
            )
        # announces AFTER every hash write: a dispatcher must never receive
        # an announce for a task whose payloads aren't readable yet
        for task in tasks:
            commands.append(("PUBLISH", channel, task[0]))
        replies = self.pipeline(commands)
        # pipeline() returns error replies in place; swallowing one here
        # would hand the caller task_ids for tasks that were never written
        # (announced ghosts) or never announced (stranded until a rescan)
        errors = [r for r in replies if isinstance(r, resp.RespError)]
        if errors:
            raise errors[0]

    def keys(self) -> list[str]:
        return self._command("KEYS", "*")

    # -- announce bus ------------------------------------------------------
    def publish(self, channel: str, payload: str) -> None:
        self._command("PUBLISH", channel, payload)

    def publish_many(self, channel: str, payloads: list[str]) -> None:
        """One pipelined round of PUBLISHes (the batched keyed-create's
        announce fan-out)."""
        if not payloads:
            return
        replies = self.pipeline(
            [("PUBLISH", channel, p) for p in payloads]
        )
        errors = [r for r in replies if isinstance(r, resp.RespError)]
        if errors:
            raise errors[0]

    def subscribe(self, channel: str) -> Subscription:
        # store=self: a multi-endpoint subscription follows the command
        # path's settled endpoint across failovers (single-endpoint
        # handles behave exactly as before — the provider returns the one
        # endpoint forever)
        return _RespSubscription(self.host, self.port, channel, store=self)

    # -- high availability (store/replication.py) --------------------------
    def replay_announces(
        self, after: int
    ) -> tuple[int, list[tuple[str, str]]]:
        """Drain the server's bounded announce ring: entries published
        with replication offset > ``after``, plus the current tail
        offset. ``after=-1`` fetches the tail alone (offset priming).
        The dispatcher's post-failover re-arm calls this on the promoted
        replica to re-discover announces the dead primary published that
        nobody drained. Raises RespError on servers without REPLAY (a
        plain Redis) — callers degrade to rescan-only re-arm."""
        reply = self._command("REPLAY", int(after))
        if not isinstance(reply, list) or not reply or not isinstance(reply[0], int):
            raise resp.RespError(f"unexpected REPLAY reply: {reply!r}")
        tail = reply[0]
        entries = list(zip(reply[1::2], reply[2::2]))
        return tail, entries

    def promote(self) -> int:
        """Promote the ACTIVE endpoint (operator action / failover
        controller): a replica takes the primary role and bumps the
        fencing epoch, which this client adopts immediately. Idempotent
        against an already-primary endpoint."""
        epoch = self._command("PROMOTE")
        if isinstance(epoch, int):
            self.known_epoch = max(self.known_epoch, epoch)
        return epoch

    def role(self) -> dict:
        """The active endpoint's replication role: ``{"role", "epoch",
        "offset"}`` (role is ``primary`` | ``replica`` | ``fenced``)."""
        reply = self._command("ROLE")
        if not (isinstance(reply, list) and len(reply) == 3):
            raise resp.RespError(f"unexpected ROLE reply: {reply!r}")
        return {"role": reply[0], "epoch": reply[1], "offset": reply[2]}

    # -- admin -------------------------------------------------------------
    def save(self, path: str | None = None) -> None:
        """Ask the server to checkpoint (to `path`, or its configured
        --snapshot file when omitted). Raises RespError if neither exists."""
        if path is None:
            self._command("SAVE")
        else:
            self._command("SAVE", path)

    def flush(self) -> None:
        self._command("FLUSHDB")

    def ping(self) -> bool:
        return self._command("PING") == "PONG"

    def info(self) -> dict[str, str]:
        """Server introspection: parse INFO's "key:value" lines (both the
        Python and native servers emit the same format)."""
        raw = self._command("INFO") or ""
        out: dict[str, str] = {}
        for line in raw.split("\n"):
            key, sep, value = line.partition(":")
            if sep:
                out[key] = value
        return out

    def close(self) -> None:
        self._closed = True  # before taking the lock: fail fast either way
        with self._lock:
            if self._conn is not None:
                self._conn.close()
                self._conn = None
