"""Federated control plane, store side: consistent-hash sharding.

Two pieces compose the single-store stack into a fleet (ROADMAP item 1):

- :class:`HashRing` — a deterministic consistent-hash ring mapping any
  store key to a shard index. Virtual nodes keep the key mass balanced,
  and adding/removing a shard moves only ~1/N of the keyspace (pinned by
  property tests) — a resize re-homes a bounded slice instead of
  reshuffling the world.
- :class:`ShardedStore` — a :class:`~tpu_faas.store.base.TaskStore` over
  N backend stores. Single-key ops route by the ring; the pipelined
  batch forms (``hgetall_many``, ``finish_task_many``,
  ``create_tasks_if_absent``, ...) partition their items by shard and fan
  the per-shard sub-batches out CONCURRENTLY, merging replies back into
  input order — a 4-shard batch pays roughly one shard's latency, not
  four. Every task-level convenience inherited from the base class keeps
  working because it is built from the routed primitives, including the
  graph promotion plane: ``complete_dep_many`` walks cross-shard
  dependency edges through the sharded batch ops, so a parent on shard A
  promotes (or poisons) its children on shard B with no extra machinery.

Routing rules (all deterministic, shared by every client of the fleet):

- task hashes (and any other plain key: ``trace:`` span hashes,
  ``function_digest:`` index entries, estimator state) route by
  ``ring(key)`` — the content-addressed ``blob:<sha256>`` and
  ``function_digest:<sha256>`` namespaces therefore shard by digest for
  free, since the digest IS the key;
- the live-task index (``tasks:index``) routes by FIELD (the task id):
  each shard carries the index slice for its own tasks, which is what
  scopes a dispatcher's stranded-task rescan to its owned shards;
- the fleet coordination hashes (``fleet:health``, ``dispatchers:alive``,
  ``fleet:lease_conf``) are BROADCAST on write and MERGED on read: a
  dispatcher's ~1 Hz capacity snapshot lands on every shard, and a
  gateway's admission refresh reads all shards and keeps the freshest
  copy per field — any single surviving shard can answer the aggregation;
- announce/result publishes route by the task id embedded in the payload
  (control prefixes like ``!cancel:`` stripped first), so a shard's
  announce bus carries exactly its own tasks' traffic.

Ownership: ``owned_shards`` scopes the *consumption* surface — announce
subscriptions, ``keys()``, the live-index scan, and announce replay — to
a dispatcher's slice while every shard stays reachable for writes (graph
edges, reclaims, fleet hashes). ``None`` (the gateway default) means all
shards: gateways are fully stateless over the ring and any of them can
route any task's ``/result`` or ``/trace``.

Per-shard failover composes with store HA (store/replication.py): each
"shard" may itself be a multi-endpoint failover ring
(``resp://p1:6380,r1:6480;p2:6381,r2:6481`` = two shards, each a
primary+replica pair), and ``failover_generation`` sums the shards' so a
dispatcher's re-arm triggers when ANY of its shards promotes.
"""

from __future__ import annotations

import hashlib
import threading
import time
import weakref
from bisect import bisect_right
from collections import OrderedDict
from concurrent.futures import ThreadPoolExecutor
from typing import Callable, Mapping

from tpu_faas.obs import REGISTRY
from tpu_faas.store.base import (
    BLOBREQ_ANNOUNCE_PREFIX,
    CANCEL_ANNOUNCE_PREFIX,
    DISPATCHERS_KEY,
    KILL_ANNOUNCE_PREFIX,
    LEASE_CONF_KEY,
    LIVE_INDEX_KEY,
    RESULT_DIGEST_PREFIX,
    RESULT_INLINE_PREFIX,
    TASKS_CHANNEL,
    TENANT_CONF_KEY,
    Subscription,
    TaskStore,
    decode_result_announce,
)

#: Fleet coordination hashes: broadcast writes, merged reads (see module
#: docstring). "fleet:health" is admission/signal.FLEET_HEALTH_KEY —
#: spelled literally here so the store layer does not import the
#: admission package. The tenant-conf hash rides the stamp-tail
#: freshest-wins merge (its values are "<spec>:<epoch>").
FLEET_KEYS = frozenset(
    {"fleet:health", DISPATCHERS_KEY, LEASE_CONF_KEY, TENANT_CONF_KEY}
)

#: Per-shard round trips, summed over this process's sharded clients.
#: A separate family from tpu_faas_store_round_trips_total{backend=}
#: (one exposition family cannot carry two label vocabularies): the
#: un-labeled total keeps counting every trip, this one attributes them.
_SHARD_ROUND_TRIPS = REGISTRY.counter(
    "tpu_faas_store_shard_round_trips_total",
    "Store wire round trips by shard (pipelined batch = 1), summed over "
    "this process's sharded store clients",
    ("shard",),
)
_SHARD_FAILOVERS = REGISTRY.counter(
    "tpu_faas_store_shard_failovers_total",
    "Store endpoint failovers by shard (reconnects that settled on a "
    "different endpoint of that shard's failover ring)",
    ("shard",),
)


def _hash64(data: str) -> int:
    """Stable 64-bit key hash — blake2b, NOT Python's randomized hash():
    every process in the fleet must place every key identically."""
    return int.from_bytes(
        hashlib.blake2b(data.encode("utf-8"), digest_size=8).digest(), "big"
    )


class HashRing:
    """Consistent-hash ring over ``n_shards`` with ``vnodes`` virtual
    nodes per shard. Deterministic across processes and runs; adding or
    removing one shard re-homes ~1/N of keys (property-tested)."""

    def __init__(self, n_shards: int, vnodes: int = 64) -> None:
        if n_shards < 1:
            raise ValueError("a ring needs at least one shard")
        self.n_shards = n_shards
        self.vnodes = vnodes
        points: list[tuple[int, int]] = []
        for shard in range(n_shards):
            for v in range(vnodes):
                points.append((_hash64(f"shard-{shard}#{v}"), shard))
        points.sort()
        self._hashes = [h for h, _ in points]
        self._shards = [s for _, s in points]

    def shard_of(self, key: str) -> int:
        """The shard owning ``key``: first ring point at or after the
        key's hash, wrapping at the top."""
        if self.n_shards == 1:
            return 0
        idx = bisect_right(self._hashes, _hash64(key))
        if idx == len(self._hashes):
            idx = 0
        return self._shards[idx]


class _FanSubscription(Subscription):
    """One logical subscription over several shards' buses: drains each
    shard's subscription round-robin. Non-blocking drains (timeout 0, the
    dispatcher tick pattern) cost one empty poll per shard; a blocking
    drain sleeps in small slices between sweeps (bounded added latency,
    default 5 ms — well under the transport floor)."""

    _SWEEP_SLEEP = 0.005

    def __init__(self, subs: list[Subscription]) -> None:
        self._subs = subs
        self._next = 0

    def get_message(self, timeout: float = 0.0) -> str | None:
        deadline = (
            time.monotonic() + timeout if timeout > 0 else None
        )
        while True:
            for _ in range(len(self._subs)):
                sub = self._subs[self._next]
                self._next = (self._next + 1) % len(self._subs)
                msg = sub.get_message(0.0)
                if msg is not None:
                    return msg
            if deadline is None:
                return None
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return None
            time.sleep(min(self._SWEEP_SLEEP, remaining))

    def pollable_fds(self) -> list[int]:
        """Every shard subscription's readability fd (event-driven serve
        loops register them all; any shard's publish wakes the poll)."""
        fds: list[int] = []
        for sub in self._subs:
            fds.extend(sub.pollable_fds())
        return fds

    def close(self) -> None:
        for sub in self._subs:
            sub.close()


def _trailing_float(raw: str) -> float | None:
    """The float encoded at the tail of a fleet-hash value (capacity
    snapshots end ``:<published_at>``, liveness stamps ARE a float)."""
    try:
        return float(raw.rsplit(":", 1)[-1])
    except (ValueError, IndexError):
        return None


class ShardedStore(TaskStore):
    """TaskStore over N backend shards (see module docstring)."""

    def __init__(
        self,
        stores: list[TaskStore],
        owned_shards: list[int] | None = None,
        ring: HashRing | None = None,
    ) -> None:
        if not stores:
            raise ValueError("ShardedStore needs at least one backend")
        self._stores = list(stores)
        self.ring = ring if ring is not None else HashRing(len(stores))
        if self.ring.n_shards != len(stores):
            raise ValueError(
                f"ring has {self.ring.n_shards} shards, got "
                f"{len(stores)} stores"
            )
        self.owned_shards: list[int] | None = None
        if owned_shards is not None:
            owned = sorted(set(int(i) for i in owned_shards))
            bad = [i for i in owned if not 0 <= i < len(stores)]
            if bad or not owned:
                raise ValueError(
                    f"owned_shards {owned_shards!r} out of range for "
                    f"{len(stores)} shards"
                )
            self.owned_shards = owned
        self._closed = False
        # one fan-out lane per shard: concurrent sub-batches are the
        # whole point (a 4-shard batch pays ~one shard's latency); extra
        # callers queue, which only serializes across caller threads
        self._pool = ThreadPoolExecutor(
            max_workers=min(32, 2 * len(stores)),
            thread_name_prefix="shard-fan",
        )
        # replay cursor table: the dispatcher's single announce-offset int
        # becomes an opaque handle mapping to per-shard ring offsets
        self._cursor_lock = threading.Lock()
        self._cursor_seq = 0
        self._replay_cursors: OrderedDict[int, list[int]] = OrderedDict()
        # per-shard scrape series (process-global registry): deltas of
        # each shard handle's counters folded in at collect time
        self._metrics_lock = threading.Lock()
        self._rt_seen = [0] * len(stores)
        self._fo_seen = [
            getattr(s, "failover_generation", 0) for s in stores
        ]
        self._rt_series = [
            _SHARD_ROUND_TRIPS.labels(shard=str(i))
            for i in range(len(stores))
        ]
        self._fo_series = [
            _SHARD_FAILOVERS.labels(shard=str(i))
            for i in range(len(stores))
        ]
        ref = weakref.ref(self)

        def _collect() -> None:
            live = ref()
            if live is not None and not live._closed:
                live._collect_shard_metrics()

        self._collector = _collect
        REGISTRY.register_collector(_collect)

    # -- topology ----------------------------------------------------------
    @property
    def shard_count(self) -> int:
        return len(self._stores)

    def shard_of(self, task_id: str) -> int:
        """The shard owning a task id (or any plain key)."""
        return self.ring.shard_of(task_id)

    def shard_store(self, index: int) -> TaskStore:
        """The backend handle of one shard (operator/bench surface —
        e.g. promoting one shard's replica)."""
        return self._stores[index]

    def _scope(self) -> list[int]:
        """Shard indices this handle CONSUMES from (subscription, keys,
        live-index scans, replay): the owned slice, or every shard."""
        if self.owned_shards is not None:
            return self.owned_shards
        return list(range(len(self._stores)))

    def shard_failover_generations(self) -> list[int]:
        """Per-shard failover generations (operator/stats surface)."""
        return [
            getattr(s, "failover_generation", 0) for s in self._stores
        ]

    def _collect_shard_metrics(self) -> None:
        with self._metrics_lock:
            for i, s in enumerate(self._stores):
                rt = s.n_round_trips
                if rt > self._rt_seen[i]:
                    self._rt_series[i].inc(rt - self._rt_seen[i])
                    self._rt_seen[i] = rt
                gen = getattr(s, "failover_generation", 0)
                if gen > self._fo_seen[i]:
                    self._fo_series[i].inc(gen - self._fo_seen[i])
                    self._fo_seen[i] = gen

    # -- fan-out machinery -------------------------------------------------
    def _fan(self, calls: dict[int, Callable]) -> dict[int, object]:
        """Run one thunk per shard, concurrently when more than one shard
        is involved. Raises the first failure (by shard order) AFTER every
        thunk finished — a partial fan-out is the same ambiguity as a
        mid-pipeline connection loss, and every caller of the batch forms
        already treats it as an outage (park + replay, idempotent)."""
        if len(calls) == 1:
            (idx, fn), = calls.items()
            return {idx: fn()}
        futures = {
            idx: self._pool.submit(fn) for idx, fn in calls.items()
        }
        out: dict[int, object] = {}
        first_exc: BaseException | None = None
        for idx in sorted(futures):
            try:
                out[idx] = futures[idx].result()
            except BaseException as exc:  # noqa: BLE001 — re-raised below
                if first_exc is None:
                    first_exc = exc
        if first_exc is not None:
            raise first_exc
        return out

    def _partition(self, indexed_items) -> dict[int, list]:
        """(shard, payload) pairs -> shard -> payload list, input order
        preserved within each shard."""
        by_shard: dict[int, list] = {}
        for shard, payload in indexed_items:
            by_shard.setdefault(shard, []).append(payload)
        return by_shard

    # -- payload routing ---------------------------------------------------
    @staticmethod
    def _payload_task_id(payload: str) -> str:
        """The task id embedded in an announce payload (control prefixes
        stripped, express inline result frames decoded) — what publishes
        route by."""
        for prefix in (
            CANCEL_ANNOUNCE_PREFIX,
            KILL_ANNOUNCE_PREFIX,
            BLOBREQ_ANNOUNCE_PREFIX,  # routes by digest, like the blob
        ):
            if payload.startswith(prefix):
                return payload[len(prefix):]
        if payload.startswith(RESULT_INLINE_PREFIX) or payload.startswith(
            RESULT_DIGEST_PREFIX
        ):
            return decode_result_announce(payload)[0]
        return payload

    def _merge_fleet_values(self, key: str, a: str, b: str) -> str:
        """Pick between two shards' copies of one fleet-hash field.
        Liveness/capacity stamps keep the FRESHEST copy (max trailing
        float); the lease-config hash keeps the EARLIEST (its setnx pins
        first-publication time, which gates the adoption grace window)."""
        fa, fb = _trailing_float(a), _trailing_float(b)
        if fa is None:
            return b
        if fb is None:
            return a
        if key == LEASE_CONF_KEY:
            return a if fa <= fb else b
        return a if fa >= fb else b

    # -- raw hash ops ------------------------------------------------------
    def hset(self, key: str, fields: Mapping[str, str]) -> None:
        if key in FLEET_KEYS:
            self._fan(
                {
                    i: (lambda s=s: s.hset(key, fields))
                    for i, s in enumerate(self._stores)
                }
            )
            return
        if key == LIVE_INDEX_KEY:
            by_shard = self._partition(
                (self.ring.shard_of(f), (f, v)) for f, v in fields.items()
            )
            self._fan(
                {
                    i: (
                        lambda i=i, kv=kv: self._stores[i].hset(
                            key, dict(kv)
                        )
                    )
                    for i, kv in by_shard.items()
                }
            )
            return
        self._stores[self.ring.shard_of(key)].hset(key, fields)

    def hget(self, key: str, field: str) -> str | None:
        if key in FLEET_KEYS:
            best: str | None = None
            for got in self._fan(
                {
                    i: (lambda s=s: s.hget(key, field))
                    for i, s in enumerate(self._stores)
                }
            ).values():
                if got is None:
                    continue
                best = (
                    got
                    if best is None
                    else self._merge_fleet_values(key, best, got)
                )
            return best
        if key == LIVE_INDEX_KEY:
            return self._stores[self.ring.shard_of(field)].hget(key, field)
        return self._stores[self.ring.shard_of(key)].hget(key, field)

    def hgetall(self, key: str) -> dict[str, str]:
        if key in FLEET_KEYS:
            merged: dict[str, str] = {}
            for got in self._fan(
                {
                    i: (lambda s=s: s.hgetall(key))
                    for i, s in enumerate(self._stores)
                }
            ).values():
                for f, v in got.items():
                    if f in merged:
                        merged[f] = self._merge_fleet_values(
                            key, merged[f], v
                        )
                    else:
                        merged[f] = v
            return merged
        if key == LIVE_INDEX_KEY:
            # the consumption scope: a dispatcher's rescan walks only its
            # owned shards' index slices; a gateway (owned=None) counts
            # the whole fleet's live tasks
            merged = {}
            for got in self._fan(
                {
                    i: (lambda i=i: self._stores[i].hgetall(key))
                    for i in self._scope()
                }
            ).values():
                merged.update(got)
            return merged
        return self._stores[self.ring.shard_of(key)].hgetall(key)

    def hmget(self, key: str, fields: list[str]) -> list[str | None]:
        return self._stores[self.ring.shard_of(key)].hmget(key, fields)

    def hexists(self, key: str, field: str) -> bool:
        if key in FLEET_KEYS:
            return self.hget(key, field) is not None
        if key == LIVE_INDEX_KEY:
            return self._stores[self.ring.shard_of(field)].hexists(
                key, field
            )
        return self._stores[self.ring.shard_of(key)].hexists(key, field)

    def hdel(self, key: str, *fields: str) -> None:
        if not fields:
            return
        if key in FLEET_KEYS:
            # broadcast: GC of an ancient snapshot must reach every
            # shard's copy, including shards the deleting reader's
            # publisher never wrote
            self._fan(
                {
                    i: (lambda s=s: s.hdel(key, *fields))
                    for i, s in enumerate(self._stores)
                }
            )
            return
        if key == LIVE_INDEX_KEY:
            by_shard = self._partition(
                (self.ring.shard_of(f), f) for f in fields
            )
            self._fan(
                {
                    i: (
                        lambda i=i, fs=fs: self._stores[i].hdel(key, *fs)
                    )
                    for i, fs in by_shard.items()
                }
            )
            return
        self._stores[self.ring.shard_of(key)].hdel(key, *fields)

    def delete(self, key: str) -> None:
        if key in FLEET_KEYS or key == LIVE_INDEX_KEY:
            self._fan(
                {
                    i: (lambda s=s: s.delete(key))
                    for i, s in enumerate(self._stores)
                }
            )
            return
        self._stores[self.ring.shard_of(key)].delete(key)

    def hincrby(self, key: str, field: str, delta: int) -> int:
        return self._stores[self.ring.shard_of(key)].hincrby(
            key, field, delta
        )

    def setnx_field(
        self, key: str, field: str, value: str
    ) -> tuple[bool, str]:
        if key in FLEET_KEYS:
            created_any = False
            best: str | None = None
            for created, current in self._fan(
                {
                    i: (lambda s=s: s.setnx_field(key, field, value))
                    for i, s in enumerate(self._stores)
                }
            ).values():
                created_any = created_any or created
                best = (
                    current
                    if best is None
                    else self._merge_fleet_values(key, best, current)
                )
            return created_any, best if best is not None else value
        return self._stores[self.ring.shard_of(key)].setnx_field(
            key, field, value
        )

    def keys(self) -> list[str]:
        out: list[str] = []
        for got in self._fan(
            {
                i: (lambda i=i: self._stores[i].keys())
                for i in self._scope()
            }
        ).values():
            out.extend(got)
        return out

    # -- pipelined batch forms (partition + concurrent fan-out) ------------
    def _fan_indexed(self, items, shard_of_item, call):
        """Generic ordered batch fan-out: partition ``items`` by
        ``shard_of_item``, run ``call(shard_store, sub_items)`` per shard
        concurrently, and scatter per-shard reply lists back to the
        original item order."""
        items = list(items)
        if not items:
            return []
        by_shard: dict[int, list[tuple[int, object]]] = {}
        for pos, item in enumerate(items):
            by_shard.setdefault(shard_of_item(item), []).append(
                (pos, item)
            )
        replies = self._fan(
            {
                i: (
                    lambda i=i, sub=sub: call(
                        self._stores[i], [it for _pos, it in sub]
                    )
                )
                for i, sub in by_shard.items()
            }
        )
        out = [None] * len(items)
        for i, sub in by_shard.items():
            got = replies[i]
            if got is None:
                continue
            for (pos, _item), value in zip(sub, got):
                out[pos] = value
        return out

    def hget_many(self, keys: list[str], field: str):
        return self._fan_indexed(
            keys,
            self.ring.shard_of,
            lambda s, sub: s.hget_many(sub, field),
        )

    def hgetall_many(self, keys: list[str]):
        return self._fan_indexed(
            keys, self.ring.shard_of, lambda s, sub: s.hgetall_many(sub)
        )

    def hset_many(self, items) -> None:
        plain: list[tuple[str, Mapping[str, str]]] = []
        for key, fields in items:
            if key in FLEET_KEYS:
                # e.g. the shared-mode liveness heartbeat riding the lease
                # renewal round: broadcast like the single-key form
                self.hset(key, fields)
            elif key == LIVE_INDEX_KEY:
                self.hset(key, fields)
            else:
                plain.append((key, fields))
        if not plain:
            return
        self._fan_indexed(
            plain,
            lambda item: self.ring.shard_of(item[0]),
            lambda s, sub: s.hset_many(sub) or [None] * len(sub),
        )

    def setnx_fields(self, items, field: str):
        return self._fan_indexed(
            items,
            lambda item: self.ring.shard_of(item[0]),
            lambda s, sub: s.setnx_fields(sub, field),
        )

    def hsetnx_many(self, items) -> list[bool]:
        return self._fan_indexed(
            items,
            lambda item: self.ring.shard_of(item[0]),
            lambda s, sub: s.hsetnx_many(sub),
        )

    def hincrby_many(self, items) -> list[int]:
        return self._fan_indexed(
            items,
            lambda item: self.ring.shard_of(item[0]),
            lambda s, sub: s.hincrby_many(sub),
        )

    def delete_many(self, keys: list[str]) -> None:
        self._fan_indexed(
            keys,
            self.ring.shard_of,
            lambda s, sub: s.delete_many(sub) or [None] * len(sub),
        )

    def set_status_many(self, status, items) -> None:
        self._fan_indexed(
            items,
            lambda item: self.ring.shard_of(item[0]),
            lambda s, sub: s.set_status_many(status, sub)
            or [None] * len(sub),
        )

    def finish_task(
        self, task_id, status, result, first_wins=False, inline_max=0,
        result_digest=None, result_size=0,
    ):
        # wholesale delegation: the shard client's pipelined form (write +
        # index drop + announce in one round) — index and announce both
        # live on the task's own shard by construction. The digest form
        # rides along untouched: the task record (and its digest FIELDS)
        # route by task id, while the blob BODY the digest names routes by
        # digest (put_blob/get_blob below) — by design on different shards
        # for unrelated keys.
        self._stores[self.ring.shard_of(task_id)].finish_task(
            task_id, status, result,
            first_wins=first_wins, inline_max=inline_max,
            result_digest=result_digest, result_size=result_size,
        )

    def finish_task_many(self, items, inline_max: int = 0) -> None:
        # same-id items stay in one shard's sub-batch in input order, so
        # intra-batch first_wins semantics survive the partition
        self._fan_indexed(
            items,
            lambda item: self.ring.shard_of(item[0]),
            lambda s, sub: s.finish_task_many(sub, inline_max=inline_max)
            or [None] * len(sub),
        )

    def create_tasks(self, tasks, channel=TASKS_CHANNEL, **kw) -> None:
        self._fan_indexed(
            tasks,
            lambda t: self.ring.shard_of(t[0]),
            lambda s, sub: s.create_tasks(sub, channel, **kw)
            or [None] * len(sub),
        )

    def create_tasks_if_absent(self, tasks, channel=TASKS_CHANNEL):
        return self._fan_indexed(
            tasks,
            lambda t: self.ring.shard_of(t[0]),
            lambda s, sub: s.create_tasks_if_absent(sub, channel),
        )

    # -- content-addressed blobs (shard by digest: it IS the key) ----------
    def put_blob(self, digest: str, data: str) -> bool:
        from tpu_faas.store.base import blob_key

        return self._stores[self.ring.shard_of(blob_key(digest))].put_blob(
            digest, data
        )

    def get_blob(self, digest: str) -> str | None:
        from tpu_faas.store.base import blob_key

        return self._stores[self.ring.shard_of(blob_key(digest))].get_blob(
            digest
        )

    def get_blobs(self, digests: list[str]):
        from tpu_faas.store.base import blob_key

        return self._fan_indexed(
            digests,
            lambda d: self.ring.shard_of(blob_key(d)),
            lambda s, sub: s.get_blobs(sub),
        )

    # -- announce bus ------------------------------------------------------
    def publish(self, channel: str, payload: str) -> None:
        shard = self.ring.shard_of(self._payload_task_id(payload))
        self._stores[shard].publish(channel, payload)

    def publish_many(self, channel: str, payloads: list[str]) -> None:
        self._fan_indexed(
            payloads,
            lambda p: self.ring.shard_of(self._payload_task_id(p)),
            lambda s, sub: s.publish_many(channel, sub)
            or [None] * len(sub),
        )

    def subscribe(self, channel: str) -> Subscription:
        scope = self._scope()
        subs: list[Subscription] = []
        try:
            for i in scope:
                subs.append(self._stores[i].subscribe(channel))
        except BaseException:
            for sub in subs:
                sub.close()
            raise
        if len(subs) == 1:
            return subs[0]
        return _FanSubscription(subs)

    # -- failover / announce replay ---------------------------------------
    @property
    def failover_generation(self) -> int:
        """Sum of the shards' generations: any shard promoting bumps it,
        which is exactly the dispatcher re-arm trigger."""
        return sum(
            getattr(s, "failover_generation", 0) for s in self._stores
        )

    def replay_announces(self, after: int):
        """Sharded announce replay. The returned "tail offset" is an
        opaque cursor HANDLE mapping to per-shard ring offsets (the
        dispatcher stores one int and hands it back — the contract is
        monotone-int-shaped, not arithmetic). ``after=-1`` primes every
        consumed shard's tail; an unknown handle (e.g. the 0 the
        dispatcher falls back to after a priming outage) replays each
        shard's whole bounded ring — exactly the single-store fallback
        semantics, deduped at intake."""
        scope = self._scope()
        with self._cursor_lock:
            base = self._replay_cursors.get(after)
            per_shard = (
                list(base)
                if base is not None
                else [0] * len(self._stores)
            )
        tails = list(per_shard)
        entries: list[tuple[str, str]] = []
        if after == -1:
            got = self._fan(
                {
                    i: (lambda i=i: self._stores[i].replay_announces(-1))
                    for i in scope
                }
            )
            for i in scope:
                tails[i] = got[i][0]
        else:
            got = self._fan(
                {
                    i: (
                        lambda i=i: self._stores[i].replay_announces(
                            per_shard[i]
                        )
                    )
                    for i in scope
                }
            )
            for i in scope:
                tail_i, entries_i = got[i]
                tails[i] = tail_i
                entries.extend(entries_i)
        with self._cursor_lock:
            self._cursor_seq += 1
            handle = self._cursor_seq
            self._replay_cursors[handle] = tails
            while len(self._replay_cursors) > 8:
                self._replay_cursors.popitem(last=False)
        return handle, entries

    def rotate_endpoint(self) -> bool:
        """Advance every multi-endpoint shard's failover ring (the
        breaker's half-open hook). True when any shard could rotate."""
        rotated = False
        for s in self._stores:
            fn = getattr(s, "rotate_endpoint", None)
            if fn is not None and fn():
                rotated = True
        return rotated

    @property
    def endpoints(self):
        """The deepest shard's failover ring — what sizes the breaker's
        rotation budget (rotations before a fresh open window must cover
        one full walk of the worst shard's ring)."""
        best: list | None = None
        for s in self._stores:
            eps = getattr(s, "endpoints", None)
            if eps and (best is None or len(eps) > len(best)):
                best = eps
        return best

    def info(self) -> dict:
        """Aggregated HA introspection: worst-case ``role`` (every shard
        must be a writable primary for the fleet to be primary), max
        ``repl_lag``, plus per-shard roles for operators."""
        roles: list[str] = []
        lag = 0.0
        have_lag = False
        for i, s in enumerate(self._stores):
            fn = getattr(s, "info", None)
            info = fn() if fn is not None else {}
            roles.append(str(info.get("role", "primary")))
            try:
                lag = max(lag, float(info["repl_lag"]))
                have_lag = True
            except (KeyError, ValueError, TypeError):
                pass
        role = "primary"
        for r in roles:
            if r != "primary":
                role = r
                break
        out = {
            "role": role,
            "shards": str(len(self._stores)),
            "shard_roles": ",".join(roles),
        }
        if have_lag:
            out["repl_lag"] = repr(lag)
        return out

    # -- instrumentation ---------------------------------------------------
    @property
    def n_round_trips(self) -> int:
        return sum(s.n_round_trips for s in self._stores)

    @property
    def n_bytes_sent(self) -> int:
        return sum(
            getattr(s, "n_bytes_sent", 0) for s in self._stores
        )

    # -- admin -------------------------------------------------------------
    def flush(self) -> None:
        self._fan(
            {i: s.flush for i, s in enumerate(self._stores)}
        )

    def ping(self) -> bool:
        return all(
            self._fan(
                {i: s.ping for i, s in enumerate(self._stores)}
            ).values()
        )

    def save(self, path: str | None = None) -> None:
        """``path=None`` checkpoints every shard to its own configured
        target; an explicit path fans out to ``<path>.shard<i>`` files
        (one file cannot hold N shards' logs)."""
        self._fan(
            {
                i: (
                    lambda i=i: self._stores[i].save(
                        None if path is None else f"{path}.shard{i}"
                    )
                )
                for i in range(len(self._stores))
            }
        )

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        # drop the registry hook (the weakref guard alone would leave one
        # dead closure per closed instance iterating on every render)
        REGISTRY.unregister_collector(self._collector)
        for s in self._stores:
            try:
                s.close()
            except Exception:
                pass
        self._pool.shutdown(wait=False)
