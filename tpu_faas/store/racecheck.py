"""Protocol race detector for the task lifecycle.

The reference has no race detection; its safety is "by construction" —
single-threaded event loops plus message passing (SURVEY §5.2). That argument
broke the moment this framework added what the reference lacks: re-dispatch of
in-flight tasks from purged workers. Now two agents can race on one task
record (the zombie worker's late result vs the replacement's result), and the
gateway and dispatcher write the same hashes from different processes.

This module makes the implicit protocol checkable:

- :class:`RaceMonitor` owns the task-lifecycle state machine
  (QUEUED -> RUNNING -> COMPLETED | FAILED) plus the re-dispatch extension
  (RUNNING -> RUNNING is legal only when declared), validates every observed
  write online, and collects :class:`Violation` records instead of raising —
  a detector, not an enforcer.
- :class:`RaceCheckStore` wraps any :class:`TaskStore` and feeds every write
  through a shared monitor. Wrap each agent's handle with its own ``actor``
  label and violations name who raced with whom.
- :func:`check_trace` replays a recorded event list through a fresh monitor
  for offline/post-mortem analysis.

Used by the test suite (wrap the store, run a full E2E stack, assert no
errors) and available in production at ~one dict update per store write.
"""

from __future__ import annotations

import itertools
import threading
import time
from collections import deque
from dataclasses import dataclass, field
from typing import Iterable, Mapping

from tpu_faas.admission.signal import FLEET_HEALTH_KEY
from tpu_faas.core.payload import payload_digest
from tpu_faas.core.task import FIELD_RESULT, FIELD_STATUS, TaskStatus
from tpu_faas.obs.tracectx import TRACE_PREFIX
from tpu_faas.store.base import (
    BLOB_DATA_FIELD,
    BLOB_PREFIX,
    LIVE_INDEX_KEY,
    TASKS_CHANNEL,
    Subscription,
    TaskStore,
)

#: Legal status transitions. ``None`` is "task does not exist yet".
#: RUNNING -> RUNNING appears here because re-dispatch re-marks a task on its
#: replacement worker; the monitor still flags it unless the dispatcher
#: declared the re-dispatch (see RaceMonitor.expect_redispatch).
_LEGAL: frozenset[tuple[str | None, str]] = frozenset(
    {
        (None, "QUEUED"),
        ("QUEUED", "QUEUED"),  # idempotent gateway retry
        ("QUEUED", "RUNNING"),
        ("RUNNING", "RUNNING"),
        ("RUNNING", "COMPLETED"),
        ("RUNNING", "FAILED"),
        # QUEUED -> terminal: legal but suspicious (result without dispatch);
        # reported as a warning, see _transition_kind.
        ("QUEUED", "COMPLETED"),
        ("QUEUED", "FAILED"),
        # queued-only cancellation (gateway POST /cancel, store cancel_task)
        ("QUEUED", "CANCELLED"),
        # the cancel's conditional write racing a concurrent RUNNING mark:
        # lawful per the protocol (the task runs; its result overwrites the
        # stale CANCELLED later) but worth surfacing — warning, see
        # _check_transition
        ("RUNNING", "CANCELLED"),
        # queue-deadline shedding (store expire_task, dispatcher-side):
        # deliberately from QUEUED ONLY — a RUNNING -> EXPIRED write is an
        # illegal-transition error, which is how the monitor proves "shed
        # never touches a dispatched task" at runtime
        ("QUEUED", "EXPIRED"),
        # -- task graphs (tpu_faas/graph, store complete_dep_many) ---------
        # a graph node created behind its dependencies (gateway
        # /execute_graph); deliberately NO ("WAITING", "RUNNING") entry —
        # that transition being illegal is how the monitor proves at
        # runtime that no WAITING node ever reaches a worker
        (None, "WAITING"),
        # promotion: the last parent COMPLETED and the pending count hit
        # zero (single writer by the FIELD_DEP_RESOLVED claim)
        ("WAITING", "QUEUED"),
        # poison: a parent reached FAILED/EXPIRED/CANCELLED, so the node
        # (and transitively its own frontier) fails without dispatching
        ("WAITING", "FAILED"),
    }
)

#: Terminal statuses that assert the task NEVER RAN. A write of one over
#: the other (cancel racing a deadline shed) is a warning, not an error:
#: both agree on the only fact a client can act on.
_NEVER_RAN = frozenset({"CANCELLED", "EXPIRED"})


@dataclass(frozen=True)
class Event:
    """One observed store write, in global observation order."""

    seq: int
    time: float
    actor: str
    op: str  # create | status | finish | delete | flush
    task_id: str
    from_status: str | None
    #: status carried by this write; None means the write had no status field
    to_status: str | None
    #: result payload accompanying a terminal write (None otherwise)
    result: str | None = None


@dataclass(frozen=True)
class Violation:
    kind: str
    severity: str  # "error" | "warning"
    task_id: str
    detail: str
    events: tuple[Event, ...] = ()

    def __str__(self) -> str:
        return f"[{self.severity}] {self.kind} on {self.task_id}: {self.detail}"


def _is_terminal(status: str | None) -> bool:
    """Terminal check that tolerates non-enum garbage: the monitor is a
    detector, not an enforcer — a corrupt status string must be FLAGGED
    (illegal-transition fires via the _LEGAL table), never crash observe().
    unknown=False: garbage is 'not terminal' here so the transition table
    gets to see and flag it."""
    return TaskStatus.terminal_str(status)


@dataclass
class _TaskState:
    status: str | None = None
    result: str | None = None
    last_writer: str = "?"
    last_event: Event | None = None
    redispatch_credits: int = 0
    #: a force-cancel (!kill) was requested for this task — a worker's
    #: result-bearing CANCELLED write is lawful only with this set
    kill_requested: bool = False
    #: a hedge replica was declared (speculation plane): the loser's
    #: CANCELLED-after-winner-terminal write is the expected kill
    #: confirmation (warning with hedge attribution, never an error);
    #: double-COMPLETION with a different result stays a terminal-
    #: overwrite ERROR — that is what "first-wins held" means at runtime
    replica_declared: bool = False


class RaceMonitor:
    """Thread-safe online checker of the task-lifecycle protocol.

    Error kinds
    -----------
    - ``terminal-overwrite`` — a write changed a terminal status or replaced
      a terminal result with a different value (the zombie-vs-replacement
      race; ``finish_task(first_wins=True)`` exists to prevent exactly this).
    - ``illegal-transition`` — any transition outside the state machine
      (e.g. COMPLETED -> RUNNING).

    Warning kinds
    -------------
    - ``double-dispatch`` — RUNNING -> RUNNING without a declared re-dispatch:
      two workers may hold the same task.
    - ``result-without-dispatch`` — terminal write on a task never marked
      RUNNING.
    - ``unknown-task`` — write to a task id with no observed create (only
      with ``strict=True``; otherwise the task is adopted silently, since a
      checker attached mid-run legitimately misses earlier creates).
    - ``cancel-after-dispatch`` / ``cancel-after-finish`` /
      ``late-cancel-race`` — the lawful interleavings of the queued-only
      cancel's conditional write racing a concurrent dispatch
      (store/base.py cancel_task): CANCELLED lands over RUNNING, CANCELLED
      transiently clobbers a just-landed terminal record (repaired from
      the final_status stamp), and the true status lands over the stale
      CANCELLED.
    """

    def __init__(self, *, strict: bool = False, max_events: int = 100_000) -> None:
        self._lock = threading.Lock()
        self._seq = itertools.count()
        self._tasks: dict[str, _TaskState] = {}
        #: blob namespace state: digest -> sha256 fingerprint of the FIRST
        #: observed data write (fingerprint, not the bytes: payloads can
        #: be multi-MB and the monitor must stay cheap)
        self._blobs: dict[str, str] = {}
        self._strict = strict
        self.events: deque[Event] = deque(maxlen=max_events)
        self.violations: list[Violation] = []

    # -- blob namespace (payload plane) ------------------------------------
    def observe_blob_write(self, actor: str, key: str, data: str) -> None:
        """Validate a write touching a blob's data field. Two invariants,
        both errors when broken:

        - ``blob-digest-mismatch`` — the bytes do not hash to the key's
          digest: a consumer resolving this digest would execute the
          wrong function (content addressing's one load-bearing promise);
        - ``blob-overwrite`` — a second data write carries DIFFERENT
          bytes than the first: blobs are create-once, and put_blob's
          setnx makes this impossible through the API — seeing it means
          some writer bypassed it.
        """
        digest = key[len(BLOB_PREFIX):]
        fp = payload_digest(data)
        with self._lock:
            if fp != digest:
                self._flag(
                    "blob-digest-mismatch",
                    "error",
                    key,
                    f"{actor} wrote bytes hashing to {fp[:16]}... under "
                    f"digest {digest[:16]}...: resolvers of this digest "
                    f"would run the wrong function",
                )
            prev = self._blobs.setdefault(digest, fp)
            if prev != fp:
                self._flag(
                    "blob-overwrite",
                    "error",
                    key,
                    f"{actor} rewrote blob {digest[:16]}... with "
                    f"different bytes (blobs are create-once; put_blob's "
                    f"setnx was bypassed)",
                )

    # -- declarations ------------------------------------------------------
    def expect_force_cancel(self, task_id: str) -> None:
        """Declare a force-cancel request: the worker's eventual
        result-bearing CANCELLED write for this task is lawful. Fed by
        RaceCheckStore.request_kill."""
        with self._lock:
            self._state(task_id).kill_requested = True

    def expect_redispatch(self, task_id: str) -> None:
        """Declare that the next RUNNING -> RUNNING write on ``task_id`` is a
        deliberate re-dispatch (purged worker's task moved to a replacement),
        not a double-dispatch bug."""
        with self._lock:
            self._state(task_id).redispatch_credits += 1

    def expect_replica(self, task_id: str) -> None:
        """Declare a hedge replica (speculation plane, tpu_faas/spec): the
        next RUNNING -> RUNNING write is the replica's deliberate dispatch
        beside a still-running original (one redispatch credit), and the
        eventual loser's CANCELLED write over the winner's terminal record
        is the kill confirmation (hedge-loser warning, not an error). The
        monitor still proves no double-COMPLETION: a second terminal write
        carrying a DIFFERENT result stays a terminal-overwrite error —
        first_wins at the store is what keeps it from ever appearing."""
        with self._lock:
            state = self._state(task_id)
            state.redispatch_credits += 1
            state.replica_declared = True

    # -- observation -------------------------------------------------------
    def observe(
        self,
        actor: str,
        op: str,
        task_id: str,
        fields: Mapping[str, str] | None = None,
    ) -> Event:
        """Record one store write and validate it. Returns the event."""
        fields = fields or {}
        with self._lock:
            state = self._tasks.get(task_id)
            if state is None:
                if self._strict and op not in ("create", "flush"):
                    self._flag(
                        "unknown-task",
                        "warning",
                        task_id,
                        f"{actor} wrote {op} to a task never created",
                    )
                state = self._state(task_id)

            event = Event(
                seq=next(self._seq),
                time=time.time(),
                actor=actor,
                op=op,
                task_id=task_id,
                from_status=state.status,
                to_status=fields.get(FIELD_STATUS),
                result=fields.get(FIELD_RESULT),
            )
            self.events.append(event)

            if op == "delete":
                self._tasks.pop(task_id, None)
                return event
            if event.to_status is not None:
                self._check_transition(state, event)
                state.status = event.to_status
            if event.result is not None:
                state.result = event.result
            state.last_writer = actor
            state.last_event = event
            return event

    def observe_flush(self, actor: str) -> None:
        with self._lock:
            self.events.append(
                Event(next(self._seq), time.time(), actor, "flush", "*", None, None)
            )
            self._tasks.clear()
            self._blobs.clear()

    # -- queries -----------------------------------------------------------
    @property
    def errors(self) -> list[Violation]:
        return [v for v in self.violations if v.severity == "error"]

    @property
    def warnings(self) -> list[Violation]:
        return [v for v in self.violations if v.severity == "warning"]

    def unfinished(self) -> list[str]:
        """Task ids observed but not terminal — call after the run drains to
        detect lost tasks (the reference loses in-flight tasks on purge,
        SURVEY §5.3; this framework must not)."""
        with self._lock:
            # keys that never carried a status are not tasks (e.g. the
            # gateway's function-registry hashes share the store)
            return [
                tid
                for tid, s in self._tasks.items()
                if s.status is not None and not _is_terminal(s.status)
            ]

    def assert_clean(self, *, allow_warnings: bool = False) -> None:
        bad = self.violations if not allow_warnings else self.errors
        if bad:
            raise AssertionError(
                "race detector found:\n" + "\n".join(str(v) for v in bad)
            )

    # -- internals ---------------------------------------------------------
    def _state(self, task_id: str) -> _TaskState:
        return self._tasks.setdefault(task_id, _TaskState())

    def _flag(
        self,
        kind: str,
        severity: str,
        task_id: str,
        detail: str,
        events: tuple[Event, ...] = (),
    ) -> None:
        self.violations.append(Violation(kind, severity, task_id, detail, events))

    def _check_transition(self, state: _TaskState, event: Event) -> None:
        frm, to = state.status, event.to_status
        assert to is not None
        prior = (state.last_event,) if state.last_event else ()
        if _is_terminal(frm):
            same = frm == to and (
                event.result is None or event.result == state.result
            )
            if frm in _NEVER_RAN and to in (
                "RUNNING", "COMPLETED", "FAILED"
            ):
                # the one lawful terminal overwrite: a cancel/shed that
                # LOST its race against dispatch (store/base.py
                # cancel_task, expire_task) — the task ran anyway and
                # reality overwrites the stale record (includes the
                # writers' own post-write repairs restoring a clobbered
                # terminal status)
                self._flag(
                    "late-cancel-race",
                    "warning",
                    event.task_id,
                    f"{event.actor} wrote {to} over {frm}: the "
                    f"cancel/shed raced dispatch and lost; the task ran",
                    prior + (event,),
                )
                return
            if to in _NEVER_RAN and frm in ("COMPLETED", "FAILED"):
                if state.replica_declared and to == "CANCELLED":
                    # hedge loser reporting in after the winner's terminal
                    # write landed (speculation plane): the CANCEL kill
                    # confirmation for a declared replica — expected, and
                    # first_wins froze the record before this write could
                    # even be attempted through finish_task
                    self._flag(
                        "hedge-loser-cancelled",
                        "warning",
                        event.task_id,
                        f"{event.actor} wrote CANCELLED over terminal "
                        f"{frm} for a declared hedge replica: the loser's "
                        f"kill confirmation; the winner's record stands",
                        prior + (event,),
                    )
                    return
                # the sub-millisecond-task interleaving: the result landed
                # inside the cancel/shed's read->write window and its
                # write transiently clobbered it — lawful because the
                # writers' post-write repair (keyed on the redundant
                # final_status stamp) restores the record immediately
                self._flag(
                    "cancel-after-finish",
                    "warning",
                    event.task_id,
                    f"{event.actor} wrote {to} over terminal {frm}; "
                    f"the post-write repair restores it from final_status",
                    prior + (event,),
                )
                return
            if to in _NEVER_RAN and frm in _NEVER_RAN and frm != to:
                # cancel racing a deadline shed (or vice versa): both
                # writes assert the task never ran — whichever stands,
                # the record tells the client the truth
                self._flag(
                    "cancel-expire-race",
                    "warning",
                    event.task_id,
                    f"{event.actor} wrote {to} over {frm}: a cancel and "
                    f"a deadline shed raced; both mean the task never ran",
                    prior + (event,),
                )
                return
            if not same:
                self._flag(
                    "terminal-overwrite",
                    "error",
                    event.task_id,
                    f"{event.actor} wrote {to} over terminal {frm} "
                    f"(prev writer {state.last_writer})",
                    prior + (event,),
                )
            return
        if (frm, to) not in _LEGAL:
            self._flag(
                "illegal-transition",
                "error",
                event.task_id,
                f"{event.actor}: {frm} -> {to}",
                prior + (event,),
            )
            return
        if frm == "RUNNING" and to == "RUNNING":
            if state.redispatch_credits > 0:
                state.redispatch_credits -= 1
            else:
                self._flag(
                    "double-dispatch",
                    "warning",
                    event.task_id,
                    f"{event.actor} re-marked RUNNING without a declared "
                    f"re-dispatch (prev writer {state.last_writer})",
                    prior + (event,),
                )
        elif frm == "QUEUED" and to in ("COMPLETED", "FAILED"):
            self._flag(
                "result-without-dispatch",
                "warning",
                event.task_id,
                f"{event.actor} wrote {to} on a task never marked RUNNING",
                prior + (event,),
            )
        elif frm == "RUNNING" and to == "CANCELLED":
            if event.op == "finish":
                if state.kill_requested:
                    # result-bearing CANCELLED from the worker AFTER an
                    # observed !kill request: a FORCE cancel confirmed by
                    # the interrupt (worker/pool.py) — deliberate, lawful
                    return
                # a CANCELLED result nobody asked for: a stray signal or a
                # misfire-repair bug shipped it — exactly what this
                # monitor exists to surface
                self._flag(
                    "unrequested-cancel-result",
                    "warning",
                    event.task_id,
                    f"{event.actor} shipped a CANCELLED result with no "
                    f"observed force-cancel request",
                    prior + (event,),
                )
                return
            self._flag(
                "cancel-after-dispatch",
                "warning",
                event.task_id,
                f"{event.actor} wrote CANCELLED over RUNNING: the "
                f"conditional cancel raced a concurrent dispatch; the "
                f"record converges when the result lands",
                prior + (event,),
            )


class RaceCheckStore(TaskStore):
    """Transparent :class:`TaskStore` wrapper feeding a :class:`RaceMonitor`.

    Wrap each agent's handle separately so the monitor can attribute writes:

        monitor = RaceMonitor()
        gw_store = RaceCheckStore(make_store(url), monitor, actor="gateway")
        disp_store = RaceCheckStore(make_store(url), monitor, actor="dispatcher")

    Only writes are intercepted; reads and the announce bus pass straight
    through (the bus is fire-and-forget by design — nothing to check).
    """

    def __init__(self, inner: TaskStore, monitor: RaceMonitor, actor: str) -> None:
        self.inner = inner
        self.monitor = monitor
        self.actor = actor

    # -- intercepted writes ------------------------------------------------
    def hset(self, key: str, fields: Mapping[str, str]) -> None:
        if key in (LIVE_INDEX_KEY, FLEET_HEALTH_KEY):
            # bookkeeping hashes, not task records: their fields are task
            # ids / dispatcher ids, which the lifecycle monitor must not
            # mistake for task fields
            self.inner.hset(key, fields)
            return
        if key.startswith(BLOB_PREFIX):
            # blob namespace, not a task record: data-field writes get the
            # create-once/content check; stamp-only writes (BLOB_AT_FIELD
            # refresh) are bookkeeping
            if BLOB_DATA_FIELD in fields:
                self.monitor.observe_blob_write(
                    self.actor, key, fields[BLOB_DATA_FIELD]
                )
            self.inner.hset(key, fields)
            return
        if key.startswith(TRACE_PREFIX):
            # span-plane hashes (obs/tracectx.py): telemetry, not task
            # records — span fields are first-write-wins by construction
            # (hsetnx), the stamp refresh is bookkeeping
            self.inner.hset(key, fields)
            return
        op = "finish" if FIELD_RESULT in fields else "status"
        if FIELD_STATUS in fields and fields[FIELD_STATUS] in (
            str(TaskStatus.QUEUED),
            str(TaskStatus.WAITING),  # graph nodes created behind deps
        ):
            op = "create"
        self.monitor.observe(self.actor, op, key, fields)
        self.inner.hset(key, fields)

    def hdel(self, key: str, *fields: str) -> None:
        return self.inner.hdel(key, *fields)

    def delete(self, key: str) -> None:
        self.monitor.observe(self.actor, "delete", key)
        self.inner.delete(key)

    def declare_redispatch(self, task_id: str) -> None:
        self.monitor.expect_redispatch(task_id)
        self.inner.declare_redispatch(task_id)

    def declare_replica(self, task_id: str) -> None:
        self.monitor.expect_replica(task_id)
        self.inner.declare_replica(task_id)

    def request_kill(
        self, task_id: str, channel: str = TASKS_CHANNEL
    ) -> None:
        self.monitor.expect_force_cancel(task_id)
        self.inner.request_kill(task_id, channel)

    def flush(self) -> None:
        self.monitor.observe_flush(self.actor)
        self.inner.flush()

    # -- pass-through ------------------------------------------------------
    def hget(self, key: str, field: str) -> str | None:
        return self.inner.hget(key, field)

    def hgetall(self, key: str) -> dict[str, str]:
        return self.inner.hgetall(key)

    def hmget(self, key: str, fields: list[str]) -> list[str | None]:
        # pass through, not the base loop-of-hget default: the reclaim path
        # relies on hmget being ONE round trip on RESP backends
        return self.inner.hmget(key, fields)

    def hget_many(self, keys: list[str], field: str) -> list[str | None]:
        return self.inner.hget_many(keys, field)

    def hincrby(self, key: str, field: str, delta: int) -> int:
        # dependency-count bookkeeping, not a lifecycle write: pass through
        # for atomicity (the base default's read-modify-write would race)
        return self.inner.hincrby(key, field, delta)

    def hincrby_many(self, items) -> list[int]:
        return self.inner.hincrby_many(items)

    def hgetall_many(self, keys: list[str]) -> list[dict[str, str]]:
        # reads pass through pipelined; only writes need the monitor.
        # The batch WRITE forms (set_status_many / finish_task_many /
        # hset_many) deliberately keep the base per-item loop defaults:
        # each item then flows through the intercepted hset above, so a
        # race-checked run trades the pipelining away for full observation
        return self.inner.hgetall_many(keys)

    @property
    def n_round_trips(self) -> int:
        # surface the wrapped backend's counter: a dispatcher wrapped for
        # race checking must still publish round-trip deltas (inflated by
        # the per-item write loops above — that is the observation tax)
        return self.inner.n_round_trips

    def setnx_field(self, key: str, field: str, value: str) -> tuple[bool, str]:
        # pass through for atomicity. Idempotency/dispatch claims are not
        # lifecycle writes — but a WINNING setnx on the STATUS field IS
        # one: create_task_if_absent claims its QUEUED status this way
        # (keyed submits), and without observing it here the monitor
        # would see the eventual RUNNING as None -> RUNNING
        created, current = self.inner.setnx_field(key, field, value)
        if created and field == FIELD_STATUS:
            self.monitor.observe(
                self.actor, "create", key, {FIELD_STATUS: value}
            )
        elif (
            created
            and field == BLOB_DATA_FIELD
            and key.startswith(BLOB_PREFIX)
        ):
            # put_blob's winning claim IS the blob's create: validate the
            # content against the digest (losers write nothing)
            self.monitor.observe_blob_write(self.actor, key, value)
        return created, current

    def setnx_fields(self, items, field: str):
        results = self.inner.setnx_fields(items, field)
        if field == FIELD_STATUS:
            for (key, value), (created, _current) in zip(items, results):
                if created:
                    self.monitor.observe(
                        self.actor, "create", key, {FIELD_STATUS: value}
                    )
        return results

    def hsetnx_many(self, items) -> list[bool]:
        # span-plane first-write-wins writes: trace hashes carry no
        # lifecycle fields, so there is nothing to observe — but route
        # through setnx_field-aware inner for atomicity
        return self.inner.hsetnx_many(items)

    def keys(self) -> list[str]:
        return self.inner.keys()

    def publish(self, channel: str, payload: str) -> None:
        self.inner.publish(channel, payload)

    def subscribe(self, channel: str) -> Subscription:
        return self.inner.subscribe(channel)

    def ping(self) -> bool:
        return self.inner.ping()

    # -- HA pass-throughs (store/replication.py) ---------------------------
    # replay delivers announces, not writes — nothing lifecycle-shaped to
    # observe; dedup/verification happens at dispatcher intake as usual
    def replay_announces(self, after: int):
        return self.inner.replay_announces(after)

    @property
    def failover_generation(self) -> int:
        return getattr(self.inner, "failover_generation", 0)

    @property
    def endpoints(self):
        return getattr(self.inner, "endpoints", None)

    # -- sharding pass-throughs (store/sharding.py) ------------------------
    # the ring is routing, not lifecycle: a race-checked sharded stack
    # keeps its shard topology visible to dispatchers/gateways while every
    # write above still flows through the observed per-item paths
    @property
    def shard_count(self):
        return getattr(self.inner, "shard_count", 0)

    @property
    def owned_shards(self):
        return getattr(self.inner, "owned_shards", None)

    def shard_of(self, task_id: str) -> int:
        fn = getattr(self.inner, "shard_of", None)
        return fn(task_id) if fn is not None else 0

    def shard_failover_generations(self):
        fn = getattr(self.inner, "shard_failover_generations", None)
        return fn() if fn is not None else []

    def rotate_endpoint(self) -> bool:
        fn = getattr(self.inner, "rotate_endpoint", None)
        return bool(fn()) if fn is not None else False

    def promote(self) -> int:
        fn = getattr(self.inner, "promote", None)
        if fn is None:
            raise NotImplementedError(
                f"{type(self.inner).__name__} cannot be promoted"
            )
        return fn()

    def info(self) -> dict:
        fn = getattr(self.inner, "info", None)
        return fn() if fn is not None else {}

    def save(self, path: str | None = None) -> None:
        self.inner.save(path)

    def close(self) -> None:
        self.inner.close()


def check_trace(events: Iterable[Event], *, strict: bool = False) -> list[Violation]:
    """Replay a recorded event trace through a fresh monitor (offline /
    post-mortem mode). Events may come from ``RaceMonitor.events`` of a live
    run or be reconstructed from logs."""
    monitor = RaceMonitor(strict=strict)
    for e in sorted(events, key=lambda e: e.seq):
        if e.op == "flush":
            monitor.observe_flush(e.actor)
            continue
        fields: dict[str, str] = {}
        if e.to_status is not None:
            fields[FIELD_STATUS] = e.to_status
        if e.result is not None:
            fields[FIELD_RESULT] = e.result
        monitor.observe(e.actor, e.op, e.task_id, fields)
    return monitor.violations
