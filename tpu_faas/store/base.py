"""Abstract task store + announce bus interface.

Operations are the minimal set the reference exercises against Redis:

- hash per task: HSET/HGET/HGETALL (reference task_dispatcher.py:48-52,
  85-86, 153-156, 288-295; gateway side per SURVEY §0.1);
- announce bus: PUBLISH task_id on a channel at submit time; the dispatcher
  SUBSCRIBEs and drains at most one message per tick via a non-blocking
  ``get_message()`` (reference task_dispatcher.py:75,170,299,394,452) so that
  back-pressure stays implicit — unread announcements buffer in the
  subscription;
- FLUSHDB between benchmark runs (reference client_performance.py:152,253).

Task-level conveniences (`create_task`, `finish_task`, ...) wrap the raw hash
ops so call sites stay readable; both levels are part of the interface because
the gateway writes the exact field contract while dispatchers read it.
"""

from __future__ import annotations

import abc
import time
from typing import Mapping

from tpu_faas.core.task import (
    DEP_FAILED_PREFIX,
    FIELD_CHILDREN,
    FIELD_DEP_RESOLVED,
    FIELD_FINAL_AT,
    FIELD_FINAL_STATUS,
    FIELD_FINISHED_AT,
    FIELD_FN,
    FIELD_PARAMS,
    FIELD_PENDING_DEPS,
    FIELD_RESULT,
    FIELD_RESULT_DIGEST,
    FIELD_RESULT_SIZE,
    FIELD_STATUS,
    TaskStatus,
    dep_done_field,
)

#: Default announce channel name (reference config.ini:7 `TASKS_CHANNEL=tasks`).
TASKS_CHANNEL = "tasks"

#: Index hash of live (non-terminal) task ids: field = task_id, value "1".
#: Written with every create, removed with every terminal write, so a
#: stranded-task rescan reads O(live tasks) instead of KEYS-walking the
#: full keyspace — whose size grows with HISTORY (every task that ever
#: ran) unless a TTL sweeper prunes it. Stale entries are harmless (the
#: rescan status-probes each candidate anyway) and are garbage-collected
#: by the rescan itself; MISSING entries (foreign producers writing the
#: raw reference contract, pre-index snapshots) are covered by the
#: rescan's periodic full-scan fallback.
LIVE_INDEX_KEY = "tasks:index"

#: Dispatcher liveness registry: field = dispatcher_id, value = epoch
#: seconds of its last lease-renewal pass. Shared-fleet adoption decisions
#: key off this — a task claim is only stealable once its OWNER's
#: heartbeat here has gone stale (a merely-overloaded sibling keeps
#: renewing and keeps its claims).
DISPATCHERS_KEY = "dispatchers:alive"
#: Fleet-wide lease configuration. Each rescanning dispatcher publishes its
#: adoption horizon as a write-once field "t:<lease_timeout>" -> wall time
#: of first publication (setnx); the fleet's effective horizon is the MIN
#: over fields (value-keyed so concurrent publishers can't lose updates to
#: each other). Every dispatcher mode folds it into its lease-renew cadence
#: (renew at timeout/3 when that is tighter than the default
#: LEASE_RENEW_PERIOD), and rescanners grace-floor adoptions briefly after
#: a value first appears. Without this, a mixed fleet where a rescanner
#: runs ``--lease-timeout`` at or below ~2-3x the siblings' fixed renew
#: period would adopt tasks whose owner is alive but between renewals —
#: double execution.
LEASE_CONF_KEY = "fleet:lease_conf"
#: Fleet-wide tenant-fairness configuration (tpu_faas/tenancy): fields
#: ``shares`` / ``caps`` hold "<spec>:<wall stamp>" — the spec is the same
#: "name=value,..." string the ``--tenant-shares``/``--tenant-caps`` CLI
#: flags take, and the trailing stamp makes the sharded store's
#: freshest-wins fleet-hash merge (store/sharding.py) pick the newest
#: publication. Dispatchers re-read it at capacity-publish cadence (~1 Hz)
#: and apply changes to their live TenantTable without a restart.
TENANT_CONF_KEY = "fleet:tenant_conf"
#: Results channel: finish_task announces every terminal write here so the
#: gateway can wake parked /result long-polls instantly instead of polling
#: the store. No reference analog (its clients poll, SURVEY §3.1); the
#: channel is fire-and-forget like the task bus — consumers must keep a
#: fallback re-read, never rely on delivery.
RESULTS_CHANNEL = "results"

#: Express result lane: terminal announces on RESULTS_CHANNEL may carry the
#: status + result INLINE ("<prefix><task_id>\\x00<status>\\x00<result>") so
#: a woken gateway long-poll replies from the forwarded payload instead of
#: paying a store re-read per delivery. Strictly opt-in at the producer
#: (finish_task's ``inline_max``; 0 = the classic id-only payload, the
#: default everywhere) — the store write stays authoritative and PRECEDES
#: the announce on the same pipelined round, so a consumer that ignores the
#: inline form and re-reads the record sees the identical terminal state.
#: Reference-era consumers never see the form unless the operator enables
#: it fleet-wide.
RESULT_INLINE_PREFIX = "!r1:"
#: Express digest form (result-blob plane, ``--result-blobs`` producers):
#: the announce carries status + result DIGEST + size instead of the body
#: ("<prefix><task_id>\\x00<status>\\x00<digest>\\x00<size>"). Produced
#: only for digest-form terminal writes (FIELD_RESULT_DIGEST set, body
#: empty), so off-plane announce bytes are untouched; a consumer that
#: doesn't know the form treats the whole payload as an opaque id (its
#: record probe finds nothing and skips, like any garbage announce).
RESULT_DIGEST_PREFIX = "!r2:"
#: Default inline-payload bound for express producers (the dispatcher's
#: ``--express`` knob): results larger than this fall back to the id-only
#: announce and the gateway's ordinary store read.
RESULT_INLINE_MAX_BYTES = 4096
_RESULT_INLINE_SEP = "\x00"


def encode_result_announce(
    task_id: str,
    status: str,
    result: str,
    inline_max: int = 0,
    result_digest: str | None = None,
    result_size: int = 0,
) -> str:
    """The RESULTS_CHANNEL payload for one terminal write: the digest form
    for digest-form writes (result-blob plane), the inline express form
    when ``inline_max`` allows it, else the classic bare task id.
    Oversized results — and any field that would collide with the
    framing — fall back to id-only rather than truncate: a wrong inline
    payload is worse than a store re-read."""
    status = str(status)
    if (
        result_digest
        and not result
        and _RESULT_INLINE_SEP not in task_id
        and _RESULT_INLINE_SEP not in status
        and _RESULT_INLINE_SEP not in result_digest
    ):
        return (
            f"{RESULT_DIGEST_PREFIX}{task_id}{_RESULT_INLINE_SEP}"
            f"{status}{_RESULT_INLINE_SEP}{result_digest}"
            f"{_RESULT_INLINE_SEP}{int(result_size)}"
        )
    if (
        inline_max > 0
        and len(result) <= inline_max
        and _RESULT_INLINE_SEP not in task_id
        and _RESULT_INLINE_SEP not in status
        and _RESULT_INLINE_SEP not in result
    ):
        return (
            f"{RESULT_INLINE_PREFIX}{task_id}{_RESULT_INLINE_SEP}"
            f"{status}{_RESULT_INLINE_SEP}{result}"
        )
    return task_id


def decode_result_announce(
    payload: str,
) -> tuple[str, str | None, str | None]:
    """(task_id, status, result) of one RESULTS_CHANNEL payload; status and
    result are None for the classic id-only form (and for any malformed
    inline frame — the consumer then falls back to its store read, which is
    always correct). The digest form decodes to (task_id, status, None):
    body-oblivious consumers get the wake-up and re-read the record."""
    tid, status, result, _digest, _size = decode_result_announce_full(payload)
    return tid, status, result


def decode_result_announce_full(
    payload: str,
) -> tuple[str, str | None, str | None, str | None, int]:
    """(task_id, status, result, result_digest, result_size) of one
    RESULTS_CHANNEL payload — the digest-aware decode for consumers that
    can materialize blobs (gateway result delivery). Classic id-only and
    malformed frames decode with every optional part None, same fallback
    contract as :func:`decode_result_announce`."""
    if payload.startswith(RESULT_DIGEST_PREFIX):
        parts = payload[len(RESULT_DIGEST_PREFIX):].split(
            _RESULT_INLINE_SEP, 3
        )
        if len(parts) != 4 or not parts[0] or not parts[1] or not parts[2]:
            return payload, None, None, None, 0
        try:
            size = int(parts[3])
        except ValueError:
            size = 0
        return parts[0], parts[1], None, parts[2], size
    if not payload.startswith(RESULT_INLINE_PREFIX):
        return payload, None, None, None, 0
    parts = payload[len(RESULT_INLINE_PREFIX):].split(_RESULT_INLINE_SEP, 2)
    if len(parts) != 3 or not parts[0] or not parts[1]:
        # malformed frame (foreign producer): treat the whole payload as an
        # opaque id — the consumer's record probe will find nothing and
        # skip, exactly like any garbage announce
        return payload, None, None, None, 0
    return parts[0], parts[1], parts[2], None, 0

#: Content-addressed payload namespace: one hash per payload body, keyed
#: ``blob:<sha256>`` (core/payload.py payload_digest). Write-once by
#: protocol — the digest IS the content, so a second writer of the same
#: key by definition carries identical bytes, and put_blob claims the data
#: field with setnx so even a buggy second writer cannot mutate it (the
#: race monitor flags any bypass, store/racecheck.py). Values keep the
#: ASCII payload contract: the RESP wire and every reference-style
#: consumer of this store are string-typed surfaces.
BLOB_PREFIX = "blob:"
#: the payload body field of a blob hash
BLOB_DATA_FIELD = "data"
#: epoch-seconds stamp of the blob's last put ATTEMPT (a dedup hit
#: refreshes it): the TTL half of refcount-or-TTL GC — the gateway's
#: sweeper only collects blobs whose stamp has aged out AND that no
#: function-registry record or live task still references (the refcount
#: half, recomputed from the referencing records at sweep time so there
#: is no counter to corrupt).
BLOB_AT_FIELD = "blob_at"


def blob_key(digest: str) -> str:
    return BLOB_PREFIX + digest


#: Materialize-request namespace (result-blob plane): a reader that needs
#: the BODY of a digest-form result the store doesn't hold yet — a legacy
#: /result consumer, mostly — claims ``blobreq:<digest>`` (setnx on the
#: REQ_AT field, dedup across concurrent readers) and publishes
#: "<BLOBREQ_ANNOUNCE_PREFIX><digest>" on the TASKS announce channel. The
#: dispatcher that tracks a producer worker for the digest pulls the body
#: off that worker's result cache (reverse BLOB_MISS/BLOB_FILL), writes
#: the ``blob:<digest>`` record, and deletes the request key; the reader
#: polls get_blob meanwhile. Plain ring-routed — every client spells the
#: key identically, so the fleet shares one copy per digest. Stale
#: requests (producer died with the only copy) are aged out by the blob
#: sweeper.
BLOBREQ_PREFIX = "blobreq:"
#: epoch-seconds stamp of the materialize request (its only field)
BLOBREQ_AT_FIELD = "req_at"


def blobreq_key(digest: str) -> str:
    return BLOBREQ_PREFIX + digest


#: Control message on the TASKS announce channel: "<prefix><task_id>" tells
#: dispatchers to drop the task from any pending structure they hold (the
#: gateway publishes it only AFTER it actually wrote CANCELLED). Plain
#: create-announces are bare task ids, which never start with this prefix;
#: a reference-style consumer that treats it as a task id just finds no
#: record and skips — the bus stays wire-compatible.
CANCEL_ANNOUNCE_PREFIX = "!cancel:"
#: Control message requesting a FORCE cancel of a RUNNING task: whichever
#: dispatcher holds it in flight relays a CANCEL to the owning worker,
#: which interrupts the task mid-run (worker/pool.py SIGUSR1) and ships a
#: terminal CANCELLED result through the ordinary result path. Best-effort:
#: no store write happens here — the record converges when the worker's
#: result lands (or stays RUNNING if the task finished first).
KILL_ANNOUNCE_PREFIX = "!kill:"
#: Control message requesting lazy materialization of a result blob:
#: "<prefix><digest>" asks whichever dispatcher tracks a live producer for
#: the digest to pull the body off that worker's result cache and write
#: the ``blob:<digest>`` record (see BLOBREQ_PREFIX). Best-effort like
#: every announce — the requester keeps polling get_blob and times out to
#: its documented failure mode if nobody can serve.
BLOBREQ_ANNOUNCE_PREFIX = "!blobreq:"


class Subscription(abc.ABC):
    """A pub/sub subscription handle with a non-blocking drain."""

    @abc.abstractmethod
    def get_message(self, timeout: float = 0.0) -> str | None:
        """Return the next published payload, or None if nothing is pending.

        ``timeout`` > 0 blocks up to that many seconds. The default 0 makes a
        dispatcher tick non-blocking, matching the reference's
        ``subscriber.get_message()`` usage.
        """

    @abc.abstractmethod
    def close(self) -> None: ...

    def fileno(self) -> int | None:
        """A file descriptor whose READABILITY signals that a message may
        be pending — what lets an event-driven serve loop park in one
        poll() over its worker sockets AND the announce bus instead of
        waking on a tick cadence. None (the default) means the backend has
        no pollable signal; consumers keep their periodic drain. The fd
        may change across reconnects — pollers should re-check each
        iteration. Readability is a HINT (spurious wakes are fine, the
        drain finding nothing is fine); the periodic fallback drain still
        covers a backend whose signal is lossy."""
        return None

    def pollable_fds(self) -> list[int]:
        """Every pollable readability fd of this subscription (fan-out
        subscriptions over several shards return one per shard); [] when
        the backend has no pollable signal."""
        fd = self.fileno()
        return [fd] if fd is not None else []

    def __enter__(self) -> "Subscription":
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()


class TaskStore(abc.ABC):
    """Hash-per-task store + announce bus."""

    #: Monotonic count of wire round trips this handle has paid (a
    #: pipelined batch counts as ONE). Only networked backends increment
    #: it (RespStore); in-process backends leave it 0. Observability only:
    #: the tpu-push dispatcher publishes per-tick deltas of this so an
    #: operator can SEE that the data plane stays at a bounded number of
    #: pipelined rounds per tick instead of O(tasks) round trips.
    n_round_trips: int = 0

    #: Bumped by failover-capable backends (multi-endpoint RespStore)
    #: every time commands settle on a DIFFERENT store endpoint.
    #: Dispatchers watch it to trigger their post-failover re-arm
    #: (announce replay + immediate rescan); 0 forever on backends that
    #: cannot fail over.
    failover_generation: int = 0

    # -- raw hash ops ------------------------------------------------------
    @abc.abstractmethod
    def hset(self, key: str, fields: Mapping[str, str]) -> None: ...

    @abc.abstractmethod
    def hget(self, key: str, field: str) -> str | None: ...

    @abc.abstractmethod
    def hgetall(self, key: str) -> dict[str, str]: ...

    @abc.abstractmethod
    def hdel(self, key: str, *fields: str) -> None:
        """Remove fields from a hash (standard Redis HDEL; a key whose last
        field is removed disappears). The live-task index depends on it."""

    @abc.abstractmethod
    def delete(self, key: str) -> None: ...

    @abc.abstractmethod
    def keys(self) -> list[str]: ...

    # -- announce bus ------------------------------------------------------
    @abc.abstractmethod
    def publish(self, channel: str, payload: str) -> None: ...

    def publish_many(self, channel: str, payloads: list[str]) -> None:
        """Batch publish on one channel. Default: a loop; the RESP client
        pipelines one round trip — the batched keyed-create's announces
        ride this so a large batch doesn't pay one round trip per task."""
        for payload in payloads:
            self.publish(channel, payload)

    @abc.abstractmethod
    def subscribe(self, channel: str) -> Subscription: ...

    def replay_announces(
        self, after: int
    ) -> tuple[int, list[tuple[str, str]]]:
        """Re-read recent announces from the backend's bounded replay ring
        (store/replication.py): entries with replay offset > ``after``
        plus the current tail offset; ``after=-1`` asks for the tail
        alone. The post-failover re-arm reads this on a promoted replica
        to re-discover announces the dead primary published that no
        dispatcher drained. Default: unsupported — tail -1, no entries
        (backends without a ring simply rely on the rescan)."""
        return -1, []

    # -- admin -------------------------------------------------------------
    @abc.abstractmethod
    def flush(self) -> None:
        """Drop all hashes (FLUSHDB equivalent). Subscriptions stay open."""

    @abc.abstractmethod
    def close(self) -> None: ...

    def ping(self) -> bool:
        return True

    def save(self, path: str | None = None) -> None:
        """Checkpoint the store (see tpu_faas/store/snapshot.py).

        `path=None` means "the backend's configured snapshot target"
        (a server's --snapshot file). Backends without durability raise."""
        raise NotImplementedError(f"{type(self).__name__} cannot checkpoint")

    def hincrby(self, key: str, field: str, delta: int) -> int:
        """Atomically add ``delta`` to an integer hash field (absent = 0)
        and return the new value — the dependency plane's pending-count
        decrement. This base default is read-modify-write and only
        single-thread safe; production backends override it (the RESP
        client sends HINCRBY, the memory store holds its lock)."""
        current = self.hget(key, field)
        try:
            value = int(current) if current is not None else 0
        except ValueError:
            value = 0
        value += int(delta)
        self.hset(key, {field: str(value)})
        return value

    def hincrby_many(self, items: list[tuple[str, str, int]]) -> list[int]:
        """hincrby over (key, field, delta) triples. Default: a loop; the
        RESP client pipelines one HINCRBY round — the promotion plane
        decrements every child of a finished parent batch at once."""
        return [self.hincrby(key, field, delta) for key, field, delta in items]

    # -- task-level conveniences ------------------------------------------
    def create_task(
        self,
        task_id: str,
        fn_payload: str,
        param_payload: str,
        channel: str = TASKS_CHANNEL,
        extra_fields: dict[str, str] | None = None,
        status: TaskStatus = TaskStatus.QUEUED,
    ) -> None:
        """Write the gateway-side contract: full hash then announce.

        Field set and QUEUED initial status per SURVEY §0.1 (demonstrated in
        the reference by old/client_debug.py:40-45). ``extra_fields`` carries
        optional scheduling hints (FIELD_PRIORITY/FIELD_COST); the core four
        fields win on any name collision. ``status`` admits exactly one
        other initial state: WAITING, for graph nodes created behind their
        dependencies (gateway /execute_graph) — the announce still fires
        (graph-aware dispatchers park the node in their frontier; everyone
        else skips non-QUEUED announces as always).
        """
        # index first: a crash after the index write leaves a stale entry
        # (filtered by the rescan's status probe); the opposite order would
        # leave a live task invisible to indexed rescans
        self.hset(LIVE_INDEX_KEY, {task_id: "1"})
        self.hset(
            task_id,
            {
                **(extra_fields or {}),
                FIELD_STATUS: str(status),
                FIELD_FN: fn_payload,
                FIELD_PARAMS: param_payload,
                FIELD_RESULT: "None",
            },
        )
        self.publish(channel, task_id)

    def create_task_if_absent(
        self,
        task_id: str,
        fn_payload: str,
        param_payload: str,
        channel: str = TASKS_CHANNEL,
        extra_fields: dict[str, str] | None = None,
    ) -> bool:
        """create_task that can NEVER regress an existing record: the status
        field is claimed with setnx, so a concurrent (or very late) second
        creator writes nothing — a plain create_task racing an already-
        dispatched copy of the same deterministic task id would reset
        RUNNING back to QUEUED and get the task executed twice. Used by the
        gateway for every idempotency-keyed create, where winner and
        adopter can both believe the record is theirs to write.

        Returns True when this call created (and announced) the record.
        A predecessor that died between its status claim and its field
        write (status QUEUED, no params) is repaired in place — same
        values, write-once — and re-announced; duplicate announces are
        deduped at dispatcher intake.
        """
        created, current = self.setnx_field(
            task_id, FIELD_STATUS, str(TaskStatus.QUEUED)
        )
        if not created and not (
            current == str(TaskStatus.QUEUED)
            and self.hget(task_id, FIELD_PARAMS) is None
        ):
            return False
        self.hset(LIVE_INDEX_KEY, {task_id: "1"})
        self.hset(
            task_id,
            {
                **(extra_fields or {}),
                FIELD_FN: fn_payload,
                FIELD_PARAMS: param_payload,
                FIELD_RESULT: "None",
            },
        )
        self.publish(channel, task_id)
        # claim-loss repair: a concurrent cancel aimed at the PREVIOUS
        # incarnation of this deterministic id can clobber the setnx'd
        # QUEUED with CANCELLED and then have its ghost cleanup strip the
        # status field entirely (cancel_task's probe saw no params yet) —
        # leaving this freshly-written record status-less, which intake
        # skips forever. Re-claim and re-announce; a duplicate announce is
        # deduped at intake. Deliberate cost: one small-field read per
        # keyed create (status is bytes, never a payload) buys out a
        # stranded acknowledged submit — the one failure in this family
        # that no retry or sweeper would ever repair.
        if self.hget(task_id, FIELD_STATUS) is None:
            self.hset(task_id, {FIELD_STATUS: str(TaskStatus.QUEUED)})
            self.publish(channel, task_id)
        return True

    def create_tasks_if_absent(
        self,
        tasks: list[tuple],  # (task_id, fn_payload, params[, extra_fields])
        channel: str = TASKS_CHANNEL,
    ) -> list[bool]:
        """Batch ``create_task_if_absent``: the common case (every id
        fresh — the gateway's auto-keyed bulk submit) pays a BOUNDED
        number of pipelined rounds on RESP backends — one status-claim
        round (setnx_fields), one create round (create_tasks; its
        QUEUED-over-just-claimed-QUEUED rewrite is the protocol's
        idempotent-retry transition), one claim-loss recheck round —
        instead of several round trips per item. Items whose status claim
        LOST (dedup-adoption races, repairs) fall back to the per-item
        form, which carries the full repair ladder; losers are rare by
        construction. Returns created flags parallel to ``tasks``."""
        if not tasks:
            return []
        ids = [t[0] for t in tasks]
        claims = self.setnx_fields(
            [(tid, str(TaskStatus.QUEUED)) for tid in ids], FIELD_STATUS
        )
        created = [False] * len(tasks)
        winners = [i for i, (won, _cur) in enumerate(claims) if won]
        if winners:
            # winners' field writes carry NO status — exactly like the
            # per-item form: the setnx above already claimed QUEUED, and
            # rewriting it here would reopen the regression this method
            # exists to prevent (a winner stalled past the adoption wait
            # has its record adopted by a duplicate submit and possibly
            # dispatched; a late status=QUEUED write would then reset
            # RUNNING and run the task twice)
            items: list[tuple[str, dict[str, str]]] = []
            for i in winners:
                tid, fn_payload, param_payload = tasks[i][:3]
                extra = tasks[i][3] if len(tasks[i]) > 3 else None
                # index first (same ordering rationale as create_task)
                items.append((LIVE_INDEX_KEY, {tid: "1"}))
                items.append(
                    (
                        tid,
                        {
                            **(extra or {}),
                            FIELD_FN: fn_payload,
                            FIELD_PARAMS: param_payload,
                            FIELD_RESULT: "None",
                        },
                    )
                )
            self.hset_many(items)
            winner_ids = [ids[i] for i in winners]
            self.publish_many(channel, winner_ids)
            # claim-loss repair, batched (see create_task_if_absent): a
            # concurrent cancel's ghost cleanup can strip the status out
            # from under the create — re-claim and re-announce stragglers
            recheck = self.hget_many(winner_ids, FIELD_STATUS)
            for tid, status in zip(winner_ids, recheck):
                if status is None:
                    self.hset(tid, {FIELD_STATUS: str(TaskStatus.QUEUED)})
                    self.publish(channel, tid)
            for i in winners:
                created[i] = True
        for i, (won, _cur) in enumerate(claims):
            if not won:
                task = tasks[i]
                created[i] = self.create_task_if_absent(
                    task[0],
                    task[1],
                    task[2],
                    channel,
                    task[3] if len(task) > 3 else None,
                )
        return created

    def hexists(self, key: str, field: str) -> bool:
        """Field presence WITHOUT transferring the value (standard Redis
        HEXISTS). Default: an hget — correct everywhere; the RESP client
        overrides with the real command so a multi-MB payload field isn't
        dragged over the wire just to test existence (cancel_task's record-
        completeness probes)."""
        return self.hget(key, field) is not None

    def hmget(self, key: str, fields: list[str]) -> list[str | None]:
        """Several fields of one hash, None per missing field. Default: a
        loop; the RESP client sends one HMGET round trip — the dispatcher's
        reclaim path uses this so re-queuing a dead worker's task never
        drags the (possibly huge) result blob over the wire."""
        return [self.hget(key, f) for f in fields]

    def setnx_field(
        self, key: str, field: str, value: str
    ) -> tuple[bool, str]:
        """Set ``field`` on ``key`` only if absent; return (created,
        current_value) — the mutual-exclusion primitive behind idempotent
        submits. Exactly one of N concurrent callers creates the field, and
        EVERY caller walks away with the winning value, so losers can
        compare payloads without a not-yet-written window.

        Backends override with a genuinely atomic form: the RESP client
        sends HSETNX+HGET (safe because claimed fields are write-once —
        the winner's later full-record write repeats the same value), the
        memory store uses its lock. This base default is check-then-set and
        only single-thread safe — production stores override it."""
        existing = self.hget(key, field)
        if existing is not None:
            return False, existing
        self.hset(key, {field: value})
        return True, value

    def setnx_fields(
        self, items: list[tuple[str, str]], field: str
    ) -> list[tuple[bool, str]]:
        """setnx_field over many (key, value) pairs. Default: a loop; the
        RESP client pipelines everything into one round trip."""
        return [self.setnx_field(key, field, value) for key, value in items]

    def hsetnx_many(
        self, items: list[tuple[str, str, str]]
    ) -> list[bool]:
        """Set-if-absent over arbitrary (key, field, value) triples —
        unlike ``setnx_fields`` the FIELD varies per item. Returns created
        flags parallel to ``items`` (no value read-back: callers of this
        form only need to know whether their write stood). The span
        plane's first-write-wins record flush rides this. Default: a
        loop; the RESP client pipelines one HSETNX round."""
        return [
            self.setnx_field(key, field, value)[0]
            for key, field, value in items
        ]

    def delete_many(self, keys: list[str]) -> None:
        """Batch delete. Default: a loop; the RESP client sends one DEL
        with all keys (the TTL sweeper's backlog purge)."""
        for key in keys:
            self.delete(key)

    def hget_many(self, keys: list[str], field: str) -> list[str | None]:
        """One field from many hashes. Default: a loop (one round trip per
        key); the RESP client overrides with a pipelined single round trip —
        this is what keeps the dispatcher's stranded-task rescan cheap as
        task history grows."""
        return [self.hget(k, field) for k in keys]

    def hgetall_many(self, keys: list[str]) -> list[dict[str, str]]:
        """Full records of many hashes, one dict per key ({} for a missing
        key — same shape as hgetall). Default: a loop; the RESP client
        pipelines one round trip. This is the dispatcher's batched-intake
        primitive: one round fetches every announced task's record instead
        of one hgetall per announce."""
        return [self.hgetall(k) for k in keys]

    def hgetall_many_raw(self, keys: list[str]) -> list[list]:
        """Full records of many hashes as FLAT ``[field, value, ...]``
        lists, one per key ([] for a missing key) — the columnar intake's
        read form (dispatch/base.py): no per-record dict is materialized.
        Elements are ``bytes`` on the RESP client's negotiated binary-batch
        path and ``str`` everywhere else; columnar consumers must accept
        both. Default: re-flatten hgetall_many."""
        return [
            [p for kv in rec.items() for p in kv]
            for rec in self.hgetall_many(keys)
        ]

    # -- content-addressed blobs ------------------------------------------
    def put_blob(self, digest: str, data: str) -> bool:
        """Put-if-absent write of a payload body under its content address.

        The data field is CLAIMED with setnx — write-once, the create-once
        protocol the race monitor enforces — and the TTL stamp is
        refreshed on every attempt (a dedup hit means the content is hot;
        the GC must not age it out under active producers). Returns True
        when this call created the blob. Two round trips on the loop
        default; the RESP client pipelines one."""
        key = blob_key(digest)
        created, _ = self.setnx_field(key, BLOB_DATA_FIELD, data)
        self.hset(key, {BLOB_AT_FIELD: repr(time.time())})
        return created

    def get_blob(self, digest: str) -> str | None:
        """The payload body for ``digest``, or None when the blob was never
        written (or was GC'd). Read-only: resolution must not perturb the
        TTL stamp — pinning is the referencing records' job."""
        return self.hget(blob_key(digest), BLOB_DATA_FIELD)

    def get_blobs(self, digests: list[str]) -> list[str | None]:
        """Pipelined multi-get of payload bodies (one round trip on RESP
        backends) — the dispatcher's warm-up path for a mixed batch of
        digests resolves them all at once."""
        return self.hget_many([blob_key(d) for d in digests], BLOB_DATA_FIELD)

    def create_tasks(
        self,
        tasks: list[tuple],  # (task_id, fn_payload, params[, extra_fields])
        channel: str = TASKS_CHANNEL,
        status: TaskStatus = TaskStatus.QUEUED,
    ) -> None:
        """Batch create_task. Each tuple is (task_id, fn_payload,
        param_payload) with an optional 4th element of extra hash fields.
        Default: a loop; the RESP client pipelines all writes + announces
        into one round trip (the gateway's batch-submit path). ``status``
        as in create_task — the graph submit creates its WAITING children
        in one pipelined batch before announcing the QUEUED roots."""
        for task in tasks:
            task_id, fn_payload, param_payload = task[:3]
            extra = task[3] if len(task) > 3 else None
            self.create_task(
                task_id, fn_payload, param_payload, channel, extra,
                status=status,
            )

    def get_payloads(self, task_id: str) -> tuple[str, str]:
        """Fetch (fn_payload, param_payload) in one round-trip —
        dispatcher-side read (reference task_dispatcher.py:48-52 does two
        HGETs; HGETALL halves the store RTTs on the intake hot path)."""
        fields = self.hgetall(task_id)
        if FIELD_FN not in fields or FIELD_PARAMS not in fields:
            raise KeyError(f"unknown task {task_id!r}")
        return fields[FIELD_FN], fields[FIELD_PARAMS]

    def set_status(
        self,
        task_id: str,
        status: TaskStatus | str,
        extra_fields: Mapping[str, str] | None = None,
    ) -> None:
        """``extra_fields`` ride in the same hash write (one round trip) —
        the RUNNING mark uses this to stamp its ownership lease."""
        fields = {FIELD_STATUS: str(status)}
        if extra_fields:
            fields.update(extra_fields)
        self.hset(task_id, fields)

    def set_status_many(
        self,
        status: TaskStatus | str,
        items: list[tuple[str, Mapping[str, str] | None]],
    ) -> None:
        """ONE status across many tasks, each item (task_id, extra_fields).
        The single shared ``status`` argument (rather than a status per
        item) is deliberate: it keeps the written status a static literal
        at call sites, so the protocol checker
        (tpu_faas/analysis/protocol.py) can prove a batch call never
        writes a terminal status — exactly as it proves plain set_status.
        Per-item ``extra_fields`` carry the ownership lease stamps of the
        dispatcher's coalesced RUNNING flush. Default: a loop; the RESP
        client pipelines one round trip."""
        for task_id, extra in items:
            self.set_status(task_id, status, extra_fields=extra)

    def finish_task_many(
        self,
        items: list[tuple],
        inline_max: int = 0,
    ) -> None:
        """Batch finish_task, each item (task_id, status, result,
        first_wins[, result_digest, result_size]) — the two optional
        trailing elements are the result-blob plane's digest form (absent
        or None on every legacy item). Sequential per-item semantics are
        the contract — including INTRA-batch first_wins: an earlier item's
        terminal write freezes a later first_wins item for the same id,
        exactly as if the items were applied one by one. Default: a loop;
        the RESP client collapses the batch into one status pre-read for
        the first_wins slice plus one pipelined write+announce round — the
        dispatcher's result drain and its deferred-result replay ride
        this. ``inline_max`` as in finish_task (express result lane)."""
        for item in items:
            task_id, status, result, first_wins = item[:4]
            self.finish_task(
                task_id, status, result,
                first_wins=first_wins, inline_max=inline_max,
                result_digest=item[4] if len(item) > 4 else None,
                result_size=int(item[5]) if len(item) > 5 else 0,
            )

    def hset_many(self, items: list[tuple[str, Mapping[str, str]]]) -> None:
        """Field writes across many hashes. Default: a loop; the RESP client
        pipelines one round trip — the dispatcher's in-flight lease renewal
        touches every in-flight task each period and must not pay a round
        trip per task."""
        for key, fields in items:
            self.hset(key, fields)

    def get_status(self, task_id: str) -> str | None:
        return self.hget(task_id, FIELD_STATUS)

    def finish_task(
        self,
        task_id: str,
        status: TaskStatus | str,
        result: str,
        first_wins: bool = False,
        inline_max: int = 0,
        result_digest: str | None = None,
        result_size: int = 0,
    ) -> None:
        """Record a terminal status + serialized result in one write
        (reference task_dispatcher.py:153-156, 284-295).

        With ``first_wins`` the record is frozen once terminal: a second
        result cannot overwrite what a client may already have observed. The
        re-dispatch upgrade makes two results for one task possible (zombie
        worker + replacement both finish it), so dispatchers pass
        ``first_wins=True`` exactly on those suspicious paths — the common
        path (first result from the task's current worker) stays one write,
        one RTT. The read-then-write pair is not atomic, but all result
        writes flow through the single dispatcher process, so there is no
        concurrent writer to race with.

        After the write the task_id is announced on RESULTS_CHANNEL (after,
        so a woken subscriber always reads the terminal record). The write
        also stamps FIELD_FINISHED_AT (epoch seconds) so a result-TTL
        sweeper can age the record out.

        ``inline_max`` > 0 (the express result lane, opt-in at the
        producing dispatcher) makes the announce carry status + result
        inline up to that many result bytes (encode_result_announce) —
        oversized results fall back to the classic id-only payload. The
        record write above stays authoritative and still precedes the
        announce.

        ``result_digest`` (result-blob plane): the digest form — the write
        additionally records FIELD_RESULT_DIGEST/FIELD_RESULT_SIZE, and
        ``result`` is typically EMPTY (the body stays in the producing
        worker's cache until something materializes it); the announce then
        carries the digest instead of a body. None (every legacy caller)
        leaves the record and announce bytes untouched."""
        if first_wins and self._result_frozen(task_id):
            return
        now = repr(time.time())
        fields = {
            FIELD_STATUS: str(status),
            # redundant status + stamp copies, same write: let a racing
            # cancel that clobbers this terminal record restore it
            # exactly (see cancel_task's post-write repair)
            FIELD_FINAL_STATUS: str(status),
            FIELD_FINAL_AT: now,
            FIELD_RESULT: result,
            FIELD_FINISHED_AT: now,
        }
        if result_digest:
            fields[FIELD_RESULT_DIGEST] = result_digest
            fields[FIELD_RESULT_SIZE] = str(int(result_size))
        self.hset(task_id, fields)
        self.hdel(LIVE_INDEX_KEY, task_id)
        self.publish(
            RESULTS_CHANNEL,
            encode_result_announce(
                task_id, str(status), result, inline_max,
                result_digest=result_digest, result_size=result_size,
            ),
        )

    def cancel_task(
        self, task_id: str, channel: str = TASKS_CHANNEL
    ) -> str | None:
        """Best-effort queued-only cancellation: QUEUED -> CANCELLED.

        Returns the record's status AFTER the attempt — "CANCELLED" when
        this (or an earlier) call cancelled it, the unchanged status when
        the task is RUNNING or already terminal, None when unknown. Built
        from plain hash primitives so any Redis-compatible backend supports
        it; the read-then-write pair is not atomic, and both racy
        interleavings against a concurrent dispatch resolve to the truth:

        - dispatch wins, result lands AFTER this write: the finish_task
          overwrite replaces the stale CANCELLED — transiently wrong,
          converges forward;
        - dispatch wins, result lands INSIDE the read->write window (a
          sub-millisecond task): this write clobbers the landed terminal
          record, so the post-write repair below re-reads the redundant
          FIELD_FINAL_STATUS stamp (written by every finish_task in the
          same hash write as its status) and restores the record exactly —
          returning the true terminal status, not "CANCELLED".

        A record mid-create (idempotency path: status claimed by setnx,
        payload fields still in flight) is reported unknown rather than
        cancelled — there is nothing dispatchable to cancel yet, and
        writing into the creator's window could strand its record.

        Dispatchers honor the cancel through two independent signals,
        either of which suffices: intake skips any announce whose record is
        no longer QUEUED, and the "<CANCEL_ANNOUNCE_PREFIX><task_id>"
        control message published here evicts the task from pending
        structures already drained from the bus (dispatch/base.py
        note_cancelled).

        The terminal write stamps FIELD_FINISHED_AT (result-TTL sweeper
        ages cancelled records like any other terminal record), drops the
        live-index entry, and announces on RESULTS_CHANNEL so parked
        /result long-polls wake immediately."""
        current = self.get_status(task_id)
        if current is None:
            return None
        if current != str(TaskStatus.QUEUED):
            return current
        # presence probes only (hexists): the payload fields can be
        # multi-MB and must not ride the wire just to prove the record is
        # fully created
        if not self.hexists(task_id, FIELD_PARAMS):
            # status QUEUED but no payload: a claim-only hash mid-create
            # (create_task_if_absent claims status via setnx, then writes
            # the fields in a second command). Writing CANCELLED here would
            # race the creator's field write — and the ghost cleanup below
            # could strip the claimed status out from under it, leaving a
            # status-less stranded record. Nothing dispatchable exists yet:
            # report unknown; the caller may retry once the create lands.
            return None
        self.hset(
            task_id,
            {
                FIELD_STATUS: str(TaskStatus.CANCELLED),
                FIELD_FINISHED_AT: repr(time.time()),
            },
        )
        # both repair stamps in ONE round trip (small fields, never payload)
        final, final_at = self.hmget(
            task_id, [FIELD_FINAL_STATUS, FIELD_FINAL_AT]
        )
        if not self.hexists(task_id, FIELD_PARAMS):
            # the record was DELETEd inside the read->write window (ran,
            # finished, was consumed and forgotten — all sub-ms): this
            # write just resurrected it as a partial ghost, which would
            # poison a later idempotency-keyed resubmit of the same id
            # (create_task_if_absent would see the ghost and swallow the
            # new submission). Remove OUR OWN fields — not DEL the key —
            # and report unknown: a recreate requires the status field to
            # be absent (create_task_if_absent claims it with setnx), so
            # field-level removal cannot destroy a record a resubmit
            # managed to recreate, while a DELETE landing after this probe
            # removes the whole hash itself, ghost included. A concurrent
            # idempotency CLAIM landing between probe and removal survives
            # as a claim-only hash, which the gateway's adoption wait and
            # the TTL sweeper's stale-claim GC already handle. The inverse
            # order — a resubmit's claim landing BEFORE our CANCELLED write
            # so this hdel strips it — is repaired from the CREATOR's side:
            # create_task_if_absent re-checks its status after the field
            # write and re-claims (see its claim-loss repair). The residual
            # six-event interleaving (creator's re-check passing on OUR
            # CANCELLED an instant before this hdel) leaves a record a
            # client retry of the same key repairs via the same re-claim;
            # accepted: it needs three actors inside two store RTTs.
            self.hdel(task_id, FIELD_STATUS, FIELD_FINISHED_AT)
            return None
        if final is not None:
            # a result landed inside the read->write window and this write
            # just clobbered it: restore the true terminal status AND its
            # finish stamp (the result payload was never touched — our
            # write carries no FIELD_RESULT)
            restore = {FIELD_STATUS: final}
            if final_at is not None:
                restore[FIELD_FINISHED_AT] = final_at
            self.hset(task_id, restore)
            self.publish(RESULTS_CHANNEL, task_id)
            return final
        self.hdel(LIVE_INDEX_KEY, task_id)
        self.publish(channel, CANCEL_ANNOUNCE_PREFIX + task_id)
        self.publish(RESULTS_CHANNEL, task_id)
        # a cancelled graph parent never completes: poison its frontier
        # (one small-field probe for non-graph tasks, nothing more)
        self.complete_dep_many([(task_id, str(TaskStatus.CANCELLED))], channel)
        return str(TaskStatus.CANCELLED)

    def expire_task(
        self, task_id: str, channel: str = TASKS_CHANNEL
    ) -> str | None:
        """Queue-deadline shed: QUEUED -> EXPIRED (terminal).

        Returns the record's status AFTER the attempt — "EXPIRED" when this
        call (or an earlier one) shed it, the unchanged status when the
        task already left QUEUED, None when unknown. Called only by the
        dispatcher that owns the task's pending copy (claim-gated in
        shared fleets), so unlike cancel_task there is no cross-process
        writer racing the happy path — the residual interleavings are a
        concurrent gateway cancel (both write a never-ran terminal; either
        standing is truthful, and the race monitor reports it as a
        warning, not an error) and a result landing inside the
        read->write window from a zombie of a previous reclaim
        generation, repaired below exactly like cancel_task repairs it:
        the redundant FIELD_FINAL_STATUS stamp every finish_task writes
        restores the record, and the true terminal status is returned.

        The terminal write stamps FIELD_FINISHED_AT (the result-TTL
        sweeper ages EXPIRED records like any other terminal record),
        drops the live-index entry, and announces on RESULTS_CHANNEL so
        parked /result long-polls wake immediately. No cancel-style
        control message rides the tasks channel: the shedder IS the
        dispatcher holding the pending copy — there is nothing to evict
        anywhere else."""
        current = self.get_status(task_id)
        if current is None:
            return None
        if current != str(TaskStatus.QUEUED):
            return current
        self.hset(
            task_id,
            {
                FIELD_STATUS: str(TaskStatus.EXPIRED),
                FIELD_FINISHED_AT: repr(time.time()),
            },
        )
        final, final_at = self.hmget(
            task_id, [FIELD_FINAL_STATUS, FIELD_FINAL_AT]
        )
        if final is not None:
            # a result landed inside the read->write window and this write
            # clobbered it: restore the true terminal status + finish stamp
            # (the result payload was never touched — no FIELD_RESULT here)
            restore = {FIELD_STATUS: final}
            if final_at is not None:
                restore[FIELD_FINISHED_AT] = final_at
            self.hset(task_id, restore)
            self.publish(RESULTS_CHANNEL, task_id)
            return final
        self.hdel(LIVE_INDEX_KEY, task_id)
        self.publish(RESULTS_CHANNEL, task_id)
        # a shed graph parent never completes: poison its frontier
        self.complete_dep_many([(task_id, str(TaskStatus.EXPIRED))], channel)
        return str(TaskStatus.EXPIRED)

    # -- task-graph promotion plane (tpu_faas/graph) -----------------------
    def complete_dep_many(
        self,
        parents: list[tuple[str, str]],
        channel: str = TASKS_CHANNEL,
    ) -> tuple[list[str], list[str]]:
        """Walk the forward dependency edges of terminal parent writes that
        LANDED: ``parents`` is (task_id, terminal_status) pairs. Returns
        (promoted_child_ids, poisoned_child_ids).

        COMPLETED parents decrement each child's pending count — exactly
        once per edge (a write-once ``dep_done:<parent>`` claim gates the
        atomic hincrby, so a zombie's duplicate terminal write cannot
        double-count) — and a count hitting zero flips the child
        WAITING -> QUEUED and announces it on the ordinary task bus, so
        promoted children flow through intake/admission/shedding
        unchanged. A parent that reached FAILED/EXPIRED/CANCELLED instead
        POISONS its children: WAITING -> FAILED with a
        ``dep_failed:<parent>`` error payload, never dispatched — and the
        poison walks the TRANSITIVE frontier iteratively (no recursion:
        graph depth must not meet Python's stack limit).

        Either way the child's exit from WAITING is arbitrated by the
        write-once FIELD_DEP_RESOLVED claim, so a promote racing a poison
        (two parents finishing oppositely from two processes) resolves to
        exactly one writer. Non-graph parents (no FIELD_CHILDREN) cost one
        pipelined small-field read and nothing else — and dispatchers skip
        even that for tasks whose records never carried children. Built
        from pipelined primitives only, so RESP backends pay a bounded
        number of rounds per generation of the walk."""
        from tpu_faas.core.serialize import serialize  # lazy: dill is heavy

        promoted: list[str] = []
        poisoned: list[str] = []
        work = [(pid, str(status)) for pid, status in parents]
        while work:
            batch, work = work, []
            kid_lists = self.hget_many([p for p, _ in batch], FIELD_CHILDREN)
            ok_edges: list[tuple[str, str]] = []  # (parent, child)
            bad_edges: list[tuple[str, str, str]] = []  # (+ parent status)
            for (pid, status), kids in zip(batch, kid_lists):
                if not kids:
                    continue
                for child in kids.split(","):
                    if not child:
                        continue
                    if status == str(TaskStatus.COMPLETED):
                        ok_edges.append((pid, child))
                    else:
                        bad_edges.append((pid, child, status))
            if ok_edges:
                claims = self.hsetnx_many(
                    [(c, dep_done_field(p), "1") for p, c in ok_edges]
                )
                dec = [c for (_p, c), won in zip(ok_edges, claims) if won]
                counts = self.hincrby_many(
                    [(c, FIELD_PENDING_DEPS, -1) for c in dec]
                )
                ready = [c for c, n in zip(dec, counts) if n <= 0]
                if ready:
                    res = self.hsetnx_many(
                        [(c, FIELD_DEP_RESOLVED, "promote") for c in ready]
                    )
                    to_promote = [c for c, won in zip(ready, res) if won]
                    if to_promote:
                        # one pipelined status round + one announce round;
                        # the claim above makes this the ONLY writer moving
                        # these children out of WAITING
                        self.set_status_many(
                            TaskStatus.QUEUED,
                            [(c, None) for c in to_promote],
                        )
                        self.publish_many(channel, to_promote)
                        promoted.extend(to_promote)
            if bad_edges:
                claims = self.hsetnx_many(
                    [
                        (child, FIELD_DEP_RESOLVED, f"poison:{pid}")
                        for pid, child, _status in bad_edges
                    ]
                )
                items: list[tuple[str, TaskStatus, str, bool]] = []
                for (pid, child, status), won in zip(bad_edges, claims):
                    if not won:
                        # promoted, or already poisoned via another parent
                        continue
                    items.append(
                        (
                            child,
                            TaskStatus.FAILED,
                            serialize(
                                RuntimeError(
                                    f"{DEP_FAILED_PREFIX}{pid}: parent "
                                    f"reached {status}; this node was "
                                    "never dispatched"
                                )
                            ),
                            True,  # first_wins: never clobber a real result
                        )
                    )
                    poisoned.append(child)
                    work.append((child, str(TaskStatus.FAILED)))
                if items:
                    # one pipelined terminal round per poison generation
                    self.finish_task_many(items)
        return promoted, poisoned

    def resolve_waiting(
        self,
        task_id: str,
        parent_statuses: dict[str, str | None],
        channel: str = TASKS_CHANNEL,
    ) -> str | None:
        """Orphan repair for a WAITING node whose promotion was lost (its
        resolver crashed between claim and status write, or the decrement
        stream died with a dispatcher): given the node's parents' current
        statuses (None = record gone), apply the fate the graph protocol
        implies — poison if any parent is a never-ran/failed terminal OR
        vanished, promote if every parent COMPLETED, nothing if any parent
        is still live. Honors an existing FIELD_DEP_RESOLVED claim by
        re-applying ITS action (idempotent: the claimed action's writes
        converge), claims otherwise. Returns "promoted", "poisoned", or
        None (left alone). Used by the gateway's result-TTL sweeper; safe
        against a concurrent live promotion because both go through the
        same write-once claim."""
        from tpu_faas.core.serialize import serialize

        if self.hget(task_id, FIELD_STATUS) != str(TaskStatus.WAITING):
            return None
        bad_parent: str | None = None
        bad_status = "MISSING"
        all_done = True
        for pid, status in parent_statuses.items():
            if status == str(TaskStatus.COMPLETED):
                continue
            all_done = False
            if status is None or TaskStatus.terminal_str(status):
                bad_parent, bad_status = pid, status or "MISSING"
            else:
                return None  # a parent is still live: not orphaned
        claim = self.hget(task_id, FIELD_DEP_RESOLVED)
        if claim is None:
            action = "promote" if all_done else (
                f"poison:{bad_parent}" if bad_parent is not None else None
            )
            if action is None:
                return None
            created, claim = self.setnx_field(
                task_id, FIELD_DEP_RESOLVED, action
            )
        if claim == "promote":
            if self.hget(task_id, FIELD_STATUS) == str(TaskStatus.WAITING):
                self.set_status(task_id, TaskStatus.QUEUED)
                self.publish(channel, task_id)
            return "promoted"
        parent = claim.split(":", 1)[1] if ":" in claim else "?"
        self.finish_task(
            task_id,
            TaskStatus.FAILED,
            serialize(
                RuntimeError(
                    f"{DEP_FAILED_PREFIX}{parent}: parent reached "
                    f"{bad_status}; this node was never dispatched"
                )
            ),
            first_wins=True,
        )
        # the repaired node may itself have children: poison them too
        self.complete_dep_many(
            [(task_id, str(TaskStatus.FAILED))], channel
        )
        return "poisoned"

    def request_kill(
        self, task_id: str, channel: str = TASKS_CHANNEL
    ) -> None:
        """Publish the force-cancel control message for a RUNNING task
        (see KILL_ANNOUNCE_PREFIX). Fire-and-forget like every announce."""
        self.publish(channel, KILL_ANNOUNCE_PREFIX + task_id)

    def _result_frozen(self, task_id: str) -> bool:
        """first_wins guard: True when the record must not be overwritten —
        already terminal, or absent (a record the client consumed and
        DELETEd must not be resurrected as a partial status+result hash by a
        zombie's late write).

        CANCELLED/EXPIRED do NOT freeze: a result can only reach a
        never-ran terminal record when that write LOST its race and the
        task actually executed (a genuinely-cancelled or shed task never
        dispatches, so nothing can produce a result for it) — e.g. the
        lost-race task's worker was purged, the reclaimed copy correctly
        dropped, and the zombie then delivered the genuine result via a
        first_wins path. Truth wins: freezing would pin 'never ran' over
        real side effects."""
        current = self.get_status(task_id)
        if current in (str(TaskStatus.CANCELLED), str(TaskStatus.EXPIRED)):
            return False
        # unknown=True: absent records and foreign status strings are
        # frozen — never overwrite what can't be parsed
        return TaskStatus.terminal_str(current, unknown=True)

    def get_result(self, task_id: str) -> tuple[str | None, str | None]:
        """(status, result) in one round-trip — the client poll hot path."""
        fields = self.hgetall(task_id)
        return fields.get(FIELD_STATUS), fields.get(FIELD_RESULT)

    def declare_redispatch(self, task_id: str) -> None:
        """Protocol-checker hook: the caller is about to re-mark ``task_id``
        RUNNING because it was reclaimed from a purged worker. No-op on real
        stores; ``racecheck.RaceCheckStore`` overrides it so its monitor can
        tell deliberate re-dispatch from a double-dispatch bug."""

    def declare_replica(self, task_id: str) -> None:
        """Protocol-checker hook (speculation plane, tpu_faas/spec): the
        caller is about to dispatch a HEDGE replica of a still-running
        ``task_id`` — a deliberate second RUNNING mark whose result race
        is arbitrated by finish_task's first-wins contract. No-op on real
        stores; ``racecheck.RaceCheckStore`` overrides it so its monitor
        can tell a declared hedge from a double-dispatch bug and prove no
        double-completion at runtime."""

    def __enter__(self) -> "TaskStore":
        return self

    def __exit__(self, *exc: object) -> None:
        self.close()
