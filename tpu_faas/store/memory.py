"""In-process task store: dict-of-hashes + per-channel fan-out queues.

Thread-safe so a gateway thread, dispatcher thread, and test driver can share
one instance. Pub/sub preserves the reference's fire-and-forget semantics:
messages published while nobody is subscribed are dropped, and each subscriber
gets its own copy (Redis pub/sub behavior the dispatcher relies on — see
SURVEY §5.4 on stranded announcements).
"""

from __future__ import annotations

import queue
import socket
import threading
from typing import Mapping

from tpu_faas.store.base import Subscription, TaskStore


class _MemorySubscription(Subscription):
    def __init__(self, store: "MemoryStore", channel: str) -> None:
        self._store = store
        self._channel = channel
        self._queue: queue.Queue[str] = queue.Queue()
        self._closed = False
        #: lazy self-pipe (socketpair) backing fileno(): created only when
        #: an event-driven consumer asks for it, so the hundreds of
        #: subscriptions a test run creates don't each burn two fds
        self._pipe: tuple[socket.socket, socket.socket] | None = None

    def fileno(self) -> int | None:
        """Readability signal for event-driven serve loops (see
        Subscription.fileno): a self-pipe the publish path pokes. Created
        on first ask; publishes before that never signal (the consumer
        registered the fd before any message it cares about)."""
        if self._closed:
            return None
        if self._pipe is None:
            r, w = socket.socketpair()
            r.setblocking(False)
            w.setblocking(False)
            self._pipe = (r, w)
        return self._pipe[0].fileno()

    def _signal(self) -> None:
        if self._pipe is not None:
            try:
                self._pipe[1].send(b"\x01")
            except (BlockingIOError, OSError):
                pass  # pipe full (consumer behind) or closed: both fine

    def _drain_signal(self) -> None:
        if self._pipe is not None:
            try:
                while self._pipe[0].recv(4096):
                    pass
            except (BlockingIOError, OSError):
                pass

    def get_message(self, timeout: float = 0.0) -> str | None:
        try:
            if timeout > 0:
                return self._queue.get(timeout=timeout)
            return self._queue.get_nowait()
        except queue.Empty:
            # empty queue: drain the wake pipe, then re-check once — a
            # publish landing between the get and the drain leaves its
            # byte for the next poll, so a wake can be spurious but never
            # lost
            self._drain_signal()
            try:
                return self._queue.get_nowait()
            except queue.Empty:
                return None

    def close(self) -> None:
        if not self._closed:
            self._closed = True
            self._store._unsubscribe(self._channel, self)
            if self._pipe is not None:
                for s in self._pipe:
                    try:
                        s.close()
                    except OSError:
                        pass
                self._pipe = None


class MemoryStore(TaskStore):
    def __init__(self, snapshot_path: str | None = None) -> None:
        self._lock = threading.RLock()
        self._hashes: dict[str, dict[str, str]] = {}
        self._subs: dict[str, list[_MemorySubscription]] = {}
        # bounded announce-replay ring, same semantics as the RESP
        # servers' (store/replication.py AnnounceRing): lets dispatcher
        # failover re-arm logic be unit-tested without sockets
        from tpu_faas.store.replication import AnnounceRing

        self._ring = AnnounceRing()
        self._ring_offset = 0
        self.snapshot_path = snapshot_path
        if snapshot_path is not None:
            self.load(snapshot_path)

    # -- raw hash ops ------------------------------------------------------
    def hset(self, key: str, fields: Mapping[str, str]) -> None:
        with self._lock:
            self._hashes.setdefault(key, {}).update(fields)

    def setnx_field(
        self, key: str, field: str, value: str
    ) -> tuple[bool, str]:
        # atomic under the store lock (the base default's check-then-set
        # would race between gateway executor threads)
        with self._lock:
            h = self._hashes.setdefault(key, {})
            if field in h:
                return False, h[field]
            h[field] = value
            return True, value

    def hincrby(self, key: str, field: str, delta: int) -> int:
        # atomic under the store lock (the base default's read-modify-write
        # would lose decrements between gateway/dispatcher threads)
        with self._lock:
            h = self._hashes.setdefault(key, {})
            try:
                value = int(h.get(field, "0"))
            except ValueError:
                value = 0
            value += int(delta)
            h[field] = str(value)
            return value

    def hget(self, key: str, field: str) -> str | None:
        with self._lock:
            return self._hashes.get(key, {}).get(field)

    def hgetall(self, key: str) -> dict[str, str]:
        with self._lock:
            return dict(self._hashes.get(key, {}))

    def hdel(self, key: str, *fields: str) -> None:
        with self._lock:
            h = self._hashes.get(key)
            if h is None:
                return
            for f in fields:
                h.pop(f, None)
            if not h:  # Redis semantics: empty hash = absent key
                self._hashes.pop(key, None)

    def delete(self, key: str) -> None:
        with self._lock:
            self._hashes.pop(key, None)

    def keys(self) -> list[str]:
        with self._lock:
            return list(self._hashes)

    # -- announce bus ------------------------------------------------------
    def publish(self, channel: str, payload: str) -> None:
        with self._lock:
            subs = list(self._subs.get(channel, ()))
            self._ring_offset += 1
            self._ring.append(self._ring_offset, channel, payload)
        for sub in subs:
            sub._queue.put(payload)
            sub._signal()

    def replay_announces(
        self, after: int
    ) -> tuple[int, list[tuple[str, str]]]:
        with self._lock:
            tail = self._ring.tail
            if after < 0:
                return tail, []
            return tail, [
                (ch, payload) for _off, ch, payload in self._ring.since(after)
            ]

    def subscribe(self, channel: str) -> Subscription:
        sub = _MemorySubscription(self, channel)
        with self._lock:
            self._subs.setdefault(channel, []).append(sub)
        return sub

    def _unsubscribe(self, channel: str, sub: _MemorySubscription) -> None:
        with self._lock:
            subs = self._subs.get(channel)
            if subs and sub in subs:
                subs.remove(sub)

    # -- checkpoint/resume -------------------------------------------------
    def save(self, path: str | None = None) -> None:
        """Checkpoint all hashes (snapshot.py RESP-log format) to `path`, or
        to the configured ``snapshot_path`` when omitted — same contract as
        RespStore.save() so backends stay URL-swappable."""
        from tpu_faas.store import snapshot

        target = path if path is not None else self.snapshot_path
        if target is None:
            raise ValueError("save() needs a path (no snapshot_path configured)")
        with self._lock:
            hashes = {k: dict(v) for k, v in self._hashes.items()}
        snapshot.save_file(target, hashes)

    def load(self, path: str) -> None:
        """Replace contents with a snapshot file (missing file = empty)."""
        from tpu_faas.store import snapshot

        hashes = snapshot.load_file(path)
        with self._lock:
            self._hashes = hashes

    # -- admin -------------------------------------------------------------
    def flush(self) -> None:
        with self._lock:
            self._hashes.clear()

    def close(self) -> None:
        pass
