"""Python asyncio task-store server speaking the RESP2 subset.

This is the portable fallback for the native C++ server (native/store_server.cpp);
both expose the identical protocol, so `RespStore` clients and a real Redis are
interchangeable. One asyncio task per connection; state is a plain dict guarded
by the event loop's single-threadedness.

Supported commands (the set the framework + the reference's usage of Redis
require): PING, SELECT (accepted, ignored — the reference pins db=1,
task_dispatcher.py:32), HSET, HSETNX, HGET, HEXISTS, HMGET, HGETALL, DEL, KEYS, PUBLISH, SUBSCRIBE,
UNSUBSCRIBE, FLUSHDB, SAVE, QUIT, SHUTDOWN.

Checkpoint/resume: ``--snapshot PATH`` loads PATH at startup and saves to it
on SAVE (no path argument), on SHUTDOWN/stop, and every ``--autosave`` seconds
while dirty. Format: tpu_faas/store/snapshot.py (replayable RESP HSET log,
shared with the native server).

Run: ``python -m tpu_faas.store.server --port 6380``.
"""

from __future__ import annotations

import argparse
import asyncio
import fnmatch
import signal
from typing import Iterable

from tpu_faas.store import resp, snapshot


class StoreState:
    def __init__(self) -> None:
        self.hashes: dict[str, dict[str, str]] = {}
        # channel -> set of subscriber StreamWriters
        self.subs: dict[str, set[asyncio.StreamWriter]] = {}
        # all open connections, so stop() can close them (Python 3.12's
        # Server.wait_closed() blocks until every handler returns)
        self.conns: set[asyncio.StreamWriter] = set()


class StoreServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 6380,
        snapshot_path: str | None = None,
        autosave_interval: float = 0.0,
    ) -> None:
        self.host = host
        self.port = port
        self.snapshot_path = snapshot_path
        self.autosave_interval = autosave_interval
        self.state = StoreState()
        self._server: asyncio.AbstractServer | None = None
        self._shutdown = asyncio.Event()
        self._dirty = False
        self._autosave_task: asyncio.Task | None = None

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> None:
        if self.snapshot_path is not None:
            self.state.hashes = snapshot.load_file(self.snapshot_path)
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port
        )
        # If port was 0, record the actual bound port.
        self.port = self._server.sockets[0].getsockname()[1]
        if self.snapshot_path is not None and self.autosave_interval > 0:
            self._autosave_task = asyncio.create_task(self._autosave_loop())

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        async with self._server:
            await self._shutdown.wait()
            # Drop live client connections before the async-with closes the
            # server: since Python 3.12 Server.wait_closed() waits for every
            # connection handler to finish, so a SHUTDOWN with an idle
            # subscriber still attached would hang the process forever.
            if self._autosave_task is not None:
                self._autosave_task.cancel()
            for w in list(self.state.conns):
                w.close()

    async def stop(self) -> None:
        try:
            self._save_if_configured()
        except OSError as exc:
            print(f"shutdown snapshot save failed: {exc}", flush=True)
        if self._autosave_task is not None:
            self._autosave_task.cancel()
        self._shutdown.set()
        if self._server is not None:
            self._server.close()
        for w in list(self.state.conns):
            w.close()
        if self._server is not None:
            await self._server.wait_closed()

    # -- checkpointing -----------------------------------------------------
    def _save_if_configured(self) -> None:
        if self.snapshot_path is not None:
            snapshot.save_file(self.snapshot_path, self.state.hashes)
            self._dirty = False

    async def _autosave_loop(self) -> None:
        while True:
            await asyncio.sleep(self.autosave_interval)
            if self._dirty:
                try:
                    self._save_if_configured()
                except OSError as exc:
                    # transient failure (disk full, dir unwritable) must not
                    # kill autosave for the rest of the server's life
                    print(f"autosave failed (will retry): {exc}", flush=True)

    # -- connection handling ----------------------------------------------
    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        parser = resp.RespParser()
        subscribed: set[str] = set()
        self.state.conns.add(writer)
        try:
            while not reader.at_eof():
                data = await reader.read(65536)
                if not data:
                    break
                parser.feed(data)
                try:
                    cmds = parser.pop_all()
                except resp.ProtocolError as exc:
                    # non-RESP bytes (health probe, stray HTTP, telnet):
                    # reply with an error and drop the connection
                    writer.write(resp.encode_error(str(exc)))
                    await writer.drain()
                    return
                for cmd in cmds:
                    if not isinstance(cmd, list) or not cmd:
                        writer.write(resp.encode_error("protocol error"))
                        continue
                    keep_going = await self._dispatch(cmd, writer, subscribed)
                    if not keep_going:
                        await writer.drain()
                        return
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError, asyncio.CancelledError):
            pass
        finally:
            self.state.conns.discard(writer)
            for ch in subscribed:
                self.state.subs.get(ch, set()).discard(writer)
            writer.close()

    async def _dispatch(
        self,
        cmd: list[str],
        writer: asyncio.StreamWriter,
        subscribed: set[str],
    ) -> bool:
        name, args = cmd[0].upper(), cmd[1:]
        st = self.state
        if name == "PING":
            writer.write(resp.encode_simple("PONG"))
        elif name == "SELECT":
            writer.write(resp.encode_simple("OK"))
        elif name == "INFO":
            # Redis-style ops introspection: "key:value" lines in one bulk
            n_subs = sum(len(ws) for ws in st.subs.values())
            lines = [
                "server:tpu-faas-store-python",
                f"keys:{len(st.hashes)}",
                f"subscribers:{n_subs}",
                f"channels:{len(st.subs)}",
                f"dirty:{int(self._dirty)}",
                f"snapshot_path:{self.snapshot_path or ''}",
            ]
            writer.write(resp.encode_bulk("\n".join(lines)))
        elif name == "HSET":
            if len(args) < 3 or len(args) % 2 == 0:
                writer.write(resp.encode_error("wrong number of arguments for HSET"))
                return True
            h = st.hashes.setdefault(args[0], {})
            added = 0
            for f, v in zip(args[1::2], args[2::2]):
                if f not in h:
                    added += 1
                h[f] = v
            self._dirty = True
            writer.write(resp.encode_integer(added))
        elif name == "HGET":
            if len(args) != 2:
                writer.write(resp.encode_error("wrong number of arguments for HGET"))
                return True
            writer.write(resp.encode_bulk(st.hashes.get(args[0], {}).get(args[1])))
        elif name == "HEXISTS":
            if len(args) != 2:
                writer.write(
                    resp.encode_error("wrong number of arguments for HEXISTS")
                )
                return True
            writer.write(
                resp.encode_integer(
                    1 if args[1] in st.hashes.get(args[0], {}) else 0
                )
            )
        elif name == "HSETNX":
            if len(args) != 3:
                writer.write(
                    resp.encode_error("wrong number of arguments for HSETNX")
                )
                return True
            h = st.hashes.setdefault(args[0], {})
            if args[1] in h:
                writer.write(resp.encode_integer(0))
            else:
                h[args[1]] = args[2]
                self._dirty = True
                writer.write(resp.encode_integer(1))
        elif name == "HDEL":
            if len(args) < 2:
                writer.write(resp.encode_error("wrong number of arguments for HDEL"))
                return True
            h = st.hashes.get(args[0])
            removed = 0
            if h is not None:
                for f in args[1:]:
                    if f in h:
                        del h[f]
                        removed += 1
                if not h:  # Redis semantics: empty hash = absent key
                    del st.hashes[args[0]]
            if removed:
                self._dirty = True
            writer.write(resp.encode_integer(removed))
        elif name == "HMGET":
            if len(args) < 2:
                writer.write(resp.encode_error("wrong number of arguments for HMGET"))
                return True
            h = st.hashes.get(args[0], {})
            writer.write(
                resp.encode_array([resp.encode_bulk(h.get(f)) for f in args[1:]])
            )
        elif name == "HGETALL":
            h = st.hashes.get(args[0], {}) if args else {}
            flat: list[bytes] = []
            for f, v in h.items():
                flat.append(resp.encode_bulk(f))
                flat.append(resp.encode_bulk(v))
            writer.write(resp.encode_array(flat))
        elif name == "DEL":
            n = 0
            for k in args:
                if st.hashes.pop(k, None) is not None:
                    n += 1
            self._dirty = self._dirty or n > 0
            writer.write(resp.encode_integer(n))
        elif name == "KEYS":
            pattern = args[0] if args else "*"
            ks = [k for k in st.hashes if fnmatch.fnmatchcase(k, pattern)]
            writer.write(resp.encode_array([resp.encode_bulk(k) for k in ks]))
        elif name == "PUBLISH":
            if len(args) != 2:
                writer.write(resp.encode_error("wrong number of arguments for PUBLISH"))
                return True
            n = await self._publish(args[0], args[1])
            writer.write(resp.encode_integer(n))
        elif name == "SUBSCRIBE":
            for ch in args:
                subscribed.add(ch)
                st.subs.setdefault(ch, set()).add(writer)
                writer.write(
                    resp.encode_array(
                        [
                            resp.encode_bulk("subscribe"),
                            resp.encode_bulk(ch),
                            resp.encode_integer(len(subscribed)),
                        ]
                    )
                )
        elif name == "UNSUBSCRIBE":
            channels: Iterable[str] = args or list(subscribed)
            for ch in channels:
                subscribed.discard(ch)
                st.subs.get(ch, set()).discard(writer)
                writer.write(
                    resp.encode_array(
                        [
                            resp.encode_bulk("unsubscribe"),
                            resp.encode_bulk(ch),
                            resp.encode_integer(len(subscribed)),
                        ]
                    )
                )
        elif name == "FLUSHDB":
            st.hashes.clear()
            self._dirty = True
            writer.write(resp.encode_simple("OK"))
        elif name == "SAVE":
            target = args[0] if args else self.snapshot_path
            if target is None:
                writer.write(
                    resp.encode_error("SAVE needs a path (no --snapshot configured)")
                )
                return True
            try:
                snapshot.save_file(target, st.hashes)
            except OSError as exc:
                writer.write(resp.encode_error(f"SAVE failed: {exc}"))
                return True
            if target == self.snapshot_path:
                self._dirty = False
            writer.write(resp.encode_simple("OK"))
        elif name == "QUIT":
            writer.write(resp.encode_simple("OK"))
            return False
        elif name == "SHUTDOWN":
            try:
                self._save_if_configured()
            except OSError as exc:
                # like Redis: a failed save aborts the shutdown and the
                # client is told, rather than dying with unsaved state or
                # silently staying up
                writer.write(
                    resp.encode_error(f"SHUTDOWN aborted, save failed: {exc}")
                )
                return True
            self._shutdown.set()
            return False
        else:
            writer.write(resp.encode_error(f"unknown command '{name}'"))
        return True

    async def _publish(self, channel: str, payload: str) -> int:
        """Fan a message out to subscribers; fire-and-forget like Redis pub/sub."""
        receivers = list(self.state.subs.get(channel, ()))
        msg = resp.encode_array(
            [
                resp.encode_bulk("message"),
                resp.encode_bulk(channel),
                resp.encode_bulk(payload),
            ]
        )
        n = 0
        for w in receivers:
            if w.is_closing():
                self.state.subs[channel].discard(w)
                continue
            try:
                w.write(msg)
                n += 1
            except (ConnectionResetError, BrokenPipeError):
                self.state.subs[channel].discard(w)
        return n


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(description="tpu-faas task store server (Python)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=6380)
    ap.add_argument(
        "--snapshot",
        default=None,
        help="checkpoint file: loaded at startup, written on SAVE/SHUTDOWN",
    )
    ap.add_argument(
        "--autosave",
        type=float,
        default=0.0,
        help="seconds between automatic snapshots while dirty (0 = off)",
    )
    ns = ap.parse_args(argv)

    async def run() -> None:
        server = StoreServer(
            ns.host, ns.port, snapshot_path=ns.snapshot, autosave_interval=ns.autosave
        )
        await server.start()
        # graceful kill/Ctrl-C must checkpoint, like the native server's
        # SIGTERM/SIGINT handlers — otherwise everything since the last
        # autosave is lost on `systemctl stop`
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(
                sig, lambda: asyncio.ensure_future(server.stop())
            )
        print(f"tpu-faas store listening on {server.host}:{server.port}", flush=True)
        await server.serve_forever()

    asyncio.run(run())


if __name__ == "__main__":
    main()
