"""Python asyncio task-store server speaking the RESP2 subset.

This is the portable fallback for the native C++ server (native/store_server.cpp);
both expose the identical protocol, so `RespStore` clients and a real Redis are
interchangeable. One asyncio task per connection; state is a plain dict guarded
by the event loop's single-threadedness.

Supported commands (the set the framework + the reference's usage of Redis
require): PING, SELECT (accepted, ignored — the reference pins db=1,
task_dispatcher.py:32), HSET, HSETNX, HGET, HEXISTS, HMGET, HGETALL, DEL, KEYS, PUBLISH, SUBSCRIBE,
UNSUBSCRIBE, FLUSHDB, SAVE, QUIT, SHUTDOWN.

Checkpoint/resume: ``--snapshot PATH`` loads PATH at startup and saves to it
on SAVE (no path argument), on SHUTDOWN/stop, and every ``--autosave`` seconds
while dirty. Format: tpu_faas/store/snapshot.py (replayable RESP command log
with DEL records, shared with the native server and the replication sync).

High availability: ``--replica-of host:port`` starts this server as a
read-only replica tailing that primary's write stream
(tpu_faas/store/replication.py): full snapshot sync, then every mutating
command in order, replicated PUBLISHes fanning out to local subscribers
and landing in the bounded announce ring that backs ``REPLAY``. A replica
accepts writes only after an explicit ``PROMOTE`` (which bumps the fencing
epoch); ``--epoch N`` restarts a previously-promoted store with its epoch
intact.

Run: ``python -m tpu_faas.store.server --port 6380``.
"""

from __future__ import annotations

import argparse
import asyncio
import fnmatch
import signal
from typing import Iterable

from tpu_faas.store import resp, snapshot
from tpu_faas.store.replication import (
    FENCED_ERR,
    MUTATING_COMMANDS,
    READONLY_ERR,
    AnnounceRing,
    ReplicaLink,
    ReplicationState,
    parse_endpoint,
)

#: Bound on the deleted-keys set carried into the next snapshot: the
#: tombstones exist so a checkpoint can EXPRESS deletions (snapshot.py);
#: past the cap the oldest are dropped — they are then simply absent from
#: the dump, which is the pre-tombstone behavior, never wrong state.
_TOMBSTONE_CAP = 100_000

#: Capability tokens the CAPS command advertises (store/client.py's
#: binary-batch negotiation): command-surface extensions beyond the
#: plain-Redis subset. "binbatch" = the MHGETALL/MFINISH aggregate forms.
#: A real Redis answers CAPS with -ERR unknown command, which the client
#: reads as "no capabilities" — negotiation is safe against any backend.
STORE_CAPS = ("binbatch",)


class StoreState:
    def __init__(self) -> None:
        self.hashes: dict[str, dict[str, str]] = {}
        # channel -> set of subscriber StreamWriters
        self.subs: dict[str, set[asyncio.StreamWriter]] = {}
        # all open connections, so stop() can close them (Python 3.12's
        # Server.wait_closed() blocks until every handler returns)
        self.conns: set[asyncio.StreamWriter] = set()
        # keys deleted since the last checkpoint, insertion-ordered (a
        # dict so the cap can drop oldest-first); written as DEL records
        # into the next snapshot so a replayed log can't resurrect them
        self.tombstones: dict[str, None] = {}


class StoreServer:
    def __init__(
        self,
        host: str = "127.0.0.1",
        port: int = 6380,
        snapshot_path: str | None = None,
        autosave_interval: float = 0.0,
        replica_of: tuple[str, int] | str | None = None,
        epoch: int = 0,
        announce_ring: int = 0,
        health_port: int | None = None,
    ) -> None:
        self.host = host
        self.port = port
        self.snapshot_path = snapshot_path
        self.autosave_interval = autosave_interval
        #: HTTP liveness/readiness surface (``--health-port``): /healthz
        #: answers 200 while the process serves; /readyz answers 503
        #: while this store cannot take writes (loading its snapshot,
        #: unpromoted replica, fenced stale primary) — parity with the
        #: gateway/dispatcher stats servers, so fleet orchestration can
        #: route and restart shards like every other process. None = off.
        self.health_port = health_port
        self._health_server: asyncio.AbstractServer | None = None
        #: True until the startup snapshot load (if any) completed — the
        #: health listener binds FIRST so orchestration sees
        #: "alive but not ready" during a long load instead of a dead port
        self._loading = True
        self.state = StoreState()
        self._server: asyncio.AbstractServer | None = None
        self._shutdown = asyncio.Event()
        self._dirty = False
        self._autosave_task: asyncio.Task | None = None
        if isinstance(replica_of, str):
            replica_of = parse_endpoint(replica_of)
        self.replica_of = replica_of
        self.repl = ReplicationState(
            role="replica" if replica_of is not None else "primary",
            epoch=int(epoch),
        )
        if announce_ring > 0:
            self.repl.ring = AnnounceRing(announce_ring)
        self._link: ReplicaLink | None = None
        self._link_down_logged = False

    # -- lifecycle ---------------------------------------------------------
    async def start(self) -> None:
        if self.health_port is not None:
            # before the snapshot load: a long load must read as
            # alive-but-not-ready, not as a dead process
            self._health_server = await asyncio.start_server(
                self._handle_health, self.host, self.health_port
            )
            self.health_port = self._health_server.sockets[0].getsockname()[1]
        if self.snapshot_path is not None:
            # off-loop: a synchronous multi-GB load would block this very
            # event loop, so the just-bound health listener could accept
            # but never ANSWER — orchestration liveness probes would time
            # out and kill the process mid-load, the exact crash loop the
            # bind-before-load ordering exists to prevent
            self.state.hashes = await asyncio.get_running_loop().run_in_executor(
                None, snapshot.load_file, self.snapshot_path
            )
        self._loading = False
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port
        )
        # If port was 0, record the actual bound port.
        self.port = self._server.sockets[0].getsockname()[1]
        if self.replica_of is not None:
            self._link = ReplicaLink(self, *self.replica_of)
            self._link.start()
        if self.snapshot_path is not None and self.autosave_interval > 0:
            self._autosave_task = asyncio.create_task(self._autosave_loop())

    async def serve_forever(self) -> None:
        if self._server is None:
            await self.start()
        async with self._server:
            await self._shutdown.wait()
            # Drop live client connections before the async-with closes the
            # server: since Python 3.12 Server.wait_closed() waits for every
            # connection handler to finish, so a SHUTDOWN with an idle
            # subscriber still attached would hang the process forever.
            if self._autosave_task is not None:
                self._autosave_task.cancel()
            if self._link is not None:
                self._link.stop()
            if self._health_server is not None:
                self._health_server.close()
            for w in list(self.state.conns):
                w.close()

    async def stop(self) -> None:
        try:
            self._save_if_configured()
        except OSError as exc:
            print(f"shutdown snapshot save failed: {exc}", flush=True)
        if self._autosave_task is not None:
            self._autosave_task.cancel()
        if self._link is not None:
            self._link.stop()
        self._shutdown.set()
        if self._health_server is not None:
            self._health_server.close()
        if self._server is not None:
            self._server.close()
        for w in list(self.state.conns):
            w.close()
        if self._server is not None:
            await self._server.wait_closed()

    # -- HTTP health surface (--health-port) -------------------------------
    def readiness(self) -> tuple[bool, str]:
        """(ready, reason) for /readyz: ready iff this store can take
        writes RIGHT NOW. A loading snapshot, an unpromoted replica, and
        a fenced stale primary all serve 503 — route elsewhere, don't
        restart (liveness stays unconditional on /healthz)."""
        if self._loading:
            return False, "loading_snapshot"
        if self.repl.fenced:
            return False, "fenced"
        if self.repl.role == "replica":
            return False, "replica"
        return True, "ok"

    async def _handle_health(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        """Minimal HTTP/1.1 for the two probe paths — deliberately not an
        HTTP framework: the store process must not grow a dependency (or
        a thread) for two constant-shaped replies."""
        import json

        try:
            # bounded read: a connection that never sends a full request
            # (port scanner, half-open LB probe) must not pin a coroutine
            # + fd for its TCP lifetime
            async def _read_request() -> bytes:
                line = await reader.readline()
                while True:  # drain headers; probes send no body
                    h = await reader.readline()
                    if h in (b"\r\n", b"\n", b""):
                        return line

            request_line = await asyncio.wait_for(_read_request(), timeout=5.0)
            parts = request_line.split()
            path = parts[1].decode("ascii", "replace") if len(parts) > 1 else "/"
            if path == "/healthz":
                status, body = 200, b'{"ok": true}'
            elif path == "/readyz":
                ready, reason = self.readiness()
                status = 200 if ready else 503
                body = json.dumps(
                    {"ready": ready, "reason": reason}
                ).encode()
            else:
                status, body = 404, b'{"error": "not found"}'
            reason_phrase = {200: "OK", 404: "Not Found", 503: "Service Unavailable"}[status]
            writer.write(
                (
                    f"HTTP/1.1 {status} {reason_phrase}\r\n"
                    "Content-Type: application/json\r\n"
                    f"Content-Length: {len(body)}\r\n"
                    "Connection: close\r\n\r\n"
                ).encode()
                + body
            )
            await writer.drain()
        except (
            ConnectionError,
            ValueError,  # readline LimitOverrun on a >64 KiB garbage line
            asyncio.IncompleteReadError,
            asyncio.TimeoutError,
        ):
            pass
        finally:
            writer.close()

    # -- checkpointing -----------------------------------------------------
    def _save_if_configured(self) -> None:
        # deliberate blocking checkpoint: state mutates ONLY on this event
        # loop, so blocking it for the dump's duration IS the point-in-time
        # consistency mechanism — same contract as Redis SAVE (an async
        # BGSAVE would need copy-on-write state this server doesn't keep)
        if self.snapshot_path is not None:
            snapshot.save_file(  # faas: allow(eventloop.blocking-file-io)
                self.snapshot_path,
                self.state.hashes,
                deleted=list(self.state.tombstones),
            )
            # the file is now a complete point-in-time dump WITH these
            # deletions recorded; start the next delta window empty
            self.state.tombstones.clear()
            self._dirty = False

    # -- replication plumbing ----------------------------------------------
    def _note_deleted(self, key: str) -> None:
        """A key vanished (DEL, or HDEL emptied it): tombstone it for the
        next snapshot so a replayed log cannot resurrect it."""
        ts = self.state.tombstones
        ts.pop(key, None)  # re-insert at the tail (ordered dict semantics)
        ts[key] = None
        while len(ts) > _TOMBSTONE_CAP:
            ts.pop(next(iter(ts)))

    def _replicate(self, parts: list[str]) -> None:
        """A mutating command was applied: advance the replication offset,
        record PUBLISHes in the announce ring, and forward the command
        verbatim to every attached replica stream — BEFORE the caller's
        reply is written, so an acknowledged write has at least reached
        the kernel send buffer toward each live replica when this
        process dies (the zero-loss-failover half-promise; the rescan and
        announce replay cover the rest)."""
        self.repl.offset += 1
        name = parts[0].upper()
        if name == "PUBLISH":
            self.repl.ring.append(self.repl.offset, parts[1], parts[2])
        elif name == "FLUSHDB":
            self.repl.ring.clear()
            self.state.tombstones.clear()
        if self.repl.replicas:
            data = resp.encode_command(*parts)
            for w in list(self.repl.replicas):
                if w.is_closing():
                    self.repl.replicas.pop(w, None)
                    continue
                try:
                    w.write(data)
                except (ConnectionResetError, BrokenPipeError):
                    self.repl.replicas.pop(w, None)

    def apply_replicated(self, cmd: list) -> None:
        """Replica side: apply one command from the primary's stream.
        Commands arrive in primary execution order; anything outside the
        mutating set is ignored (future-proofing — an upgraded primary
        must not crash an older replica). Chained replication falls out:
        applying re-forwards through _replicate to OUR replicas."""
        if not cmd or not isinstance(cmd[0], str):
            return
        name, args = cmd[0].upper(), [str(a) for a in cmd[1:]]
        if name not in MUTATING_COMMANDS:
            return
        st = self.state
        if name == "HSET":
            h = st.hashes.setdefault(args[0], {})
            for f, v in zip(args[1::2], args[2::2]):
                h[f] = v
        elif name == "HSETNX":
            h = st.hashes.setdefault(args[0], {})
            h.setdefault(args[1], args[2])
        elif name == "HINCRBY":
            h = st.hashes.setdefault(args[0], {})
            try:
                value = int(h.get(args[1], "0")) + int(args[2])
            except ValueError:
                value = 0
            h[args[1]] = str(value)
        elif name == "HDEL":
            h = st.hashes.get(args[0])
            if h is not None:
                for f in args[1:]:
                    h.pop(f, None)
                if not h:
                    del st.hashes[args[0]]
                    self._note_deleted(args[0])
        elif name == "DEL":
            for k in args:
                if st.hashes.pop(k, None) is not None:
                    self._note_deleted(k)
        elif name == "PUBLISH":
            # local fan-out (fire-and-forget, like the primary's own) so
            # subscribers attached to the replica see the announce stream
            asyncio.ensure_future(self._publish(args[0], args[1]))
        elif name == "FLUSHDB":
            st.hashes.clear()
        self._dirty = True
        self._replicate([name, *args])

    def load_replicated_snapshot(
        self, hashes: dict[str, dict[str, str]], epoch: int, offset: int
    ) -> None:
        """Replica side: adopt the primary's full-sync state (REPLSYNC
        header + snapshot). Replaces local hashes wholesale — a fresh
        point-in-time dump needs no tombstones."""
        self.state.hashes = hashes
        self.state.tombstones.clear()
        self.repl.epoch = epoch
        self.repl.offset = offset
        self._dirty = True
        self._link_down_logged = False
        print(
            f"replica synced from {self.replica_of}: epoch={epoch} "
            f"offset={offset} keys={len(hashes)}",
            flush=True,
        )

    def note_link_down(self, exc: BaseException) -> None:
        if not self._link_down_logged:
            self._link_down_logged = True
            print(
                f"replication link to {self.replica_of} lost ({exc}); "
                "retrying until promoted or the primary returns",
                flush=True,
            )

    def promote(self) -> int:
        """Replica -> primary: stop tailing, take writes, bump the fencing
        epoch. Idempotent on an already-primary server (epoch unchanged —
        a retried PROMOTE must not burn fencing generations)."""
        if self.repl.role != "replica":
            return self.repl.epoch
        if self._link is not None:
            self._link.stop()
            self._link = None
        self.repl.role = "primary"
        self.repl.epoch += 1
        print(f"promoted to primary (epoch {self.repl.epoch})", flush=True)
        return self.repl.epoch

    async def _autosave_loop(self) -> None:
        while True:
            await asyncio.sleep(self.autosave_interval)
            if self._dirty:
                try:
                    self._save_if_configured()
                except OSError as exc:
                    # transient failure (disk full, dir unwritable) must not
                    # kill autosave for the rest of the server's life
                    print(f"autosave failed (will retry): {exc}", flush=True)

    # -- connection handling ----------------------------------------------
    async def _handle_conn(
        self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter
    ) -> None:
        parser = resp.RespParser()
        subscribed: set[str] = set()
        self.state.conns.add(writer)
        try:
            while not reader.at_eof():
                data = await reader.read(65536)
                if not data:
                    break
                parser.feed(data)
                try:
                    cmds = parser.pop_all()
                except resp.ProtocolError as exc:
                    # non-RESP bytes (health probe, stray HTTP, telnet):
                    # reply with an error and drop the connection
                    writer.write(resp.encode_error(str(exc)))
                    await writer.drain()
                    return
                for cmd in cmds:
                    if not isinstance(cmd, list) or not cmd:
                        writer.write(resp.encode_error("protocol error"))
                        continue
                    keep_going = await self._dispatch(cmd, writer, subscribed)
                    if not keep_going:
                        await writer.drain()
                        return
                await writer.drain()
        except (ConnectionResetError, BrokenPipeError, asyncio.CancelledError):
            pass
        finally:
            self.state.conns.discard(writer)
            self.repl.replicas.pop(writer, None)
            for ch in subscribed:
                self.state.subs.get(ch, set()).discard(writer)
            writer.close()

    async def _dispatch(
        self,
        cmd: list[str],
        writer: asyncio.StreamWriter,
        subscribed: set[str],
    ) -> bool:
        name, args = cmd[0].upper(), cmd[1:]
        st = self.state
        if name in MUTATING_COMMANDS:
            # HA write gating, BEFORE any state is touched: an unpromoted
            # replica is read-only (its state is the primary's to write),
            # and a fenced stale primary refuses everyone — including
            # epoch-oblivious legacy clients (see replication.py)
            if self.repl.role == "replica":
                writer.write(resp.encode_error(READONLY_ERR))
                return True
            if self.repl.fenced:
                writer.write(resp.encode_error(FENCED_ERR))
                return True
        if name == "PING":
            writer.write(resp.encode_simple("PONG"))
        elif name == "SELECT":
            writer.write(resp.encode_simple("OK"))
        elif name == "INFO":
            # Redis-style ops introspection: "key:value" lines in one bulk
            n_subs = sum(len(ws) for ws in st.subs.values())
            lines = [
                "server:tpu-faas-store-python",
                f"keys:{len(st.hashes)}",
                f"subscribers:{n_subs}",
                f"channels:{len(st.subs)}",
                f"dirty:{int(self._dirty)}",
                f"snapshot_path:{self.snapshot_path or ''}",
                # -- replication introspection (replication.py) ----------
                f"role:{'fenced' if self.repl.fenced else self.repl.role}",
                f"epoch:{self.repl.epoch}",
                f"repl_offset:{self.repl.offset}",
                f"repl_replicas:{len(self.repl.replicas)}",
                f"repl_min_acked:{self.repl.min_acked()}",
                f"repl_lag:{self.repl.lag()}",
                f"repl_link_up:{int(self._link.synced) if self._link else 0}",
                f"announce_ring:{len(self.repl.ring)}",
            ]
            writer.write(resp.encode_bulk("\n".join(lines)))
        elif name == "ROLE":
            # [role, epoch, offset]: the client failover handshake's "can
            # this endpoint take writes?" probe (store/client.py _connect)
            role = "fenced" if self.repl.fenced else self.repl.role
            writer.write(
                resp.encode_array(
                    [
                        resp.encode_bulk(role),
                        resp.encode_integer(self.repl.epoch),
                        resp.encode_integer(self.repl.offset),
                    ]
                )
            )
        elif name == "FENCE":
            # epoch declaration: a client that has seen a promotion
            # declares the highest epoch it knows. A PRIMARY seeing a
            # declaration above its own epoch has been superseded — fence
            # it permanently. Replies with this server's epoch so the
            # client's knowledge is monotone too.
            try:
                declared = int(args[0]) if args else 0
            except ValueError:
                writer.write(resp.encode_error("FENCE needs an integer epoch"))
                return True
            if declared > self.repl.epoch and self.repl.role == "primary":
                if not self.repl.fenced:
                    self.repl.fenced = True
                    print(
                        f"fenced: a client declared epoch {declared} > "
                        f"our {self.repl.epoch}; refusing writes",
                        flush=True,
                    )
            writer.write(resp.encode_integer(self.repl.epoch))
        elif name == "PROMOTE":
            writer.write(resp.encode_integer(self.promote()))
        elif name == "REPLSYNC":
            # full sync + stream registration, atomically (no await between
            # the snapshot and the registration, so no command is missed
            # or doubled): [epoch, offset, snapshot] then raw forwarded
            # commands ride this connection forever
            snap = snapshot.dump_hashes(st.hashes)
            writer.write(
                resp.encode_array(
                    [
                        resp.encode_integer(self.repl.epoch),
                        resp.encode_integer(self.repl.offset),
                        resp.encode_bulk(snap),
                    ]
                )
            )
            self.repl.replicas[writer] = self.repl.offset
        elif name == "REPLACK":
            # reply-less by design: the primary->replica direction of this
            # connection is the replication stream, and an interleaved
            # "+OK" would corrupt it
            try:
                acked = int(args[0])
            except (IndexError, ValueError):
                return True
            if writer in self.repl.replicas:
                self.repl.replicas[writer] = acked
        elif name == "REPLAY":
            # announce-ring replay: [tail, ch, payload, ch, payload ...]
            # for entries with offset > after; after=-1 asks for the tail
            # alone (the dispatcher's offset-priming read)
            try:
                after = int(args[0]) if args else -1
            except ValueError:
                writer.write(resp.encode_error("REPLAY needs an integer offset"))
                return True
            items = [resp.encode_integer(self.repl.ring.tail)]
            if after >= 0:
                for _off, ch, payload in self.repl.ring.since(after):
                    items.append(resp.encode_bulk(ch))
                    items.append(resp.encode_bulk(payload))
            writer.write(resp.encode_array(items))
        elif name == "HSET":
            if len(args) < 3 or len(args) % 2 == 0:
                writer.write(resp.encode_error("wrong number of arguments for HSET"))
                return True
            h = st.hashes.setdefault(args[0], {})
            added = 0
            for f, v in zip(args[1::2], args[2::2]):
                if f not in h:
                    added += 1
                h[f] = v
            self._dirty = True
            self._replicate(["HSET", *args])
            writer.write(resp.encode_integer(added))
        elif name == "HGET":
            if len(args) != 2:
                writer.write(resp.encode_error("wrong number of arguments for HGET"))
                return True
            writer.write(resp.encode_bulk(st.hashes.get(args[0], {}).get(args[1])))
        elif name == "HEXISTS":
            if len(args) != 2:
                writer.write(
                    resp.encode_error("wrong number of arguments for HEXISTS")
                )
                return True
            writer.write(
                resp.encode_integer(
                    1 if args[1] in st.hashes.get(args[0], {}) else 0
                )
            )
        elif name == "HSETNX":
            if len(args) != 3:
                writer.write(
                    resp.encode_error("wrong number of arguments for HSETNX")
                )
                return True
            h = st.hashes.setdefault(args[0], {})
            if args[1] in h:
                writer.write(resp.encode_integer(0))
            else:
                h[args[1]] = args[2]
                self._dirty = True
                self._replicate(["HSETNX", *args])
                writer.write(resp.encode_integer(1))
        elif name == "HINCRBY":
            if len(args) != 3:
                writer.write(
                    resp.encode_error("wrong number of arguments for HINCRBY")
                )
                return True
            h = st.hashes.setdefault(args[0], {})
            try:
                delta = int(args[2])
            except ValueError:
                writer.write(
                    resp.encode_error("HINCRBY delta is not an integer")
                )
                return True
            try:
                current = int(h.get(args[1], "0"))
            except ValueError:
                writer.write(
                    resp.encode_error("hash value is not an integer")
                )
                return True
            value = current + delta
            h[args[1]] = str(value)
            self._dirty = True
            self._replicate(["HINCRBY", *args])
            writer.write(resp.encode_integer(value))
        elif name == "HDEL":
            if len(args) < 2:
                writer.write(resp.encode_error("wrong number of arguments for HDEL"))
                return True
            h = st.hashes.get(args[0])
            removed = 0
            if h is not None:
                for f in args[1:]:
                    if f in h:
                        del h[f]
                        removed += 1
                if not h:  # Redis semantics: empty hash = absent key
                    del st.hashes[args[0]]
                    self._note_deleted(args[0])
            if removed:
                self._dirty = True
                self._replicate(["HDEL", *args])
            writer.write(resp.encode_integer(removed))
        elif name == "HMGET":
            if len(args) < 2:
                writer.write(resp.encode_error("wrong number of arguments for HMGET"))
                return True
            h = st.hashes.get(args[0], {})
            writer.write(
                resp.encode_array([resp.encode_bulk(h.get(f)) for f in args[1:]])
            )
        elif name == "HGETALL":
            h = st.hashes.get(args[0], {}) if args else {}
            flat: list[bytes] = []
            for f, v in h.items():
                flat.append(resp.encode_bulk(f))
                flat.append(resp.encode_bulk(v))
            writer.write(resp.encode_array(flat))
        elif name == "CAPS":
            writer.write(
                resp.encode_array([resp.encode_bulk(c) for c in STORE_CAPS])
            )
        elif name == "MHGETALL":
            # batched HGETALL: ONE command whose reply is an array of
            # per-key flat field/value arrays (missing key -> empty array,
            # matching HGETALL). Replaces N pipelined HGETALLs on the
            # intake hot path — the client builds one command and parses
            # one reply instead of N of each.
            records: list[bytes] = []
            for k in args:
                h = st.hashes.get(k, {})
                flat = []
                for f, v in h.items():
                    flat.append(resp.encode_bulk(f))
                    flat.append(resp.encode_bulk(v))
                records.append(resp.encode_array(flat))
            writer.write(resp.encode_array(records))
        elif name == "MFINISH":
            return await self._mfinish(args, writer)
        elif name == "DEL":
            n = 0
            for k in args:
                if st.hashes.pop(k, None) is not None:
                    self._note_deleted(k)
                    n += 1
            self._dirty = self._dirty or n > 0
            if n:
                self._replicate(["DEL", *args])
            writer.write(resp.encode_integer(n))
        elif name == "KEYS":
            pattern = args[0] if args else "*"
            ks = [k for k in st.hashes if fnmatch.fnmatchcase(k, pattern)]
            writer.write(resp.encode_array([resp.encode_bulk(k) for k in ks]))
        elif name == "PUBLISH":
            if len(args) != 2:
                writer.write(resp.encode_error("wrong number of arguments for PUBLISH"))
                return True
            # replicate BEFORE replying: the announce reaches the
            # replica's ring (and its subscribers) no later than the
            # publisher's acknowledgment — what makes post-failover
            # REPLAY a trustworthy re-discovery source
            self._replicate(["PUBLISH", args[0], args[1]])
            n = await self._publish(args[0], args[1])
            writer.write(resp.encode_integer(n))
        elif name == "SUBSCRIBE":
            for ch in args:
                subscribed.add(ch)
                st.subs.setdefault(ch, set()).add(writer)
                writer.write(
                    resp.encode_array(
                        [
                            resp.encode_bulk("subscribe"),
                            resp.encode_bulk(ch),
                            resp.encode_integer(len(subscribed)),
                        ]
                    )
                )
        elif name == "UNSUBSCRIBE":
            channels: Iterable[str] = args or list(subscribed)
            for ch in channels:
                subscribed.discard(ch)
                st.subs.get(ch, set()).discard(writer)
                writer.write(
                    resp.encode_array(
                        [
                            resp.encode_bulk("unsubscribe"),
                            resp.encode_bulk(ch),
                            resp.encode_integer(len(subscribed)),
                        ]
                    )
                )
        elif name == "FLUSHDB":
            st.hashes.clear()
            self._dirty = True
            self._replicate(["FLUSHDB"])
            writer.write(resp.encode_simple("OK"))
        elif name == "SAVE":
            target = args[0] if args else self.snapshot_path
            if target is None:
                writer.write(
                    resp.encode_error("SAVE needs a path (no --snapshot configured)")
                )
                return True
            try:
                # deliberate blocking checkpoint, like Redis SAVE: the loop
                # pause guarantees the dump is a consistent point-in-time
                # cut (see _save_if_configured)
                snapshot.save_file(  # faas: allow(eventloop.blocking-file-io)
                    target, st.hashes, deleted=list(st.tombstones)
                )
            except OSError as exc:
                writer.write(resp.encode_error(f"SAVE failed: {exc}"))
                return True
            if target == self.snapshot_path:
                # delta window restarts only for the CONFIGURED target —
                # an ad-hoc SAVE elsewhere must not eat the tombstones the
                # next checkpoint still needs to record
                st.tombstones.clear()
                self._dirty = False
            writer.write(resp.encode_simple("OK"))
        elif name == "QUIT":
            writer.write(resp.encode_simple("OK"))
            return False
        elif name == "SHUTDOWN":
            try:
                self._save_if_configured()
            except OSError as exc:
                # like Redis: a failed save aborts the shutdown and the
                # client is told, rather than dying with unsaved state or
                # silently staying up
                writer.write(
                    resp.encode_error(f"SHUTDOWN aborted, save failed: {exc}")
                )
                return True
            self._shutdown.set()
            return False
        else:
            writer.write(resp.encode_error(f"unknown command '{name}'"))
        return True

    async def _mfinish(self, args: list[str], writer) -> bool:
        """MFINISH <now> <inline_max> <n> (task_id status result fw)*n —
        the server-side batched terminal flush behind the client's
        binary-batch fast path (store/client.py finish_task_many).

        Semantics mirror the client's pipelined slow path exactly: the
        first-wins freeze set is evaluated against PRE-batch state
        (CANCELLED is lawfully overwritable by a late real result; any
        other terminal or unknown/missing status freezes), and ids written
        earlier in the SAME batch freeze later first-wins duplicates. Each
        surviving task applies record-write -> live-index drop -> announce
        in order, and replicates as the same PRIMITIVE commands the slow
        path would have sent (HSET/HDEL/PUBLISH) — replication streams,
        snapshots, and replica-attached subscribers are indistinguishable
        from the pipelined form. Replies with the written-task count."""
        from tpu_faas.core.task import (
            FIELD_FINAL_AT,
            FIELD_FINAL_STATUS,
            FIELD_FINISHED_AT,
            FIELD_RESULT,
            FIELD_STATUS,
            TaskStatus,
        )
        from tpu_faas.store.base import (
            LIVE_INDEX_KEY,
            RESULTS_CHANNEL,
            encode_result_announce,
        )

        # branch-local HA write gate (MFINISH expands to mutating
        # primitives but is not itself in MUTATING_COMMANDS — the
        # replication stream only ever carries the primitives)
        if self.repl.role == "replica":
            writer.write(resp.encode_error(READONLY_ERR))
            return True
        if self.repl.fenced:
            writer.write(resp.encode_error(FENCED_ERR))
            return True
        try:
            now, inline_max, n = args[0], int(args[1]), int(args[2])
            rest = args[3:]
            if n < 0 or len(rest) != 4 * n:
                raise ValueError
        except (IndexError, ValueError):
            writer.write(
                resp.encode_error("wrong number of arguments for MFINISH")
            )
            return True
        st = self.state
        items = [
            (rest[4 * i], rest[4 * i + 1], rest[4 * i + 2], rest[4 * i + 3] == "1")
            for i in range(n)
        ]
        frozen: set[str] = set()
        for task_id, _status, _result, fw in items:
            if not fw or task_id in frozen:
                continue
            status = st.hashes.get(task_id, {}).get(FIELD_STATUS)
            if status == str(TaskStatus.CANCELLED):
                continue  # a late real result lawfully overwrites
            if TaskStatus.terminal_str(status, unknown=True):
                frozen.add(task_id)
        written: set[str] = set()
        for task_id, status, result, fw in items:
            if fw and (task_id in written or task_id in frozen):
                continue
            h = st.hashes.setdefault(task_id, {})
            h[FIELD_STATUS] = status
            h[FIELD_FINAL_STATUS] = status
            h[FIELD_FINAL_AT] = now
            h[FIELD_RESULT] = result
            h[FIELD_FINISHED_AT] = now
            self._replicate(
                [
                    "HSET", task_id,
                    FIELD_STATUS, status,
                    FIELD_FINAL_STATUS, status,
                    FIELD_FINAL_AT, now,
                    FIELD_RESULT, result,
                    FIELD_FINISHED_AT, now,
                ]
            )
            live = st.hashes.get(LIVE_INDEX_KEY)
            if live is not None and task_id in live:
                del live[task_id]
                if not live:
                    del st.hashes[LIVE_INDEX_KEY]
                    self._note_deleted(LIVE_INDEX_KEY)
                self._replicate(["HDEL", LIVE_INDEX_KEY, task_id])
            payload = encode_result_announce(task_id, status, result, inline_max)
            self._replicate(["PUBLISH", RESULTS_CHANNEL, payload])
            await self._publish(RESULTS_CHANNEL, payload)
            written.add(task_id)
        if written:
            self._dirty = True
        writer.write(resp.encode_integer(len(written)))
        return True

    async def _publish(self, channel: str, payload: str) -> int:
        """Fan a message out to subscribers; fire-and-forget like Redis pub/sub."""
        receivers = list(self.state.subs.get(channel, ()))
        msg = resp.encode_array(
            [
                resp.encode_bulk("message"),
                resp.encode_bulk(channel),
                resp.encode_bulk(payload),
            ]
        )
        n = 0
        for w in receivers:
            if w.is_closing():
                self.state.subs[channel].discard(w)
                continue
            try:
                w.write(msg)
                n += 1
            except (ConnectionResetError, BrokenPipeError):
                self.state.subs[channel].discard(w)
        return n


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(description="tpu-faas task store server (Python)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=6380)
    ap.add_argument(
        "--snapshot",
        default=None,
        help="checkpoint file: loaded at startup, written on SAVE/SHUTDOWN",
    )
    ap.add_argument(
        "--autosave",
        type=float,
        default=0.0,
        help="seconds between automatic snapshots while dirty (0 = off)",
    )
    ap.add_argument(
        "--replica-of",
        default=None,
        metavar="HOST:PORT",
        help="start as a read-only replica tailing this primary's write "
        "stream; accepts writes only after an explicit PROMOTE command",
    )
    ap.add_argument(
        "--epoch",
        type=int,
        default=0,
        help="fencing epoch to start with (restart a previously-promoted "
        "store with its post-promotion epoch so old primaries stay fenced)",
    )
    ap.add_argument(
        "--announce-ring",
        type=int,
        default=0,
        help="override the bounded announce-replay ring size "
        "(default 10000 entries)",
    )
    ap.add_argument(
        "--health-port",
        type=int,
        default=None,
        help="serve HTTP GET /healthz (liveness) and /readyz (503 while "
        "loading a snapshot / unpromoted replica / fenced) on this port — "
        "probe parity with the gateway and dispatcher stats servers",
    )
    ns = ap.parse_args(argv)

    async def run() -> None:
        server = StoreServer(
            ns.host,
            ns.port,
            snapshot_path=ns.snapshot,
            autosave_interval=ns.autosave,
            replica_of=ns.replica_of,
            epoch=ns.epoch,
            announce_ring=ns.announce_ring,
            health_port=ns.health_port,
        )
        await server.start()
        # graceful kill/Ctrl-C must checkpoint, like the native server's
        # SIGTERM/SIGINT handlers — otherwise everything since the last
        # autosave is lost on `systemctl stop`
        loop = asyncio.get_running_loop()
        for sig in (signal.SIGTERM, signal.SIGINT):
            loop.add_signal_handler(
                sig, lambda: asyncio.ensure_future(server.stop())
            )
        print(f"tpu-faas store listening on {server.host}:{server.port}", flush=True)
        await server.serve_forever()

    asyncio.run(run())


if __name__ == "__main__":
    main()
