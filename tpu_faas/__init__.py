"""tpu-faas: a TPU-native distributed Function-as-a-Service framework.

Capability parity with the reference system mshalimay/Distributed-FaaS
(see SURVEY.md): clients register arbitrary Python functions over REST and
invoke them; tasks flow through a hash-per-task store + announce bus into a
dispatcher (local / pull / push / tpu-push modes) and out to multiprocessing
worker nodes over ZeroMQ. Where the reference makes its per-tick placement
decision by greedily walking a Python list (reference task_dispatcher.py:297-322),
this framework computes placement, heartbeat-timeout detection, and
work-redistribution as one batched JAX device step (tpu_faas.sched).

Layer map (mirrors SURVEY.md §1, rebuilt TPU-first):

    client SDK / benchmarks          tpu_faas.client, bench/
    REST gateway (aiohttp)           tpu_faas.gateway
    task store + announce bus        tpu_faas.store  (native C++ or in-proc)
    dispatch / scheduling            tpu_faas.dispatch + tpu_faas.sched (TPU)
    worker runtime                   tpu_faas.worker
    execution core                   tpu_faas.core
    transport                        ZeroMQ / RESP-TCP / HTTP
"""

from tpu_faas.version import __version__

__all__ = ["__version__"]
