"""Host-side hedge lifecycle for the speculation plane.

The device tick flags stragglers (spec/straggler.py); this module owns what
the dispatcher does about them: the opt-in policy knobs, the wasted-work
budget, and the per-task hedge book that tracks each replica from launch to
first-wins resolution. The store is never taught anything new — the hedge
is the SAME task id dispatched to a second worker behind a declared replica
(store ``declare_replica``, racecheck ``expect_replica``), both results
write through the existing first-wins ``finish_task`` path, and the loser
is killed through the existing CANCEL plane.

Invariants the book enforces (the dispatcher drives the transitions):

- at most ONE outstanding hedge per task id (a slot re-flagged by the tick
  while its hedge is pending/running is ignored);
- the wasted-work budget is a hard gate: ``hedges_launched`` never exceeds
  ``max_frac x tasks_dispatched`` (suppressions are counted, not silent);
- exactly-once accounting on every exit path — replica wins, original
  wins, hedge worker dies (abandon), original's worker dies (the hedge is
  PROMOTED to owner instead of re-queuing the task), task cancelled —
  because every exit pops the entry exactly once and releases exactly the
  charges that entry recorded.
"""

from __future__ import annotations

import time
from dataclasses import dataclass

from tpu_faas.spec.straggler import DEFAULT_MIN_RUNTIME_S

#: resolved hedges whose loser's late result is still expected: bounded
#: map for wasted-work attribution (a loser that never reports ages out)
_LOSER_CAP = 10_000


@dataclass
class HedgeEntry:
    """One task's outstanding hedge, from consider to resolution."""

    task_id: str
    #: worker row running the ORIGINAL when the hedge was considered —
    #: the anti-affinity row the ghost placement must avoid
    orig_row: int
    launched_at: float
    #: set when the replica actually dispatches (None = ghost row still
    #: pending placement)
    hedge_row: int | None = None
    hedge_wid: bytes | None = None
    #: the replica's own tenant inflight charge (a hedge burns the
    #: tenant's share like any dispatch), released at resolution
    tenant_row: int | None = None
    #: the task's SLO class (obs/attribution.py), stamped at launch so
    #: resolution can attribute the outcome per class without a re-read
    cls: str = "default"

    @property
    def dispatched(self) -> bool:
        return self.hedge_row is not None


class SpeculationPolicy:
    """Policy knobs + hedge book + counters for one dispatcher.

    ``quantile_mult`` — flag an execution past this multiple of its
    predicted runtime (the device threshold); ``max_frac`` — hard ceiling
    on hedges_launched / tasks_dispatched (the wasted-work budget);
    ``min_runtime_s`` — absolute floor under which nothing hedges.
    """

    def __init__(
        self,
        quantile_mult: float,
        max_frac: float = 0.1,
        min_runtime_s: float = DEFAULT_MIN_RUNTIME_S,
        clock=time.monotonic,
    ) -> None:
        if not quantile_mult > 1.0:
            raise ValueError(
                "--speculate-mult must be > 1 (flag past that multiple of "
                "the predicted runtime)"
            )
        if not 0.0 < max_frac <= 1.0:
            raise ValueError("--speculate-max-frac must be in (0, 1]")
        self.quantile_mult = float(quantile_mult)
        self.max_frac = float(max_frac)
        self.min_runtime_s = max(0.0, float(min_runtime_s))
        self.clock = clock
        self.entries: dict[str, HedgeEntry] = {}
        #: task_id -> loser worker row: resolved hedges whose loser's late
        #: result is still in flight somewhere (wasted-work attribution)
        self._losers: dict[str, int] = {}
        self.n_launched = 0
        self.n_replica_wins = 0
        self.n_original_wins = 0
        self.n_promoted = 0
        self.n_abandoned = 0
        self.n_suppressed_budget = 0
        #: loser execution seconds actually reported back (the measured
        #: wasted work; losers killed pre-start report ~0)
        self.wasted_exec_s = 0.0

    # -- gates -------------------------------------------------------------
    def within_budget(self, n_dispatched: int) -> bool:
        """Would one more hedge keep hedges_launched / tasks <= max_frac?
        Callers pass the PRIMARY dispatch count (hedges excluded — the
        dispatcher subtracts ``n_launched`` from its total): a denominator
        that counted hedges would loosen the bound to f/(1-f) under heavy
        hedging, breaking the documented hard-budget contract."""
        return (self.n_launched + 1) <= self.max_frac * max(n_dispatched, 1)

    def consider(self, task_id: str, orig_row: int, n_dispatched: int):
        """Admit one straggler flag into the book: returns the new entry,
        or None when a hedge is already outstanding for the id or the
        budget is spent (counted)."""
        if task_id in self.entries:
            return None
        if not self.within_budget(n_dispatched):
            self.n_suppressed_budget += 1
            return None
        entry = HedgeEntry(task_id, int(orig_row), self.clock())
        self.entries[task_id] = entry
        self.n_launched += 1
        return entry

    # -- resolution --------------------------------------------------------
    def resolve(self, task_id: str, *, winner: str, loser_row: int) -> None:
        """Pop the entry on a first result; remember the loser for
        wasted-work attribution when its late result straggles in."""
        self.entries.pop(task_id, None)
        if winner == "replica":
            self.n_replica_wins += 1
        else:
            self.n_original_wins += 1
        if len(self._losers) >= _LOSER_CAP:
            self._losers.pop(next(iter(self._losers)), None)
        self._losers[task_id] = int(loser_row)

    def note_loser_result(
        self, task_id: str, sender_row, elapsed
    ) -> float | None:
        """A late result arrived for a task whose hedge already resolved:
        account its execution window as wasted work — but only when it
        came from the recorded LOSER's worker row (a winner's duplicate
        retransmit for the same id must not consume the entry and book
        the winner's window as waste). ``sender_row=None`` (unknown/
        purged sender) never matches — conservative: unattributable
        windows stay uncounted. Returns the seconds counted (0.0 for a
        pre-start kill with no window) when consumed, None otherwise."""
        row = self._losers.get(task_id)
        if row is None or sender_row is None or int(sender_row) != row:
            return None
        self._losers.pop(task_id, None)
        secs = (
            float(elapsed)
            if isinstance(elapsed, (int, float)) and elapsed > 0
            else 0.0
        )
        self.wasted_exec_s += secs
        return secs

    def abandon(self, task_id: str) -> HedgeEntry | None:
        """Drop an entry without a winner (hedge worker died, task
        cancelled/expired, original reclaimed pre-dispatch)."""
        entry = self.entries.pop(task_id, None)
        if entry is not None:
            self.n_abandoned += 1
        return entry

    def promote(self, task_id: str) -> HedgeEntry | None:
        """The ORIGINAL's worker died with the replica still running: the
        replica becomes the task's plain owner (no re-queue). Pops the
        entry; the caller moves the inflight table over."""
        entry = self.entries.pop(task_id, None)
        if entry is not None:
            self.n_promoted += 1
        return entry

    def stats(self) -> dict:
        # oldest outstanding hedge age: a value that keeps GROWING while
        # `outstanding` sits nonzero is a stuck race — a loser whose kill
        # never landed, or a ghost with no capacity off its sick worker
        oldest = (
            round(
                self.clock()
                - min(e.launched_at for e in self.entries.values()),
                3,
            )
            if self.entries
            else None
        )
        return {
            "quantile_mult": self.quantile_mult,
            "max_frac": self.max_frac,
            "min_runtime_s": self.min_runtime_s,
            "outstanding": len(self.entries),
            "oldest_outstanding_s": oldest,
            "launched": self.n_launched,
            "replica_wins": self.n_replica_wins,
            "original_wins": self.n_original_wins,
            "promoted": self.n_promoted,
            "abandoned": self.n_abandoned,
            "suppressed_budget": self.n_suppressed_budget,
            "wasted_exec_s": round(self.wasted_exec_s, 3),
        }
