"""Speculation plane: device-scored straggler hedging with first-wins
replica results.

``straggler`` holds the device kernels (flagging + anti-affinity, traced
inside the scheduler step by both tick backends); ``policy`` holds the
host-side hedge book and the opt-in knobs. Everything is off — and every
surface byte-identical — until a dispatcher runs with ``--speculate-mult``
AND a submit carries ``speculative=true``.
"""

from tpu_faas.spec.policy import HedgeEntry, SpeculationPolicy
from tpu_faas.spec.straggler import (
    DEFAULT_MIN_RUNTIME_S,
    HEDGE_FIXUP_K,
    anti_affinity_veto,
    anti_affinity_veto_impl,
    hedge_fixup,
    hedge_fixup_impl,
    straggler_flags,
    straggler_flags_impl,
)

__all__ = [
    "DEFAULT_MIN_RUNTIME_S",
    "HEDGE_FIXUP_K",
    "HedgeEntry",
    "SpeculationPolicy",
    "anti_affinity_veto",
    "anti_affinity_veto_impl",
    "hedge_fixup",
    "hedge_fixup_impl",
    "straggler_flags",
    "straggler_flags_impl",
]
