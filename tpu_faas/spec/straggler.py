"""Device-side straggler scoring + anti-affinity for the speculation plane.

The tail-latency bet (Dean & Barroso, "The Tail at Scale", CACM 2013;
PAPERS.md): when one execution of a task runs demonstrably longer than its
predicted runtime, a second copy on a DIFFERENT worker bounds the task's
latency by the second-fastest machine instead of the sickest one. The
ingredients already exist in this system — the estimator's size x speed
runtime predictions ride the in-flight table, and the store's first-wins
``finish_task`` arbitrates two results for one id — this module adds the
two device-side pieces that compose them into the tick:

- :func:`straggler_flags_impl` — flag in-flight slots whose observed
  elapsed time exceeds ``quantile_mult x`` their predicted runtime (with an
  absolute floor so sub-millisecond noise never hedges). One vectorized
  compare over the in-flight table, traced INSIDE the scheduler step by
  BOTH tick backends (the jitted XLA resident tick and the fused Pallas
  kernel trace the same ``_impl`` — the PR-11/13 pattern), so flagging
  costs no extra device dispatch.
- :func:`anti_affinity_veto_impl` — a hedge candidate re-enters the
  placement problem as an ordinary pending row carrying the row index of
  the worker already running its original; the veto masks the one
  (task, worker) pairing that would be useless (a replica racing on the
  SAME sick worker), composed into the device step after placement exactly
  like the tenancy cap mask composes before it. The vetoed task stays
  valid and is re-placed next tick against whatever capacity exists
  elsewhere — a hedge never launches onto its original's worker, and never
  silently drops.

Both kernels follow the solver stack's ``_impl`` convention: the un-jitted
core is what ``scheduler_tick_impl`` traces (a pjit primitive inside a
pallas_call body does not lower), the jitted twin serves direct callers
and unit tests.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

#: default absolute floor (seconds) under which an execution is never
#: flagged, whatever the multiplier says: predictions for sub-hundred-ms
#: tasks carry scheduling jitter comparable to the runtime itself, and a
#: hedge there burns a slot to save nothing
DEFAULT_MIN_RUNTIME_S = 0.05


def straggler_flags_impl(
    inflight_elapsed: jnp.ndarray,  # f32[I] seconds since dispatch
    inflight_predicted: jnp.ndarray,  # f32[I] predicted runtime, <=0 = opt out
    inflight_occupied: jnp.ndarray,  # bool[I] slot holds a live dispatch
    quantile_mult: jnp.ndarray,  # f32 scalar: flag past mult x predicted
    min_runtime_s: jnp.ndarray,  # f32 scalar: absolute floor
) -> jnp.ndarray:
    """bool[I]: in-flight slots whose execution has outlived its prediction.

    A slot opts out of hedging with ``predicted <= 0`` — the dispatcher
    stamps a positive prediction only for tasks that are hedge-eligible
    (submit-gated ``speculative`` AND a runtime prediction in seconds:
    client cost hint or learned estimate; payload-byte fallback sizes are
    not seconds and never hedge). The threshold is
    ``max(quantile_mult x predicted, min_runtime_s)`` so a tight
    prediction on a tiny task cannot hedge on scheduling noise."""
    threshold = jnp.maximum(
        quantile_mult * inflight_predicted, min_runtime_s
    )
    return (
        inflight_occupied
        & (inflight_predicted > 0.0)
        & (inflight_elapsed > threshold)
    )


straggler_flags = jax.jit(straggler_flags_impl)


def anti_affinity_veto_impl(
    assignment: jnp.ndarray,  # i32[T] placement output, -1 = queued
    task_avoid_worker: jnp.ndarray,  # i32[T] forbidden row per task, -1 none
) -> jnp.ndarray:
    """Mask placements that landed a task on its forbidden worker row.

    The vetoed task's assignment reverts to -1 (stays queued/valid — the
    resident kernel only clears slots it reports placed, so the ghost row
    re-enters next tick's problem); every other pairing passes through
    untouched. Flat workloads (all -1) trace to a no-op compare."""
    veto = (task_avoid_worker >= 0) & (assignment == task_avoid_worker)
    return jnp.where(veto, -1, assignment)


anti_affinity_veto = jax.jit(anti_affinity_veto_impl)


#: per-tick bound on vetoed ghost rows re-placed by the fixup pass below:
#: hedges are budget-bounded rarities, and a surplus simply waits a tick
HEDGE_FIXUP_K = 64


def hedge_fixup_impl(
    assignment: jnp.ndarray,  # i32[T] placement output
    task_avoid_worker: jnp.ndarray,  # i32[T] forbidden row (-1 = none)
    worker_speed: jnp.ndarray,  # f32[W]
    worker_free: jnp.ndarray,  # i32[W] capacity the placement pass saw
    worker_live: jnp.ndarray,  # bool[W]
) -> jnp.ndarray:
    """Anti-affinity composed into the device step: veto + re-place.

    The placement kernels are rank/price matchers with no per-(task,
    worker) exclusion lane, so the forbidden pairing is masked AFTER
    placement (:func:`anti_affinity_veto_impl`) — but a bare veto starves
    under rank's deterministic tie-break: the same ghost row keeps winning
    the same forbidden slot every tick. This fixup closes the loop inside
    the same traced step: up to :data:`HEDGE_FIXUP_K` vetoed rows are
    re-placed greedily onto the fastest live worker with capacity REMAINING
    after the main pass, excluding each row's own forbidden worker —
    rank's largest-task/fastest-slot pairing applied to the hedge tail.
    A ghost row with no eligible capacity stays queued (a hedge must never
    launch onto its original's worker, and never silently drops). Flat
    ticks never trace this: the caller gates on the avoid lane existing.
    """
    T = assignment.shape[0]
    W = worker_speed.shape[0]
    veto = (task_avoid_worker >= 0) & (assignment == task_avoid_worker)
    assignment = jnp.where(veto, -1, assignment)
    # capacity remaining after the main pass (one bounded scatter-add —
    # only traced on speculation-enabled ticks)
    placed = assignment >= 0
    counts = (
        jnp.zeros(W, dtype=jnp.int32)
        .at[jnp.where(placed, assignment, W)]
        .add(1, mode="drop")
    )
    free_rem = jnp.maximum(
        jnp.where(worker_live, worker_free, 0) - counts, 0
    )
    # compact the vetoed rows to the fixup bound (first-K in index order)
    pos = jnp.cumsum(veto) - 1
    idx = jnp.where(veto & (pos < HEDGE_FIXUP_K), pos, HEDGE_FIXUP_K)
    vet_idx = (
        jnp.full(HEDGE_FIXUP_K, -1, dtype=jnp.int32)
        .at[idx]
        .set(jnp.arange(T, dtype=jnp.int32), mode="drop")
    )
    rows = jnp.arange(W, dtype=jnp.int32)

    def body(k, carry):
        assignment, free_rem = carry
        t = vet_idx[k]
        safe_t = jnp.clip(t, 0)
        avoid = task_avoid_worker[safe_t]
        score = jnp.where(
            worker_live & (free_rem > 0) & (rows != avoid),
            worker_speed,
            -jnp.inf,
        )
        row = jnp.argmax(score).astype(jnp.int32)
        can = (t >= 0) & (score[row] > -jnp.inf)
        assignment = assignment.at[jnp.where(can, safe_t, T)].set(
            row, mode="drop"
        )
        free_rem = free_rem.at[row].add(jnp.where(can, -1, 0))
        return assignment, free_rem

    assignment, _ = jax.lax.fori_loop(
        0, HEDGE_FIXUP_K, body, (assignment, free_rem)
    )
    return assignment


hedge_fixup = jax.jit(hedge_fixup_impl)
