"""Async client SDK (aiohttp) — the concurrent-submitter counterpart of
:mod:`tpu_faas.client.sdk`.

Same wire format as the sync client (SURVEY §0.1 endpoints + the batch
extension), but every call is a coroutine and result polling multiplexes on
one event loop — a single process can drive thousands of outstanding tasks
without a thread per poll. The sync ``FaaSClient`` remains the default for
scripts; this is for gateway-scale load generators and services embedding
the client in an async stack.
"""

from __future__ import annotations

import asyncio
import contextlib
import uuid
from dataclasses import dataclass
from typing import Any, Callable

import aiohttp

from tpu_faas.client.sdk import (
    OVERLOAD_BACKOFF,  # shared 429/503 schedule: sync and async must agree
    TaskCancelledError,
    TaskDependencyError,
    TaskExpiredError,
    TaskFailedError,
    _FnMemo,  # shared serialize()/register dedup: sync and async agree
    _retry_after_s,  # shared Retry-After parsing: sync and async must agree
    _unwrap_terminal,  # shared terminal protocol (incl. dep_failed parsing)
)
from tpu_faas.core.executor import pack_params
from tpu_faas.obs.tracectx import new_trace_id
from tpu_faas.utils.backoff import Backoff, BackoffPolicy

#: Connection-establishment retries: deterministic doubling from 0.3 s
#: (no jitter — these are budget-clamped by the caller's deadline, and
#: a lone client reconnecting to a restarting gateway has no thundering
#: herd to spread).
CONNECT_BACKOFF = BackoffPolicy(
    floor_s=0.3, factor=2.0, cap_s=30.0, jitter_lo=1.0, jitter_hi=1.0
)


@dataclass
class AsyncTaskHandle:
    client: "AsyncFaaSClient"
    task_id: str
    #: distributed trace id of this submit (trace-enabled clients against
    #: a --trace gateway); None otherwise — same contract as the sync
    #: TaskHandle.trace_id
    trace_id: str | None = None

    async def status(self) -> str:
        async with self.client.request(
            "GET", f"{self.client.base_url}/status/{self.task_id}"
        ) as r:
            r.raise_for_status()
            return (await r.json())["status"]

    async def result(
        self, timeout: float = 60.0, poll_interval: float = 0.01
    ) -> Any:
        """Push-based await: the request PARKS at the gateway (``?wait=``)
        and is woken by the result's announce — against an express-lane
        dispatcher the gateway replies straight from the forwarded
        payload, so ``await handle.result()`` never polls anything.
        ``poll_interval`` paces only the degenerate wait<=0 rounds right
        at the deadline — and any non-terminal reply that came back in
        well under the requested wait (a draining or wait-oblivious
        gateway never parked; pacing there prevents a zero-delay request
        hot-spin). A parked round was its own pacing, and sleeping after
        it would put a client-side floor under every delivery."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while True:
            remaining = max(0.0, min(deadline - loop.time(), 5.0))
            t_req = loop.time()
            async with self.client.request(
                "GET",
                f"{self.client.base_url}/result/{self.task_id}",
                # retry sleeps AND the parked request itself are bounded by
                # the caller's deadline: a dark or wedged gateway must not
                # block result(timeout=T) far past T
                retry_budget=max(0.5, deadline - loop.time()),
                params={"wait": remaining} if remaining > 0 else None,
                timeout=aiohttp.ClientTimeout(total=remaining + 15.0),
            ) as r:
                r.raise_for_status()
                body = await r.json()
            done, value = _unwrap_terminal(
                self.task_id, body["status"], body["result"]
            )
            if done:
                return value
            if loop.time() > deadline:
                raise TimeoutError(
                    f"task {self.task_id} still {body['status']} "
                    f"after {timeout}s"
                )
            if remaining <= 0 or loop.time() - t_req < 0.5 * remaining:
                await asyncio.sleep(poll_interval)

    async def forget(self) -> None:
        """Delete this task's store record once terminal."""
        await self.client.delete_task(self.task_id)

    async def cancel(self, force: bool = False) -> bool:
        """Best-effort cancel; True = the record now reads CANCELLED,
        which a lost dispatch race can still overwrite. ``force=True``
        asks a RUNNING task's worker to interrupt it mid-run (async; see
        sync TaskHandle.cancel for the full contract)."""
        return await self.client.cancel(self.task_id, force=force)


class AsyncFaaSClient:
    """Use as an async context manager:

        async with AsyncFaaSClient(url) as client:
            fid = await client.register(fn)
            handles = await client.submit_many(fid, params)
            values = await asyncio.gather(*(h.result() for h in handles))
    """

    def __init__(
        self,
        base_url: str = "http://127.0.0.1:8000",
        connect_retries: int = 5,
        overload_retries: int = 4,
        auto_idempotency: bool = True,
        trace: bool = False,
        tenant: str | None = None,
    ) -> None:
        """``overload_retries``/``auto_idempotency``: same overload
        contract as the sync FaaSClient — 429/503 submit rejects retry
        honoring ``Retry-After`` with jittered exponential backoff, and
        every submit carries an idempotency key (auto-minted unless the
        caller supplied one or disabled it) so retries are
        duplicate-safe. ``trace``: mint a distributed trace id per submit
        and send it along — same contract as the sync FaaSClient.
        ``tenant``: sent as ``X-Tenant-Id`` on every request (same
        contract as the sync FaaSClient's tenant)."""
        self.base_url = base_url.rstrip("/")
        self.connect_retries = connect_retries
        self.overload_retries = int(overload_retries)
        self.auto_idempotency = bool(auto_idempotency)
        self.trace = bool(trace)
        self.tenant = tenant
        #: serialize()/register dedup, shared shape with the sync SDK
        self._memo = _FnMemo()
        self._http: aiohttp.ClientSession | None = None

    @contextlib.asynccontextmanager
    async def request(
        self,
        method: str,
        url: str,
        retry_budget: float | None = None,
        retry_overload: bool = False,
        **kw,
    ):
        """All SDK HTTP rides through here: CONNECTION-establishment
        failures retry with backoff (gateway restarting behind a stable
        address — mirrors the sync client's adapter). Nothing has reached
        the wire on a connector error, so the retry is safe even for
        POSTs; errors after the request is sent are never retried —
        EXCEPT 429/503 overload rejects when ``retry_overload`` is set
        (submit paths only, whose bodies carry idempotency keys): those
        sleep the server's Retry-After (jittered) and re-send, up to
        ``overload_retries`` times.

        ``retry_budget`` caps the total seconds spent in retry sleeps —
        deadline-bound callers (AsyncTaskHandle.result) pass their
        remaining time so the retry loop can't blow past their timeout."""
        loop = asyncio.get_running_loop()
        give_up_at = (
            loop.time() + retry_budget if retry_budget is not None else None
        )
        connect_bo = Backoff(CONNECT_BACKOFF)
        overload_bo = Backoff(OVERLOAD_BACKOFF)
        while True:
            try:
                async with self.http.request(method, url, **kw) as r:
                    if (
                        retry_overload
                        and r.status in (429, 503)
                        and overload_bo.attempt < self.overload_retries
                    ):
                        await asyncio.sleep(
                            overload_bo.next(
                                hint=_retry_after_s(r, overload_bo.peek()),
                                clamp=(
                                    give_up_at - loop.time()
                                    if give_up_at is not None
                                    else None
                                ),
                            )
                        )
                        continue
                    yield r
                return
            except aiohttp.ClientConnectorError:
                if connect_bo.attempt >= self.connect_retries:
                    raise
                if give_up_at is not None:
                    remaining = give_up_at - loop.time()
                    if remaining <= 0:
                        raise
                    await asyncio.sleep(connect_bo.next(clamp=remaining))
                else:
                    await asyncio.sleep(connect_bo.next())

    @property
    def http(self) -> aiohttp.ClientSession:
        if self._http is None:
            raise RuntimeError(
                "AsyncFaaSClient must be entered first: "
                "`async with AsyncFaaSClient(url) as client: ...`"
            )
        return self._http

    async def __aenter__(self) -> "AsyncFaaSClient":
        headers = (
            {"X-Tenant-Id": str(self.tenant)}
            if self.tenant is not None
            else None
        )
        self._http = aiohttp.ClientSession(headers=headers)
        return self

    async def __aexit__(self, *exc: object) -> None:
        if self._http is not None:
            await self._http.close()
            self._http = None

    async def register(self, fn: Callable, name: str | None = None) -> str:
        # serialization is CPU work: off the event loop, like all packing
        # (the memo makes the repeat case a dict probe — see _FnMemo)
        loop = asyncio.get_running_loop()
        payload = await loop.run_in_executor(None, self._memo.serialize_fn, fn)
        function_id = self._memo.function_id_for(payload)
        if function_id is not None:
            return function_id
        async with self.request(
            "POST",
            f"{self.base_url}/register_function",
            json={"name": name or fn.__name__, "payload": payload},
        ) as r:
            r.raise_for_status()
            function_id = (await r.json())["function_id"]
        self._memo.note_registered(payload, function_id)
        return function_id

    async def submit(
        self, function_id: str, *args: Any, **kwargs: Any
    ) -> AsyncTaskHandle:
        loop = asyncio.get_running_loop()
        payload = await loop.run_in_executor(
            None, lambda: pack_params(*args, **kwargs)
        )
        body = {"function_id": function_id, "payload": payload}
        if self.trace:
            body["trace_id"] = new_trace_id()
        if self.auto_idempotency:
            body["idempotency_key"] = uuid.uuid4().hex
        async with self.request(
            "POST",
            f"{self.base_url}/execute_function",
            retry_overload=True,
            json=body,
        ) as r:
            r.raise_for_status()
            out = await r.json()
            return AsyncTaskHandle(self, out["task_id"], out.get("trace_id"))

    async def submit_with(
        self,
        function_id: str,
        args: tuple = (),
        kwargs: dict | None = None,
        *,
        priority: int | None = None,
        cost: float | None = None,
        timeout: float | None = None,
        idempotency_key: str | None = None,
        deadline: float | None = None,
        speculative: bool = False,
        slo_class: str | None = None,
    ) -> AsyncTaskHandle:
        """submit() plus scheduling hints (mirrors the sync SDK): higher
        ``priority`` is admitted first under overload; ``cost`` is the
        estimated run-cost used for task<->worker pairing; ``timeout`` is
        the execution budget enforced inside the worker's pool child;
        ``deadline`` is a submit-TTL in seconds (still QUEUED past it →
        terminal EXPIRED, result() raises TaskExpiredError);
        ``idempotency_key`` makes the submit safely retryable (a re-send
        addresses the same task instead of running it twice; auto-minted
        unless auto_idempotency=False); ``speculative`` declares the task
        IDEMPOTENT and hedge-eligible (tpu_faas/spec) — only set it for
        functions safe to execute more than once; ``slo_class`` declares
        the task's SLO class (interactive/batch/default,
        obs/attribution.py) for per-class latency accounting when the
        observability plane runs with TPU_FAAS_OBS_CLASS=1."""
        loop = asyncio.get_running_loop()
        payload = await loop.run_in_executor(
            None, lambda: pack_params(*args, **(kwargs or {}))
        )
        body: dict = {"function_id": function_id, "payload": payload}
        if priority is not None:
            body["priority"] = priority
        if slo_class is not None:
            body["slo_class"] = slo_class
        if cost is not None:
            body["cost"] = cost
        if timeout is not None:
            body["timeout"] = timeout
        if deadline is not None:
            body["deadline"] = deadline
        if speculative:
            body["speculative"] = True
        if self.trace:
            body["trace_id"] = new_trace_id()
        if idempotency_key is None and self.auto_idempotency:
            idempotency_key = uuid.uuid4().hex
        if idempotency_key is not None:
            body["idempotency_key"] = idempotency_key
        async with self.request(
            "POST",
            f"{self.base_url}/execute_function",
            retry_overload=True,
            json=body,
        ) as r:
            r.raise_for_status()
            out = await r.json()
            return AsyncTaskHandle(self, out["task_id"], out.get("trace_id"))

    async def submit_many(
        self,
        function_id: str,
        params_list: list[tuple[tuple, dict]],
        priorities: list[int] | None = None,
        costs: list[float] | None = None,
        timeouts: list[float] | None = None,
        idempotency_keys: list[str | None] | None = None,
        deadlines: list[float] | None = None,
        speculative: bool = False,
        slo_class: str | None = None,
    ) -> list[AsyncTaskHandle]:
        # dill-packing thousands of payloads inline would stall the event
        # loop (and every concurrently polling handle) — do it in a worker
        # thread
        loop = asyncio.get_running_loop()
        payloads = await loop.run_in_executor(
            None,
            lambda: [
                pack_params(*args, **kwargs) for args, kwargs in params_list
            ],
        )
        body: dict = {"function_id": function_id, "payloads": payloads}
        if priorities is not None:
            body["priorities"] = priorities
        if costs is not None:
            body["costs"] = costs
        if timeouts is not None:
            body["timeouts"] = timeouts
        if deadlines is not None:
            body["deadlines"] = deadlines
        if speculative:
            body["speculative"] = True
        if slo_class is not None:
            # one declared class for the whole batch, applied element-wise
            # by the gateway (same wire contract as the sync SDK)
            body["slo_class"] = slo_class
        if idempotency_keys is None and self.auto_idempotency:
            idempotency_keys = [uuid.uuid4().hex for _ in params_list]
        if idempotency_keys is not None:
            body["idempotency_keys"] = idempotency_keys
        if self.trace:
            body["trace_ids"] = [new_trace_id() for _ in params_list]
        async with self.request(
            "POST",
            f"{self.base_url}/execute_batch",
            retry_overload=True,
            json=body,
        ) as r:
            r.raise_for_status()
            out = await r.json()
            trace_ids = out.get("trace_ids") or [None] * len(out["task_ids"])
            return [
                AsyncTaskHandle(self, tid, trace)
                for tid, trace in zip(out["task_ids"], trace_ids)
            ]

    async def wait_many(
        self, task_ids: list[str], wait: float = 0.0
    ) -> tuple[dict[str, tuple[str, str]], list[str], list[str]]:
        """The multiplexed long-poll (``POST /results/wait``), async twin
        of the sync SDK's wait_many: many task ids, ONE parked request;
        returns ``(results, pending, unknown)`` with ``results`` mapping
        newly-terminal ids to raw ``(status, result)`` pairs. The gateway
        replies as soon as ANY watched task is terminal — loop over waves
        until ``pending`` empties."""
        async with self.request(
            "POST",
            f"{self.base_url}/results/wait",
            json={"task_ids": list(task_ids), "wait": wait},
            timeout=aiohttp.ClientTimeout(total=wait + 15.0),
        ) as r:
            r.raise_for_status()
            body = await r.json()
        results = {
            tid: (entry["status"], entry["result"])
            for tid, entry in body.get("results", {}).items()
        }
        return results, body.get("pending", []), body.get("unknown", [])

    async def delete_task(self, task_id: str) -> None:
        """Free a terminal task's store record (409 while it is live)."""
        async with self.request(
            "DELETE", f"{self.base_url}/task/{task_id}"
        ) as r:
            r.raise_for_status()

    async def cancel(self, task_id: str, force: bool = False) -> bool:
        """POST /cancel/{task_id}; True when the task is now CANCELLED.
        409 (RUNNING) maps to False — "too late" is an answer, not an
        error. ``force=True`` requests a mid-run interrupt of a RUNNING
        task (202, still False; sync FaaSClient.cancel)."""
        async with self.request(
            "POST",
            f"{self.base_url}/cancel/{task_id}",
            json={"force": True} if force else None,
        ) as r:
            if r.status == 409:
                return False
            r.raise_for_status()
            return bool((await r.json()).get("cancelled"))

    async def run(
        self, fn: Callable, *args: Any, timeout: float = 60.0, **kwargs: Any
    ) -> Any:
        handle = await self.submit(await self.register(fn), *args, **kwargs)
        return await handle.result(timeout)

    def graph(self) -> "AsyncGraphBuilder":
        """Start a task-graph submission (async twin of
        FaaSClient.graph()): ``g.call(...)`` stays synchronous and cheap
        (callables register lazily at submit); ``await g.submit()`` posts
        the whole DAG in one call."""
        return AsyncGraphBuilder(self)

    async def execute_graph(self, nodes: list[dict]) -> dict:
        """Raw graph submit (wire format of POST /execute_graph)."""
        async with self.request(
            "POST",
            f"{self.base_url}/execute_graph",
            retry_overload=True,
            json={"nodes": nodes},
        ) as r:
            r.raise_for_status()
            return await r.json()


@dataclass
class AsyncGraphNode:
    """One node of an async graph submission — a dependency reference
    before submit(), an :class:`AsyncTaskHandle` delegate after. A
    dep-poisoned node's ``await result()`` raises
    :class:`TaskDependencyError` naming the failed parent."""

    builder: "AsyncGraphBuilder"
    index: int
    task_id: str | None = None
    trace_id: str | None = None

    @property
    def handle(self) -> AsyncTaskHandle:
        if self.task_id is None:
            raise RuntimeError(
                "graph not submitted yet: await GraphBuilder.submit() first"
            )
        return AsyncTaskHandle(self.builder.client, self.task_id, self.trace_id)

    async def status(self) -> str:
        return await self.handle.status()

    async def result(
        self, timeout: float = 60.0, poll_interval: float = 0.01
    ) -> Any:
        return await self.handle.result(timeout, poll_interval)

    async def cancel(self, force: bool = False) -> bool:
        return await self.handle.cancel(force=force)


class AsyncGraphBuilder:
    """The sync GraphBuilder's async twin. ``call`` is synchronous (graph
    assembly is pure bookkeeping — awaiting per node would serialize a
    wide fan-out for nothing); callables are registered at submit() time
    through the shared dedup memo, one HTTP round per distinct function."""

    def __init__(self, client: AsyncFaaSClient) -> None:
        self.client = client
        #: (fn-or-id, args, kwargs, deps, hints) per node until submit
        self._calls: list[tuple] = []
        self._handles: list[AsyncGraphNode] = []
        self._submitted = False

    def call(
        self,
        fn: "Callable | str",
        *args: Any,
        after: "list[AsyncGraphNode] | tuple[AsyncGraphNode, ...]" = (),
        priority: int | None = None,
        cost: float | None = None,
        timeout: float | None = None,
        deadline: float | None = None,
        **kwargs: Any,
    ) -> AsyncGraphNode:
        if self._submitted:
            raise RuntimeError("graph already submitted")
        deps: list[int] = []
        for dep in after:
            if not isinstance(dep, AsyncGraphNode) or dep.builder is not self:
                raise ValueError(
                    "'after' entries must be AsyncGraphNodes from this builder"
                )
            if dep.index not in deps:
                deps.append(dep.index)
        hints = {
            "priority": priority,
            "cost": cost,
            "timeout": timeout,
            "deadline": deadline,
        }
        handle = AsyncGraphNode(self, len(self._calls))
        self._calls.append((fn, args, kwargs, deps, hints))
        self._handles.append(handle)
        return handle

    async def submit(self) -> list[AsyncGraphNode]:
        if self._submitted:
            raise RuntimeError("graph already submitted")
        loop = asyncio.get_running_loop()
        nodes: list[dict] = []
        for fn, args, kwargs, deps, hints in self._calls:
            function_id = (
                fn if isinstance(fn, str) else await self.client.register(fn)
            )
            payload = await loop.run_in_executor(
                None, lambda a=args, k=kwargs: pack_params(*a, **k)
            )
            node: dict = {
                "function_id": function_id,
                "payload": payload,
                "depends_on": deps,
            }
            for key, value in hints.items():
                if value is not None:
                    node[key] = value
            nodes.append(node)
        out = await self.client.execute_graph(nodes)
        self._submitted = True
        trace_ids = out.get("trace_ids") or [None] * len(out["task_ids"])
        for handle, task_id, trace in zip(
            self._handles, out["task_ids"], trace_ids
        ):
            handle.task_id = task_id
            handle.trace_id = trace
        return list(self._handles)


__all__ = [
    "AsyncFaaSClient",
    "AsyncGraphBuilder",
    "AsyncGraphNode",
    "AsyncTaskHandle",
    "TaskCancelledError",
    "TaskDependencyError",
    "TaskExpiredError",
    "TaskFailedError",
]
