"""Client SDK for the REST gateway (sync; async lives in
tpu_faas.client.aio, imported lazily so sync users don't pay for aiohttp)."""

from tpu_faas.client.sdk import (
    FaaSClient,
    GraphBuilder,
    GraphNode,
    TaskCancelledError,
    TaskDependencyError,
    TaskExpiredError,
    TaskFailedError,
    TaskHandle,
)

# async names stay OUT of __all__: `import *` must not eagerly pull aiohttp
__all__ = [
    "FaaSClient", "TaskHandle", "GraphBuilder", "GraphNode",
    "TaskCancelledError", "TaskDependencyError", "TaskExpiredError",
    "TaskFailedError",
]

_LAZY_ASYNC = ("AsyncFaaSClient", "AsyncTaskHandle", "AsyncGraphBuilder",
               "AsyncGraphNode")


def __getattr__(name: str):
    if name in _LAZY_ASYNC:
        from tpu_faas.client import aio

        return getattr(aio, name)
    raise AttributeError(name)
