"""Client SDK for the REST gateway (sync; async lives in
tpu_faas.client.aio, imported lazily so sync users don't pay for aiohttp)."""

from tpu_faas.client.sdk import FaaSClient, TaskHandle, TaskFailedError

__all__ = ["FaaSClient", "TaskHandle", "TaskFailedError", "AsyncFaaSClient"]


def __getattr__(name: str):
    if name == "AsyncFaaSClient":
        from tpu_faas.client.aio import AsyncFaaSClient

        return AsyncFaaSClient
    raise AttributeError(name)
