"""Client SDK for the REST gateway."""

from tpu_faas.client.sdk import FaaSClient, TaskHandle, TaskFailedError

__all__ = ["FaaSClient", "TaskHandle", "TaskFailedError"]
