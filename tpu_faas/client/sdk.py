"""Python client SDK.

The reference has no SDK — every test/benchmark hand-rolls requests + dill
(e.g. test_client.py:95-129). This wraps the four REST endpoints (SURVEY §0.1)
plus serialization and result polling into an ergonomic client, while keeping
the raw wire format identical so hand-rolled clients interoperate.
"""

from __future__ import annotations

import time
import uuid
import weakref
from dataclasses import dataclass
from typing import Any, Callable

import requests
from requests.adapters import HTTPAdapter, Retry

from tpu_faas.core.executor import pack_params
from tpu_faas.core.payload import payload_digest
from tpu_faas.core.serialize import deserialize, serialize
from tpu_faas.core.task import DEP_FAILED_PREFIX, TaskStatus
from tpu_faas.obs.tracectx import new_trace_id
from tpu_faas.utils.backoff import Backoff, BackoffPolicy


class _FnMemo:
    """Client-side function dedup — the SDK half of the payload plane.

    Two memo levels, both bounded:

    - ``serialize_fn`` caches the dill+base64 payload per CALLABLE
      IDENTITY (id + weakref liveness check, so a recycled id can never
      serve another function's bytes): a submit loop that registers or
      re-serializes the same function per call stops paying dill per
      iteration;
    - ``function_id_for``/``note_registered`` dedup registration by
      payload CONTENT (sha256): register(fn) called N times — or called
      with two closures that serialize identically — yields one
      function_id and one HTTP round trip.

    Correctness does not depend on either cache: a miss just pays the
    old cost, and the gateway's own register-once dedup (payload-plane
    mode) catches what the client-side memo can't see across processes.

    The one semantic the identity memo trades away: mutating state a
    callable CLOSES OVER (cell contents, ``__defaults__``) and
    re-registering the same object returns the originally-serialized
    bytes — the memo keys on object identity, not captured state (a
    per-call deep content probe would cost what the memo saves). Code
    that mutates-and-re-registers should pass a fresh callable (def/
    lambda re-evaluation gives one) — the same discipline dill's own
    snapshot-at-serialize behavior already demands between submits.
    """

    _CAP = 1024

    def __init__(self) -> None:
        self._payloads: dict[int, tuple[weakref.ref, str]] = {}
        self._registered: dict[str, str] = {}

    def serialize_fn(self, fn: Callable) -> str:
        entry = self._payloads.get(id(fn))
        if entry is not None:
            ref, payload = entry
            if ref() is fn:
                return payload
            del self._payloads[id(fn)]  # id recycled: stale entry
        payload = serialize(fn)
        try:
            ref = weakref.ref(fn)
        except TypeError:
            return payload  # not weakref-able: correct but unmemoized
        while len(self._payloads) >= self._CAP:
            self._payloads.pop(next(iter(self._payloads)))
        self._payloads[id(fn)] = (ref, payload)
        return payload

    def function_id_for(self, payload: str) -> str | None:
        return self._registered.get(payload_digest(payload))

    def note_registered(self, payload: str, function_id: str) -> None:
        while len(self._registered) >= self._CAP:
            self._registered.pop(next(iter(self._registered)))
        self._registered[payload_digest(payload)] = function_id


class TaskFailedError(Exception):
    def __init__(self, task_id: str, cause: object) -> None:
        super().__init__(f"task {task_id} FAILED: {cause!r}")
        self.task_id = task_id
        self.cause = cause


class TaskDependencyError(TaskFailedError):
    """Raised by result() on a dep-poisoned graph node: a parent reached a
    FAILED/EXPIRED/CANCELLED terminal, so this node was failed by the
    store's promotion plane WITHOUT ever being dispatched — no side
    effects exist for it (unlike its failed ancestor, which may have run
    partially). ``parent_id`` names the direct parent whose failure
    poisoned it (for transitive poisoning, the parent is itself poisoned
    and its own result carries the next hop up).

    Retry semantics: resubmitting the poisoned subgraph is safe — none of
    its nodes executed. Address the ROOT CAUSE first: fetch the parent's
    result (``client.raw_result(parent_id)``) for the original failure,
    fix/resubmit that node, then resubmit the dependents (graph
    submissions are not idempotency-keyed; a resubmit creates fresh
    nodes). Subclasses TaskFailedError, so code that catches the generic
    failure keeps working."""

    def __init__(self, task_id: str, parent_id: str, cause: object) -> None:
        super().__init__(task_id, cause)
        self.parent_id = parent_id


def _maybe_dependency_error(task_id: str, value: object):
    """The poison protocol is message-shaped (``dep_failed:<parent>: ...``
    on a RuntimeError), not dill-class-shaped, so any client can detect it
    without import coupling. Returns the specific error or None."""
    message = str(value)
    if isinstance(value, Exception) and message.startswith(DEP_FAILED_PREFIX):
        parent = message[len(DEP_FAILED_PREFIX):].split(":", 1)[0].strip()
        return TaskDependencyError(task_id, parent, value)
    return None


class TaskExpiredError(Exception):
    """Raised by result() when the task's terminal status is EXPIRED: its
    queue deadline (the ``deadline`` submit hint) lapsed while it was
    still QUEUED, so the dispatcher shed it — the function NEVER ran, no
    side effects exist. Distinct from CANCELLED (an explicit client act)
    and from the execution ``timeout`` hint (which interrupts a RUNNING
    task and surfaces as FAILED/TaskTimeout)."""

    def __init__(self, task_id: str) -> None:
        super().__init__(
            f"task {task_id} expired in queue before dispatch"
        )
        self.task_id = task_id


#: Longest single backoff sleep either SDK will take, whatever the server
#: (or a misconfigured proxy) puts in Retry-After — an hour-scale header
#: must not hang a submit() thread for an hour.
_RETRY_AFTER_CAP_S = 30.0

#: Overload (429/503) retry schedule, shared verbatim with the async
#: SDK: 0.25 s floor doubling to a 30 s cap, multiplicative jitter so a
#: rejected burst doesn't re-arrive as the same synchronized burst.
OVERLOAD_BACKOFF = BackoffPolicy(
    floor_s=0.25, factor=2.0, cap_s=30.0, jitter_lo=0.8, jitter_hi=1.3
)


def _retry_after_s(response, default: float) -> float:
    """The server's Retry-After (delay-seconds form), else ``default``;
    clamped to ``_RETRY_AFTER_CAP_S`` — the value is caller-controlled
    input from the network, not something to sleep on unbounded."""
    raw = response.headers.get("Retry-After")
    try:
        return min(max(0.0, float(raw)), _RETRY_AFTER_CAP_S)
    except (TypeError, ValueError):
        return default


class TaskCancelledError(Exception):
    """Raised by result() when the task's terminal status is CANCELLED —
    either cancelled while still QUEUED (never ran, no side effects) or
    force-cancelled mid-run (interrupted; side effects may have PARTIALLY
    executed). The terminal record doesn't distinguish the two; callers
    that care about side effects must not assume the task never started."""

    def __init__(self, task_id: str) -> None:
        super().__init__(f"task {task_id} was cancelled before completing")
        self.task_id = task_id


@dataclass
class TaskHandle:
    client: "FaaSClient"
    task_id: str
    #: distributed trace id of this submit (trace-enabled clients against
    #: a --trace gateway); None otherwise. Key for GET /trace/<task_id>'s
    #: cross-process timeline and for joining JSON logs fleet-wide.
    trace_id: str | None = None

    def status(self) -> str:
        return self.client.status(self.task_id)

    def done(self) -> bool:
        return TaskStatus(self.status()).is_terminal()

    def forget(self) -> None:
        """Delete this task's store record once terminal (frees the store;
        the gateway refuses with 409 while the task is still live)."""
        self.client.delete_task(self.task_id)

    def cancel(self, force: bool = False) -> bool:
        """Best-effort cancel; True when the record now reads CANCELLED.
        False when it could not be cancelled — already RUNNING or already
        terminal. True is best-effort, not a guarantee the function never
        executes: a cancel racing a concurrent dispatch can lose
        (store/base.py cancel_task), in which case the task runs and the
        record converges to COMPLETED/FAILED — poll status() before
        relying on side effects having been suppressed.

        ``force=True`` additionally asks a RUNNING task to stop: its
        worker interrupts it mid-run and ships a terminal CANCELLED
        result. Asynchronous — this call still returns False for a
        RUNNING task; await the outcome via status()/result() (which
        raises TaskCancelledError once the interrupt lands, or returns
        the value if the task beat the signal)."""
        return self.client.cancel(self.task_id, force=force)

    def result(self, timeout: float = 60.0, poll_interval: float = 0.01) -> Any:
        """Wait until terminal; return the deserialized value or raise
        :class:`TaskFailedError` with the deserialized exception. Uses the
        gateway's long-poll (``?wait=``) so each round trip parks at the
        gateway instead of hammering it; ``poll_interval`` only paces the
        degenerate wait<=0 rounds right at the deadline — when the SERVER
        parked the request, the park already was the pacing, and sleeping
        another poll_interval on top would put a client-side floor under
        every result delivery (against an express-lane gateway the whole
        submit->result path can be sub-millisecond). A non-terminal reply
        that came back in well under the requested wait means the server
        did NOT park (gateway draining/stopping, or a wait-oblivious
        foreign gateway) — those rounds pace, or the loop would hot-spin
        zero-delay requests at the worst moment."""
        deadline = time.monotonic() + timeout
        while True:
            remaining = deadline - time.monotonic()
            wait = max(0.0, min(remaining, 5.0))
            t_req = time.monotonic()
            status, payload = self.client.raw_result(self.task_id, wait=wait)
            done, value = _unwrap_terminal(self.task_id, status, payload)
            if done:
                return value
            if time.monotonic() > deadline:
                raise TimeoutError(
                    f"task {self.task_id} still {status} after {timeout}s"
                )
            if wait <= 0 or time.monotonic() - t_req < 0.5 * wait:
                time.sleep(poll_interval)


def _unwrap_terminal(task_id: str, status: str, payload: str):
    """(done, value) for one /result poll — the single place that knows the
    terminal-status protocol (FAILED carries a serialized exception;
    CANCELLED and EXPIRED carry no result at all)."""
    if not TaskStatus(status).is_terminal():
        return False, None
    if status == str(TaskStatus.CANCELLED):
        raise TaskCancelledError(task_id)
    if status == str(TaskStatus.EXPIRED):
        raise TaskExpiredError(task_id)
    value = deserialize(payload)
    if status == str(TaskStatus.FAILED):
        dep_error = _maybe_dependency_error(task_id, value)
        if dep_error is not None:
            raise dep_error
        raise TaskFailedError(task_id, value)
    return True, value


class FaaSClient:
    def __init__(
        self,
        base_url: str = "http://127.0.0.1:8000",
        connect_retries: int = 5,
        overload_retries: int = 4,
        auto_idempotency: bool = True,
        trace: bool = False,
        tenant: str | None = None,
    ) -> None:
        """``overload_retries``: how many times a submit rejected with
        429/503 (admission brownout, saturated system, store breaker) is
        retried, honoring the server's ``Retry-After`` with jittered
        exponential backoff; 0 surfaces the HTTPError on the first
        reject. ``auto_idempotency``: mint a fresh idempotency key per
        submit when the caller supplied none, so those retries (and any
        manual re-send after a lost response) are duplicate-safe end to
        end — the retry addresses the SAME task record. ``trace``: mint a
        distributed trace id per submit (obs/tracectx) and send it with
        the request; against a ``--trace`` gateway the returned handles
        carry ``trace_id`` and ``/trace/<task_id>`` assembles the
        cross-process timeline. Harmless against a trace-disabled
        gateway (the field is ignored there). ``tenant``: this client's
        tenant identity (tpu_faas/tenancy) — sent as ``X-Tenant-Id`` on
        every request, so the dispatcher's weighted-fair tick accounts
        the submits to it; None (the default) is the shared default
        tenant, and the header is ignored by tenancy-oblivious
        gateways."""
        self.base_url = base_url.rstrip("/")
        self.overload_retries = int(overload_retries)
        self.auto_idempotency = bool(auto_idempotency)
        self.trace = bool(trace)
        self.tenant = tenant
        #: serialize()/register dedup (see _FnMemo)
        self._memo = _FnMemo()
        self.http = requests.Session()
        if tenant is not None:
            # session-wide: single, batch, and graph submits all carry it
            self.http.headers["X-Tenant-Id"] = str(tenant)
        # retry CONNECTION-establishment failures only (gateway restarting
        # behind a load balancer): nothing has reached the wire yet, so the
        # retry is safe even for POSTs — re-sending an /execute_function
        # whose first attempt may have been APPLIED would run the task
        # twice, so read/status errors are deliberately never retried
        adapter = HTTPAdapter(
            max_retries=Retry(
                total=None,
                connect=connect_retries,
                read=0,
                status=0,
                # 'other' (SSL/proxy errors) must be 0 too: urllib3 treats a
                # None counter as unbounded, which would retry a bad cert
                # forever instead of raising
                other=0,
                # window must outlast a COLD gateway start (interpreter +
                # aiohttp import is seconds), not just a socket blip.
                # urllib3 sleeps factor*2^(n-1) per retry: 0+1+2+4+8 ~= 15 s
                # worst case against a dead gateway; a measured live cold
                # start bridged at ~7 s
                backoff_factor=0.5,
            )
        )
        self.http.mount("http://", adapter)
        self.http.mount("https://", adapter)

    def _post_submit(self, url: str, body: dict) -> requests.Response:
        """POST a submit with overload backoff: 429/503 replies are
        retried up to ``overload_retries`` times, sleeping the server's
        ``Retry-After`` (or the ``OVERLOAD_BACKOFF`` schedule when
        absent) with multiplicative jitter. Safe for submits because
        every retried body carries an idempotency key (auto-minted when
        the caller gave none) — the re-send addresses the same task
        record. The final reject is returned (not raised): callers keep
        their raise_for_status semantics."""
        bo = Backoff(OVERLOAD_BACKOFF)
        for attempt in range(self.overload_retries + 1):
            r = self.http.post(url, json=body)
            if r.status_code not in (429, 503) or attempt == self.overload_retries:
                return r
            time.sleep(bo.next(hint=_retry_after_s(r, bo.peek())))
        return r

    # -- raw endpoints (wire format identical to SURVEY §0.1) --------------
    def register_payload(self, name: str, payload: str) -> str:
        r = self.http.post(
            f"{self.base_url}/register_function",
            json={"name": name, "payload": payload},
        )
        r.raise_for_status()
        return r.json()["function_id"]

    def execute_payload(
        self,
        function_id: str,
        payload: str,
        priority: int | None = None,
        cost: float | None = None,
        timeout: float | None = None,
        idempotency_key: str | None = None,
        deadline: float | None = None,
        trace_id: str | None = None,
        parent_span: str | None = None,
        speculative: bool = False,
        slo_class: str | None = None,
    ) -> str:
        return self._execute(
            function_id,
            payload,
            priority=priority,
            cost=cost,
            timeout=timeout,
            idempotency_key=idempotency_key,
            deadline=deadline,
            trace_id=trace_id,
            parent_span=parent_span,
            speculative=speculative,
            slo_class=slo_class,
        )["task_id"]

    def _execute(
        self,
        function_id: str,
        payload: str,
        priority: int | None = None,
        cost: float | None = None,
        timeout: float | None = None,
        idempotency_key: str | None = None,
        deadline: float | None = None,
        trace_id: str | None = None,
        parent_span: str | None = None,
        speculative: bool = False,
        slo_class: str | None = None,
    ) -> dict:
        """One submit; returns the gateway's parsed response body (the
        handle constructors read ``trace_id`` off it — present only when
        the gateway runs ``--trace`` and the record was actually
        created)."""
        body: dict = {"function_id": function_id, "payload": payload}
        if priority is not None:
            body["priority"] = priority
        if slo_class is not None:
            body["slo_class"] = slo_class
        if cost is not None:
            body["cost"] = cost
        if timeout is not None:
            body["timeout"] = timeout
        if deadline is not None:
            body["deadline"] = deadline
        if speculative:
            body["speculative"] = True
        if trace_id is None and self.trace:
            trace_id = new_trace_id()
        if trace_id is not None:
            body["trace_id"] = trace_id
        if parent_span is not None:
            body["parent_span"] = parent_span
        if idempotency_key is None and self.auto_idempotency:
            idempotency_key = uuid.uuid4().hex
        if idempotency_key is not None:
            body["idempotency_key"] = idempotency_key
        r = self._post_submit(f"{self.base_url}/execute_function", body)
        r.raise_for_status()
        return r.json()

    def status(self, task_id: str) -> str:
        r = self.http.get(f"{self.base_url}/status/{task_id}")
        r.raise_for_status()
        return r.json()["status"]

    def delete_task(self, task_id: str) -> None:
        r = self.http.delete(f"{self.base_url}/task/{task_id}")
        r.raise_for_status()

    def cancel(self, task_id: str, force: bool = False) -> bool:
        """POST /cancel/{task_id}; True when the task is now CANCELLED.
        409 (RUNNING — the gateway refuses) maps to False rather than an
        exception: "too late to cancel" is an expected answer, not an
        error. ``force=True`` sends ``{"force": true}`` — a RUNNING task
        gets a mid-run interrupt request (202, still False here; the
        record converges via the result path)."""
        r = self.http.post(
            f"{self.base_url}/cancel/{task_id}",
            json={"force": True} if force else None,
        )
        if r.status_code == 409:
            return False
        r.raise_for_status()
        return bool(r.json().get("cancelled"))

    def raw_result(self, task_id: str, wait: float = 0.0) -> tuple[str, str]:
        """``wait`` > 0 long-polls at the gateway (capped server-side). The
        HTTP read timeout is wait + margin — a parked request against a
        wedged gateway must fail instead of blocking past the caller's own
        deadline forever."""
        params = {"wait": wait} if wait > 0 else None
        r = self.http.get(
            f"{self.base_url}/result/{task_id}",
            params=params,
            timeout=(5.0, wait + 15.0),
        )
        r.raise_for_status()
        body = r.json()
        return body["status"], body["result"]

    def wait_many(
        self, task_ids: list[str], wait: float = 0.0
    ) -> tuple[dict[str, tuple[str, str]], list[str], list[str]]:
        """The multiplexed long-poll (``POST /results/wait``): many task
        ids, ONE parked request — replaces a serial per-id long-poll
        rotation when waiting on a batch. Returns ``(results, pending,
        unknown)`` where ``results`` maps each newly-terminal task_id to
        its raw ``(status, result)`` pair (feed :func:`_unwrap_terminal` /
        deserialize as with raw_result), ``pending`` lists watched ids
        still live, and ``unknown`` ids the gateway found no record for.
        The gateway replies as soon as ANY watched task is terminal, so
        callers loop over waves until ``pending`` empties."""
        r = self.http.post(
            f"{self.base_url}/results/wait",
            json={"task_ids": list(task_ids), "wait": wait},
            timeout=(5.0, wait + 15.0),
        )
        r.raise_for_status()
        body = r.json()
        results = {
            tid: (entry["status"], entry["result"])
            for tid, entry in body.get("results", {}).items()
        }
        return results, body.get("pending", []), body.get("unknown", [])

    # -- ergonomic layer ---------------------------------------------------
    def register(self, fn: Callable, name: str | None = None) -> str:
        """Register ``fn``, deduplicated twice over: the serialize() of an
        unchanged callable is memoized, and re-registering content this
        client already registered returns the existing function_id with
        no HTTP round trip at all (run()/map() in a loop stop paying a
        registration per call)."""
        payload = self._memo.serialize_fn(fn)
        function_id = self._memo.function_id_for(payload)
        if function_id is not None:
            return function_id
        function_id = self.register_payload(name or fn.__name__, payload)
        self._memo.note_registered(payload, function_id)
        return function_id

    def submit(self, function_id: str, *args: Any, **kwargs: Any) -> TaskHandle:
        payload = pack_params(*args, **kwargs)
        body = self._execute(function_id, payload)
        return TaskHandle(self, body["task_id"], body.get("trace_id"))

    def submit_with(
        self,
        function_id: str,
        args: tuple = (),
        kwargs: dict | None = None,
        *,
        priority: int | None = None,
        cost: float | None = None,
        timeout: float | None = None,
        idempotency_key: str | None = None,
        deadline: float | None = None,
        speculative: bool = False,
        slo_class: str | None = None,
    ) -> TaskHandle:
        """submit() plus scheduling hints. The hints can't ride submit()
        itself — its **kwargs belong to the remote function — so args/kwargs
        are explicit here. ``priority``: higher is admitted first under
        overload (FCFS within a class); ``cost``: estimated run-cost, used to
        pair expensive tasks with fast workers; ``timeout``: execution time
        budget in seconds, enforced inside the worker's pool child — the
        task FAILs with TaskTimeout instead of eating a process slot
        forever; ``deadline``: submit-TTL in seconds — a task still QUEUED
        this long after submit is shed to the terminal EXPIRED status
        (result() raises TaskExpiredError) instead of burning a worker
        slot on an answer nobody is waiting for; ``idempotency_key``: a
        client-chosen string making this submit safely retryable — a
        re-send (lost response, impatient caller) addresses the SAME task
        instead of running it twice (auto-minted per submit unless
        auto_idempotency=False); ``speculative``: declares the task IDEMPOTENT
        and hedge-eligible — a dispatcher running --speculate-mult may race a
        replica against a straggling execution (tpu_faas/spec; exactly one
        result is ever delivered, the store's first-wins write arbitrates).
        Only set it for functions safe to execute more than once.
        ``slo_class``: the task's declared SLO class (``interactive``/
        ``batch``/``default``, obs/attribution.py) — labels its latency
        samples and attribution counters when the observability plane
        runs with TPU_FAAS_OBS_CLASS=1; undeclared tasks default by
        priority sign."""
        payload = pack_params(*args, **(kwargs or {}))
        body = self._execute(
            function_id,
            payload,
            priority=priority,
            cost=cost,
            timeout=timeout,
            idempotency_key=idempotency_key,
            deadline=deadline,
            speculative=speculative,
            slo_class=slo_class,
        )
        return TaskHandle(self, body["task_id"], body.get("trace_id"))

    def submit_many(
        self,
        function_id: str,
        params_list: list[tuple[tuple, dict]],
        priorities: list[int] | None = None,
        costs: list[float] | None = None,
        timeouts: list[float] | None = None,
        idempotency_keys: list[str | None] | None = None,
        deadlines: list[float] | None = None,
        speculative: bool = False,
        slo_class: str | None = None,
    ) -> list[TaskHandle]:
        """Batch submit over ONE HTTP call (+ one pipelined store round
        trip): ``params_list`` holds (args, kwargs) pairs. N single submits
        cost N round trips on both hops — this is the bulk path.
        ``priorities``/``costs``/``timeouts``/``deadlines`` are optional
        scheduling-hint lists parallel to ``params_list``. Keys are
        auto-minted per item (unless auto_idempotency=False or the caller
        passed its own list), so an overload-rejected batch retries
        duplicate-safe."""
        body: dict = {
            "function_id": function_id,
            "payloads": [
                pack_params(*args, **kwargs) for args, kwargs in params_list
            ],
        }
        if priorities is not None:
            body["priorities"] = priorities
        if costs is not None:
            body["costs"] = costs
        if timeouts is not None:
            body["timeouts"] = timeouts
        if deadlines is not None:
            body["deadlines"] = deadlines
        if speculative:
            # one flag for the whole batch: the idempotency promise is
            # per-call (tpu_faas/spec hedge eligibility)
            body["speculative"] = True
        if slo_class is not None:
            # one declared SLO class for the whole batch (the gateway
            # applies it element-wise), matching the wire contract
            body["slo_class"] = slo_class
        if idempotency_keys is None and self.auto_idempotency:
            idempotency_keys = [uuid.uuid4().hex for _ in params_list]
        if idempotency_keys is not None:
            body["idempotency_keys"] = idempotency_keys
        if self.trace:
            body["trace_ids"] = [new_trace_id() for _ in params_list]
        r = self._post_submit(f"{self.base_url}/execute_batch", body)
        r.raise_for_status()
        out = r.json()
        # the gateway's echo is authoritative: null for dedup hits (their
        # records carry the claim winner's trace), absent with tracing off
        trace_ids = out.get("trace_ids") or [None] * len(out["task_ids"])
        return [
            TaskHandle(self, tid, trace)
            for tid, trace in zip(out["task_ids"], trace_ids)
        ]

    def graph(self) -> "GraphBuilder":
        """Start a task-graph submission: ``g = client.graph()``, then
        ``h = g.call(fn, x)``, ``g.call(fn2, y, after=[h])``, ...,
        ``g.submit()``. Nodes run only after everything they depend on
        COMPLETED; a failed/cancelled/expired dependency fails its
        dependents without running them (result() raises
        :class:`TaskDependencyError`)."""
        return GraphBuilder(self)

    def execute_graph(self, nodes: list[dict]) -> dict:
        """Raw graph submit (wire format of POST /execute_graph); the
        ergonomic layer is :meth:`graph`."""
        r = self._post_submit(f"{self.base_url}/execute_graph", {"nodes": nodes})
        r.raise_for_status()
        return r.json()

    def run(
        self, fn: Callable, *args: Any, timeout: float = 60.0, **kwargs: Any
    ) -> Any:
        """Register + submit + wait, in one call."""
        return self.submit(self.register(fn), *args, **kwargs).result(timeout)

    def map(
        self,
        fn: Callable,
        iterable,
        timeout: float = 120.0,
        poll_interval: float = 0.01,
    ) -> list[Any]:
        """Pool.map-style batch: register once, submit every item, then wait
        on the whole wave with ONE parked multiplexed request per round
        (``wait_many`` — the reference's clients hand-roll a serial poll
        rotation instead, test_client.py:109-128); results come back in
        input order, and any FAILED task raises its TaskFailedError. A
        pre-express gateway (no /results/wait route) degrades to the
        serial long-poll rotation."""
        fid = self.register(fn)
        handles = [self.submit(fid, item) for item in iterable]
        deadline = time.monotonic() + timeout
        results: dict[int, Any] = {}
        pending = set(range(len(handles)))
        index_of = {h.task_id: i for i, h in enumerate(handles)}
        multiplex = True
        while pending:
            wait = min(2.0, max(0.0, deadline - time.monotonic()))
            t_req = time.monotonic()
            if multiplex:
                try:
                    got, _live, unknown = self.wait_many(
                        [handles[i].task_id for i in sorted(pending)],
                        wait=wait,
                    )
                except requests.HTTPError as exc:
                    if (
                        exc.response is not None
                        and exc.response.status_code == 404
                    ):
                        multiplex = False  # older gateway: serial rotation
                        continue
                    raise
                for tid, (status, payload) in got.items():
                    done, value = _unwrap_terminal(tid, status, payload)
                    if done:
                        results[index_of[tid]] = value
                        pending.discard(index_of[tid])
                if unknown:
                    # a watched record vanished mid-wait (swept/deleted):
                    # the serial rotation surfaced this as an immediate
                    # 404 — burning the remaining timeout on ids that can
                    # never resolve would hide which task died and why.
                    # (Delivered results above are consumed first: an id
                    # can never be both.)
                    raise requests.HTTPError(
                        f"task record(s) gone while waiting: {unknown}"
                    )
            else:
                first = min(pending)
                for i in sorted(pending):
                    status, payload = self.raw_result(
                        handles[i].task_id, wait=wait if i == first else 0.0
                    )
                    done, value = _unwrap_terminal(
                        handles[i].task_id, status, payload
                    )
                    if done:
                        results[i] = value
                        pending.discard(i)
            if pending:
                if time.monotonic() > deadline:
                    raise TimeoutError(
                        f"{len(pending)} of {len(handles)} tasks still "
                        f"running after {timeout}s"
                    )
                if wait <= 0 or time.monotonic() - t_req < 0.5 * wait:
                    # the server never parked (deadline imminent, or a
                    # draining/wait-oblivious gateway replied instantly):
                    # pace the residual spin; parked rounds need no
                    # client pacing
                    time.sleep(poll_interval)
        return [results[i] for i in range(len(handles))]


# -- task-graph builder ------------------------------------------------------


@dataclass
class GraphNode:
    """One node of a graph submission: a dependency reference before
    submit() (pass it in another call's ``after=[...]``), a task handle
    after (``task_id`` assigned; result()/status()/cancel() delegate to a
    :class:`TaskHandle`). A poisoned node's result() raises
    :class:`TaskDependencyError` naming the failed parent."""

    builder: "GraphBuilder"
    index: int
    task_id: str | None = None
    trace_id: str | None = None

    @property
    def handle(self) -> TaskHandle:
        if self.task_id is None:
            raise RuntimeError(
                "graph not submitted yet: call GraphBuilder.submit() first"
            )
        return TaskHandle(self.builder.client, self.task_id, self.trace_id)

    def status(self) -> str:
        return self.handle.status()

    def result(self, timeout: float = 60.0, poll_interval: float = 0.01):
        return self.handle.result(timeout, poll_interval)

    def cancel(self, force: bool = False) -> bool:
        return self.handle.cancel(force=force)

    def forget(self) -> None:
        self.handle.forget()


class GraphBuilder:
    """Accumulate a DAG locally, submit it in ONE call::

        g = client.graph()
        parts = [g.call(extract, shard) for shard in shards]   # fan-out
        merged = g.call(merge, after=parts)                    # fan-in
        g.submit()
        total = merged.result(timeout=120.0)

    ``call`` accepts a callable (registered through the client's dedup
    memo — N calls of one function cost one registration) or a
    function_id string, plus the usual scheduling hints. ``after`` lists
    the GraphNodes this node depends on; the gateway validates
    acyclicity, charges admission for the whole graph up front, and the
    store's promotion plane runs the frontier from there. submit() may be
    called once; it returns the nodes in call order."""

    def __init__(self, client: FaaSClient) -> None:
        self.client = client
        self._nodes: list[dict] = []
        self._handles: list[GraphNode] = []
        self._submitted = False

    def call(
        self,
        fn: "Callable | str",
        *args: Any,
        after: "list[GraphNode] | tuple[GraphNode, ...]" = (),
        priority: int | None = None,
        cost: float | None = None,
        timeout: float | None = None,
        deadline: float | None = None,
        **kwargs: Any,
    ) -> GraphNode:
        if self._submitted:
            raise RuntimeError("graph already submitted")
        function_id = fn if isinstance(fn, str) else self.client.register(fn)
        deps: list[int] = []
        for dep in after:
            if not isinstance(dep, GraphNode) or dep.builder is not self:
                raise ValueError(
                    "'after' entries must be GraphNodes from this builder"
                )
            if dep.index not in deps:
                deps.append(dep.index)
        node: dict = {
            "function_id": function_id,
            "payload": pack_params(*args, **kwargs),
            "depends_on": deps,
        }
        if priority is not None:
            node["priority"] = priority
        if cost is not None:
            node["cost"] = cost
        if timeout is not None:
            node["timeout"] = timeout
        if deadline is not None:
            node["deadline"] = deadline
        handle = GraphNode(self, len(self._nodes))
        self._nodes.append(node)
        self._handles.append(handle)
        return handle

    def submit(self) -> list[GraphNode]:
        if self._submitted:
            raise RuntimeError("graph already submitted")
        out = self.client.execute_graph(self._nodes)
        self._submitted = True
        trace_ids = out.get("trace_ids") or [None] * len(out["task_ids"])
        for handle, task_id, trace in zip(
            self._handles, out["task_ids"], trace_ids
        ):
            handle.task_id = task_id
            handle.trace_id = trace
        return list(self._handles)
