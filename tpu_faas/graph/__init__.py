"""Task graphs: DAG submission, store-side promotion, device-side frontier.

The subsystem spans four layers (ROADMAP item 4):

- **Submission** — the gateway's ``POST /execute_graph`` accepts a node
  list with intra-graph ``depends_on`` refs; :mod:`tpu_faas.graph.validate`
  proves acyclicity + size caps and yields a creation order (children
  before parents, so a parent can never finish against missing child
  records).
- **Promotion plane** — ``TaskStore.complete_dep_many``
  (tpu_faas/store/base.py): every landed terminal write decrements its
  children's pending counts (write-once per-edge claims + atomic hincrby);
  a count hitting zero flips WAITING -> QUEUED and announces on the
  ordinary bus; a FAILED/EXPIRED/CANCELLED parent poisons its transitive
  frontier (WAITING -> FAILED, ``dep_failed:<parent>``) without ever
  dispatching it.
- **Device frontier** — :mod:`tpu_faas.graph.frontier`: the tpu-push
  dispatcher keeps WAITING nodes resident beside the pending batch; the
  tick computes the readiness mask as one segment-reduce over the padded
  edge list INSIDE the jitted device step, plus a data-locality exchange
  that prefers the worker whose payload-plane cache already holds a
  parent's function.
- **Repair** — ``TaskStore.resolve_waiting``: the gateway's result-TTL
  sweeper re-derives an orphaned WAITING node's fate from its parents'
  terminal statuses, so a resolver crash can never strand a node forever.
"""

from tpu_faas.graph.validate import (
    GraphValidationError,
    MAX_GRAPH_NODES,
    validate_graph,
)

__all__ = [
    "GraphValidationError",
    "MAX_GRAPH_NODES",
    "validate_graph",
]
