"""Device-computed ready frontier for task graphs (tpu-push batch tick).

The tpu-push dispatcher keeps WAITING graph nodes resident beside the
pending batch and feeds the device step a padded edge list; the readiness
mask is ONE segment-reduce composed into the jitted tick
(sched/state._packed_tick), so dependency-aware placement happens where
placement already happens — not in a host pre-pass. The host side of this
module is pure bookkeeping: which nodes are waiting, which parents have
been CONFIRMED complete (confirmed = the store's promotion plane ran for
that parent, so the child's record is already QUEUED by the time the mask
can say "ready" — a dispatched frontier child is never WAITING store-side,
which is the invariant the race monitor's missing WAITING -> RUNNING
transition enforces).

Also here: the data-locality exchange. The worker that ran a COMPLETED
parent holds the parent's function in its payload-plane cache (PR 5), so a
ready child prefers that worker. The exchange is a jitted post-placement
pass that swaps a preferring task with the task currently holding its
preferred worker — only between EQUAL-SPEED workers, where the swap is
makespan-neutral by the rank-pairing argument (the multiset of
size/speed completion times is unchanged) and therefore a pure cache win.
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("T",))
def dep_ready_mask(
    edge_child: jnp.ndarray,  # i32[E] batch row per edge (T = dropped pad)
    edge_undone: jnp.ndarray,  # i32[E] 1 while the edge's parent is unconfirmed
    *,
    T: int,
) -> jnp.ndarray:
    """bool[T]: True where a batch row has no unconfirmed parents — the
    segment-reduce over the edge list. Rows without edges are ready (flat
    tasks and frontier-free batches compose for free)."""
    blocked = jnp.zeros(T, jnp.int32).at[edge_child].add(
        edge_undone, mode="drop"
    )
    return blocked == 0


def locality_exchange(
    assignment: jnp.ndarray,  # i32[T] worker row per task, -1 queued
    task_pref: jnp.ndarray,  # i32[T] preferred worker row, -1 none
    worker_speed: jnp.ndarray,  # f32[W]
) -> jnp.ndarray:
    """Swap preferring tasks toward their preferred workers, makespan-
    neutrally. For each preferred worker the (index-lowest) preferring
    task swaps assignments with that worker's (index-lowest) currently
    assigned task, iff both workers' speeds are equal (rank pairing makes
    an equal-speed swap change nothing but cache hit rate) and the holder
    is itself preference-free (so no task participates in two swaps). All
    scatters use distinct indices by construction; invalid lanes scatter
    out of range and drop."""
    T = assignment.shape[0]
    W = worker_speed.shape[0]
    tidx = jnp.arange(T, dtype=jnp.int32)
    BIG = jnp.int32(T)
    assigned = assignment >= 0
    a_clip = jnp.clip(assignment, 0, W - 1)
    p_clip = jnp.clip(task_pref, 0, W - 1)
    want = (
        (task_pref >= 0)
        & assigned
        & (task_pref != assignment)
        & (
            jnp.abs(worker_speed[a_clip] - worker_speed[p_clip])
            <= 1e-6 * jnp.maximum(worker_speed[a_clip], 1e-9)
        )
    )
    # representative holder per worker (lowest assigned task index)
    holder = (
        jnp.full(W, BIG, jnp.int32)
        .at[a_clip]
        .min(jnp.where(assigned, tidx, BIG))
    )
    # chosen wanter per preferred worker (a task wants exactly one worker,
    # so each task appears under at most one w)
    wanter = (
        jnp.full(W, BIG, jnp.int32)
        .at[p_clip]
        .min(jnp.where(want, tidx, BIG))
    )
    h = jnp.clip(holder, 0, T - 1)
    t = jnp.clip(wanter, 0, T - 1)
    valid = (holder < BIG) & (wanter < BIG) & (holder != wanter)
    # the holder must not itself be a preferring task: that makes every
    # task's swap membership unique (a wanter can't double as a holder,
    # because any worker holding it would fail this guard)
    valid = valid & ~want[h]
    w_ids = jnp.arange(W, dtype=jnp.int32)
    # scatter with mode="drop": invalid lanes target index T (out of range)
    t_idx = jnp.where(valid, t, T)
    h_idx = jnp.where(valid, h, T)
    old_of_t = assignment[t]
    out = assignment.at[t_idx].set(w_ids, mode="drop")
    out = out.at[h_idx].set(old_of_t, mode="drop")
    return out


def parent_pref_impl(
    pref_child: jnp.ndarray,  # i32[P] batch row per (child, holder) pair
    pref_row: jnp.ndarray,  # i32[P] worker row holding parent-result bytes
    pref_bytes: jnp.ndarray,  # f32[P] bytes that row holds for the child
    *,
    T: int,
) -> jnp.ndarray:
    """i32[T] preferred worker row per batch row (-1 none): the row
    holding the MOST of the child's parent-result bytes, ties to the
    lowest row. The result-data-plane sibling of the function-locality
    pref: a child placed on a holder consumes its parents straight from
    the worker's result cache (dep_digests on the TASK frame) instead of
    round-tripping bodies through the store.

    Un-jitted ``_impl`` per the solver-stack convention (PR 11/13/15):
    the XLA path traces it under :data:`parent_pref`'s jit, the fused-
    Pallas resident tick traces the same ops inside its one pallas_call —
    scatter-max then masked scatter-min, both mode="drop" so pad lanes
    (child = T, bytes = 0) fall out structurally."""
    best = (
        jnp.zeros(T, jnp.float32)
        .at[pref_child]
        .max(pref_bytes, mode="drop")
    )
    c = jnp.clip(pref_child, 0, T - 1)
    win = (pref_bytes > 0.0) & (pref_bytes >= best[c])
    BIG = jnp.int32(2**30)
    row = (
        jnp.full(T, BIG, jnp.int32)
        .at[jnp.where(win, pref_child, T)]
        .min(pref_row, mode="drop")
    )
    return jnp.where(row < BIG, row, jnp.int32(-1))


parent_pref = partial(jax.jit, static_argnames=("T",))(parent_pref_impl)


def pad_pref(
    child: list[int], row: list[int], nbytes: list[float], T: int
) -> tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Pad the host (child, holder row, bytes) triplets to the next power
    of two (bounded jit signatures, same discipline as :func:`pad_edges`)
    with dropped lanes (child = T, row = 0, bytes = 0)."""
    P = max(len(child), 1)
    k = 1 << (P - 1).bit_length()
    c = np.full(k, T, dtype=np.int32)
    r = np.zeros(k, dtype=np.int32)
    b = np.zeros(k, dtype=np.float32)
    if child:
        c[: len(child)] = child
        r[: len(row)] = row
        b[: len(nbytes)] = nbytes
    return c, r, b


def pad_edges(
    edge_child: list[int], edge_undone: list[int], T: int
) -> tuple[np.ndarray, np.ndarray]:
    """Pad the host edge list to the next power of two (bounded jit
    signatures) with dropped lanes (child = T, undone = 0)."""
    E = max(len(edge_child), 1)
    k = 1 << (E - 1).bit_length()
    child = np.full(k, T, dtype=np.int32)
    undone = np.zeros(k, dtype=np.int32)
    if edge_child:
        child[: len(edge_child)] = edge_child
        undone[: len(edge_undone)] = edge_undone
    return child, undone


class GraphFrontier:
    """Host bookkeeping of the device frontier: WAITING nodes held beside
    the pending batch, parent confirmations, and per-node preferred rows.

    A parent becomes ``done`` here ONLY when the dispatcher's
    complete_dep_many round for it succeeded (note_parent), which is what
    makes the device mask's "ready" imply "record already QUEUED". Nodes
    leave through pop() — dispatch, promotion-announce adoption into
    pending, poison, or reconciliation."""

    def __init__(self, cap: int = 8192) -> None:
        self.cap = cap
        #: task_id -> PendingTask (the payload source at dispatch time)
        self.waiting: dict[str, object] = {}
        #: task_id -> parent ids (immutable edge list from FIELD_DEPS)
        self.parents: dict[str, list[str]] = {}
        #: parent id -> waiting child ids (reverse index)
        self._children: dict[str, set[str]] = {}
        #: parent id -> (ok, worker_row, result_digest, result_size) once
        #: CONFIRMED terminal; kept only while some waiting child still
        #: references the parent. digest/size are None/0 outside the
        #: result data plane (--result-blobs) — the pref triplet builder
        #: then has nothing to weigh and the byte-locality lane stays off.
        self._parent_state: dict[str, tuple[bool, int, str | None, int]] = {}
        self.n_frontier_dispatches = 0

    def __len__(self) -> int:
        return len(self.waiting)

    def add(self, task, parent_ids: list[str]) -> bool:
        """Hold a WAITING node; False when full or already held (the
        promotion-announce path covers skipped nodes)."""
        tid = task.task_id
        if tid in self.waiting or len(self.waiting) >= self.cap:
            return False
        self.waiting[tid] = task
        self.parents[tid] = list(parent_ids)
        for pid in parent_ids:
            self._children.setdefault(pid, set()).add(tid)
        return True

    def has_waiting_children(self, parent_id: str) -> bool:
        return bool(self._children.get(parent_id))

    def note_parent(
        self,
        parent_id: str,
        ok: bool,
        row: int = -1,
        digest: str | None = None,
        size: int = 0,
    ) -> None:
        """A parent's terminal write landed AND its complete_dep_many round
        succeeded: flip its edges. ``row`` is the worker row that returned
        the result (the locality preference for ok parents); ``digest``/
        ``size`` identify the result body in the content-addressed plane
        when the producer shipped digest-form (--result-blobs) — what the
        byte-weighted pref lane scores children toward."""
        if self._children.get(parent_id):
            self._parent_state[parent_id] = (
                bool(ok), int(row), digest, int(size),
            )

    def pop(self, task_id: str):
        """Remove and return a held node (None if not held). Parent states
        nothing references anymore are dropped with it."""
        task = self.waiting.pop(task_id, None)
        if task is None:
            return None
        for pid in self.parents.pop(task_id, ()):
            kids = self._children.get(pid)
            if kids is not None:
                kids.discard(task_id)
                if not kids:
                    del self._children[pid]
                    self._parent_state.pop(pid, None)
        return task

    def confirmed_parents(
        self, task_id: str
    ) -> list[tuple[str, str | None, int]]:
        """(parent_id, result_digest, result_size) for every confirmed-OK
        parent of a held node — the dispatch-time source of the child's
        dep delivery (digest = None means the body lives in the store
        record). Captured BEFORE pop(): popping drops the edge list."""
        out: list[tuple[str, str | None, int]] = []
        for pid in self.parents.get(task_id, ()):
            state = self._parent_state.get(pid)
            if state is not None and state[0]:
                out.append((pid, state[2], state[3]))
        return out

    def failed_parent_of(self, task_id: str) -> str | None:
        """A confirmed-failed parent of this node, if any — the host-side
        fast drop for poisoned nodes (the store record is already FAILED
        by the promotion plane; the frontier just forgets)."""
        for pid in self.parents.get(task_id, ()):
            state = self._parent_state.get(pid)
            if state is not None and not state[0]:
                return pid
        return None

    def edge_arrays(
        self, rows: dict[int, str], T: int
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray | None]:
        """(edge_child, edge_undone, task_pref) for this tick's batch:
        ``rows`` maps batch row -> held task_id. task_pref is None when no
        node has a confirmed-ok parent row (skips the exchange pass and
        its jit signature entirely)."""
        edge_child: list[int] = []
        edge_undone: list[int] = []
        pref = np.full(T, -1, dtype=np.int32)
        any_pref = False
        for row, tid in rows.items():
            best = -1
            for pid in self.parents.get(tid, ()):
                state = self._parent_state.get(pid)
                done = state is not None and state[0]
                edge_child.append(row)
                edge_undone.append(0 if done else 1)
                if done and state[1] >= 0:
                    best = state[1]
            if best >= 0:
                pref[row] = best
                any_pref = True
        child, undone = pad_edges(edge_child, edge_undone, T)
        return child, undone, (pref if any_pref else None)

    def pref_arrays(
        self,
        rows: dict[int, str],
        T: int,
        holder_rows: dict[str, set[int]],
    ) -> tuple[np.ndarray, np.ndarray, np.ndarray] | None:
        """Deduped, padded (pref_child, pref_row, pref_bytes) triplets for
        :func:`parent_pref` — one lane per (batch row, candidate worker
        row) pair, weighted by how many of the child's confirmed parents'
        result bytes that worker's cache holds. ``holder_rows`` is the
        dispatcher's digest -> worker-row mirror. None when no waiting
        child has a digest-form parent held anywhere (the jitted tick
        keeps its pref-free signature)."""
        acc: dict[tuple[int, int], float] = {}
        for row, tid in rows.items():
            for pid in self.parents.get(tid, ()):
                state = self._parent_state.get(pid)
                if state is None or not state[0]:
                    continue
                digest, size = state[2], state[3]
                if not digest or size <= 0:
                    continue
                for hrow in holder_rows.get(digest, ()):
                    key = (row, int(hrow))
                    acc[key] = acc.get(key, 0.0) + float(size)
        if not acc:
            return None
        return pad_pref(
            [k[0] for k in acc],
            [k[1] for k in acc],
            list(acc.values()),
            T,
        )
