"""Graph-submission validation: refs, acyclicity, size caps.

The gateway admits a whole graph atomically (admission charges every node
up front), so validation must be complete BEFORE any store write: a cycle
discovered after half the nodes were created would leave acknowledged
WAITING records whose parents can never finish. Everything here is pure
and store-free.
"""

from __future__ import annotations

import os
from collections import deque

#: Hard cap on nodes per graph submission (env-overridable). Bounds the
#: gateway's create pipeline, the FIELD_CHILDREN/FIELD_DEPS field sizes,
#: and the device frontier's padded edge list.
MAX_GRAPH_NODES = int(os.environ.get("TPU_FAAS_MAX_GRAPH_NODES", "4096"))


class GraphValidationError(ValueError):
    """A graph submission the gateway must 400: bad refs, a cycle, or a
    size-cap violation. The message is client-facing."""


def _resolve_ref(ref, index: int, names: dict[str, int], n: int) -> int:
    """One depends_on entry -> node index. Accepts an integer index or a
    string naming another node's client-local ``id``."""
    if isinstance(ref, bool):
        raise GraphValidationError(
            f"nodes[{index}].depends_on contains a boolean; use an integer "
            "index or a node id string"
        )
    if isinstance(ref, int):
        if not 0 <= ref < n:
            raise GraphValidationError(
                f"nodes[{index}].depends_on references node {ref}, out of "
                f"range for {n} nodes"
            )
        return ref
    if isinstance(ref, str):
        target = names.get(ref)
        if target is None:
            raise GraphValidationError(
                f"nodes[{index}].depends_on references unknown node id "
                f"{ref!r}"
            )
        return target
    raise GraphValidationError(
        f"nodes[{index}].depends_on entries must be integer indices or "
        "node id strings"
    )


def validate_graph(
    nodes: list[dict], max_nodes: int | None = None
) -> tuple[list[list[int]], list[int]]:
    """Validate a graph submission; returns ``(deps, topo_order)`` where
    ``deps[i]`` is node i's parent indices (deduplicated, resolution of
    every depends_on ref) and ``topo_order`` is a topological order of the
    node indices (parents before children — Kahn's algorithm; its
    exhaustion proves acyclicity). Raises :class:`GraphValidationError`
    with a client-facing message on any violation."""
    cap = max_nodes if max_nodes is not None else MAX_GRAPH_NODES
    if not isinstance(nodes, list) or not nodes:
        raise GraphValidationError("'nodes' must be a non-empty list")
    if len(nodes) > cap:
        raise GraphValidationError(
            f"graph has {len(nodes)} nodes, above the cap of {cap} "
            "(TPU_FAAS_MAX_GRAPH_NODES); split the submission"
        )
    names: dict[str, int] = {}
    for i, node in enumerate(nodes):
        if not isinstance(node, dict):
            raise GraphValidationError(f"nodes[{i}] must be an object")
        name = node.get("id")
        if name is None:
            continue
        if not isinstance(name, str) or not name:
            raise GraphValidationError(
                f"nodes[{i}].id must be a non-empty string"
            )
        if name in names:
            raise GraphValidationError(
                f"nodes[{i}].id {name!r} duplicates nodes[{names[name]}].id"
            )
        names[name] = i
    n = len(nodes)
    deps: list[list[int]] = []
    for i, node in enumerate(nodes):
        raw = node.get("depends_on") or []
        if not isinstance(raw, list):
            raise GraphValidationError(
                f"nodes[{i}].depends_on must be a list"
            )
        seen: list[int] = []
        seen_set: set[int] = set()  # list keeps ref order; set keeps the
        # membership probe O(1) — a dense in-cap graph (4096 nodes x
        # thousands of refs) runs this inside the gateway event loop
        for ref in raw:
            parent = _resolve_ref(ref, i, names, n)
            if parent == i:
                raise GraphValidationError(
                    f"nodes[{i}] depends on itself"
                )
            if parent not in seen_set:
                seen_set.add(parent)
                seen.append(parent)
        deps.append(seen)
    # Kahn's algorithm: exhaustion == acyclic, and the pop order IS the
    # creation-safe topological order
    children: list[list[int]] = [[] for _ in range(n)]
    pending = [len(d) for d in deps]
    for i, d in enumerate(deps):
        for parent in d:
            children[parent].append(i)
    frontier = deque(i for i in range(n) if pending[i] == 0)
    topo: list[int] = []
    while frontier:
        i = frontier.popleft()
        topo.append(i)
        for child in children[i]:
            pending[child] -= 1
            if pending[child] == 0:
                frontier.append(child)
    if len(topo) != n:
        cyclic = sorted(i for i in range(n) if pending[i] > 0)
        raise GraphValidationError(
            f"graph contains a dependency cycle through nodes {cyclic[:8]}"
        )
    return deps, topo
