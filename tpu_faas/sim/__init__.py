"""Simulated worker fleets: drive the scheduler at 1k-4k workers without
sockets (SURVEY §7.7 — needed for the BASELINE configs the reference's
localhost-subprocess testing could never reach)."""

from tpu_faas.sim.fleet import SimFleet, SimResult

__all__ = ["SimFleet", "SimResult"]
