"""Discrete-event simulated worker fleet driving the real scheduler state.

The fleet models exactly what the scheduler can observe about real push
workers — registration capacity, heartbeats, results arriving when tasks
finish, crashes and rejoins — while skipping serialization and sockets, so
configs like "4k workers, 5% churn per tick" (BASELINE config 5) run in
seconds. The object under test is the production path: the same
:class:`SchedulerArrays` + fused ``scheduler_tick`` the TpuPushDispatcher
uses, not a model of it.
"""

from __future__ import annotations

import time
from dataclasses import dataclass, field

import numpy as np

from tpu_faas.sched.state import SchedulerArrays


@dataclass
class SimResult:
    completed: int
    lost: int  # tasks that vanished (must be 0: redistribution works)
    makespan: float  # sim-time until every task completed
    ticks: int
    tick_seconds: list[float] = field(default_factory=list)  # wall per tick

    @property
    def median_tick_ms(self) -> float:
        return float(np.median(self.tick_seconds) * 1e3)


class SimFleet:
    """n workers with heterogeneous speeds/capacities executing sized tasks
    in simulated time, with optional fail/rejoin churn."""

    def __init__(
        self,
        n_workers: int,
        max_pending: int,
        rng: np.random.Generator,
        procs_per_worker: int = 4,
        hetero: bool = True,
        time_to_expire: float = 10.0,
        max_slots: int = 8,
    ) -> None:
        self.rng = rng
        self.n = n_workers
        self.sim_time = 0.0
        # 2x row headroom: a crashed worker rejoins under a FRESH identity
        # (like a restarted process with a new ZMQ routing id), so its old
        # row stays allocated until the heartbeat timeout purges it
        self.arrays = SchedulerArrays(
            max_workers=n_workers * 2,
            max_pending=max_pending,
            max_inflight=n_workers * max_slots + max_pending,
            max_slots=max_slots,
            time_to_expire=time_to_expire,
            clock=lambda: self.sim_time,
        )
        self.speeds = (
            rng.uniform(0.5, 4.0, n_workers).astype(np.float32)
            if hetero
            else np.ones(n_workers, dtype=np.float32)
        )
        self.procs = np.full(n_workers, procs_per_worker, dtype=np.int32)
        self.alive = np.ones(n_workers, dtype=bool)
        # incarnation counter: bumped on every rejoin so the scheduler sees
        # a brand-new worker, never a resurrected row
        self.generation = np.zeros(n_workers, dtype=np.int64)
        # per worker: list of (finish_time, task_id)
        self.running: list[list[tuple[float, str]]] = [[] for _ in range(n_workers)]
        for w in range(n_workers):
            self.arrays.register(self._wid(w), procs_per_worker, float(self.speeds[w]))

    def _wid(self, w: int) -> bytes:
        return f"sim-{w}-g{int(self.generation[w])}".encode()

    def _row(self, w: int) -> int | None:
        return self.arrays.worker_ids.get(self._wid(w))

    def run(
        self,
        task_sizes: np.ndarray,
        dt: float = 0.5,
        churn: float = 0.0,
        max_ticks: int = 10_000,
    ) -> SimResult:
        """Feed `task_sizes` as the pending queue and tick until drained.

        churn: per-tick probability that a live worker crashes (losing its
        running tasks) and a dead one rejoins fresh.
        """
        a = self.arrays
        pending: list[tuple[str, float]] = [
            (f"task-{i}", float(s)) for i, s in enumerate(task_sizes)
        ]
        sizes = {tid: s for tid, s in pending}
        completed: set[str] = set()
        dispatched_at: dict[str, int] = {}
        ticks = 0
        tick_wall: list[float] = []

        while len(completed) < len(task_sizes) and ticks < max_ticks:
            ticks += 1
            self.sim_time += dt

            # -- churn: crashes lose running tasks; rejoins come back empty
            if churn > 0:
                flips = self.rng.random(self.n) < churn
                for w in np.flatnonzero(flips):
                    if self.alive[w]:
                        self.alive[w] = False  # silent crash: heartbeats stop
                        self.running[w].clear()
                    else:
                        # rejoin as a fresh process: new identity, new row;
                        # the old row dies by heartbeat timeout and its
                        # in-flight tasks are redistributed
                        self.alive[w] = True
                        self.generation[w] += 1
                        a.register(
                            self._wid(w),
                            int(self.procs[w]),
                            float(self.speeds[w]),
                        )

            # -- workers: finish tasks, heartbeat
            for w in range(self.n):
                if not self.alive[w]:
                    continue
                a.heartbeat(self._wid(w))
                still: list[tuple[float, str]] = []
                for finish, tid in self.running[w]:
                    if finish <= self.sim_time:
                        completed.add(tid)
                        row = a.inflight_done(tid)
                        if row is not None:
                            a.worker_free[row] = min(
                                a.worker_free[row] + 1, a.worker_procs[row]
                            )
                    else:
                        still.append((finish, tid))
                self.running[w] = still

            # -- scheduler tick over the pending window
            window = pending[: a.max_pending]
            batch_sizes = np.asarray([s for _, s in window], dtype=np.float32)
            t0 = time.perf_counter()
            out = a.tick(batch_sizes)
            tick_wall.append(time.perf_counter() - t0)

            # redistribution: reclaim tasks of purged workers
            for slot in np.flatnonzero(np.asarray(out.redispatch)):
                tid = a.inflight_clear_slot(int(slot))
                if tid is not None and tid not in completed:
                    pending.append((tid, sizes[tid]))
            for row in np.flatnonzero(np.asarray(out.purged)):
                a.deactivate(int(row))

            # dispatch assignments into the sim workers
            assignment = np.asarray(out.assignment)[: len(window)]
            dispatched_tids: set[str] = set()
            for i, row in enumerate(assignment):
                row = int(row)
                if row < 0 or row not in a.row_ids:
                    continue
                wid = a.row_ids[row]
                parts = wid.decode().split("-")
                w, gen = int(parts[1]), int(parts[2][1:])
                if not self.alive[w] or gen != self.generation[w]:
                    continue  # message to a dead incarnation is lost
                tid, size = window[i]
                duration = size / float(self.speeds[w])
                self.running[w].append((self.sim_time + duration, tid))
                a.inflight_add(tid, row)
                a.worker_free[row] -= 1
                dispatched_at[tid] = ticks
                dispatched_tids.add(tid)
            if dispatched_tids:
                pending = [p for p in pending if p[0] not in dispatched_tids]

        lost = len(task_sizes) - len(completed)
        return SimResult(
            completed=len(completed),
            lost=lost,
            makespan=self.sim_time,
            ticks=ticks,
            tick_seconds=tick_wall,
        )
