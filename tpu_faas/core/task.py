"""Task model: ids, lifecycle statuses, and the per-task record.

Lifecycle contract (reference SURVEY §0.1; status enum observed at reference
test_suit.py:19): QUEUED -> RUNNING -> COMPLETED | FAILED. Statuses are plain
strings on the wire and in the store.

Beyond the reference surface: QUEUED -> CANCELLED (terminal), written by the
gateway's POST /cancel/{task_id}. Cancellation is queued-only and
best-effort: a task already RUNNING keeps running (the gateway refuses with
409), and the rare cancel that loses its race against dispatch simply runs
to completion — the record then reads COMPLETED/FAILED, never a lie. See
store/base.py cancel_task for the protocol.
"""

from __future__ import annotations

import enum
import uuid
from dataclasses import dataclass, field


class TaskStatus(str, enum.Enum):
    QUEUED = "QUEUED"
    #: non-terminal "not yet dispatchable": a graph node whose parents have
    #: not all COMPLETED. Created by the gateway's POST /execute_graph for
    #: every node with a non-empty depends_on; the store's promotion plane
    #: (store/base.py complete_dep_many) flips it to QUEUED when the last
    #: parent completes (then it flows through intake/admission/shedding
    #: like any submit) or to FAILED when any parent reaches a
    #: FAILED/EXPIRED/CANCELLED terminal (the transitive frontier is
    #: poisoned, never dispatched). WAITING -> RUNNING is an ILLEGAL
    #: transition by protocol: no dispatcher may ever send a WAITING task
    #: to a worker.
    WAITING = "WAITING"
    RUNNING = "RUNNING"
    COMPLETED = "COMPLETED"
    FAILED = "FAILED"
    #: terminal "never ran, never will": queued-only cancellation
    CANCELLED = "CANCELLED"
    #: terminal "never ran, never will": the task's queue deadline
    #: (FIELD_DEADLINE, an optional client hint) lapsed while it was still
    #: QUEUED, and the dispatcher shed it instead of burning a worker slot
    #: on an answer nobody is waiting for. Written only by the dispatcher
    #: that owns the task's pending copy, via store.expire_task — the
    #: transition is legal from QUEUED alone (a RUNNING task always runs
    #: to completion; mid-run deadlines are the per-task `timeout` hint's
    #: job, enforced in the worker pool child).
    EXPIRED = "EXPIRED"

    def is_terminal(self) -> bool:
        return self in (
            TaskStatus.COMPLETED,
            TaskStatus.FAILED,
            TaskStatus.CANCELLED,
            TaskStatus.EXPIRED,
        )

    @classmethod
    def terminal_str(cls, status: str | None, *, unknown: bool = False) -> bool:
        """``is_terminal`` over a raw store/wire string. ``unknown`` is the
        answer for None or a foreign status string — callers pick their
        safe side (a result-freeze guard wants True: never overwrite what
        it can't parse; a drop/GC site wants False: leave it alone). The
        ValueError policy lives HERE so every consumer of raw status
        strings agrees on it."""
        if status is None:
            return unknown
        try:
            return cls(status).is_terminal()
        except ValueError:
            return unknown

    def __str__(self) -> str:  # plain string on the wire
        return self.value


#: Store hash field names, one hash per task (reference contract demonstrated
#: by old/client_debug.py:40-45 and read back at task_dispatcher.py:48-52).
FIELD_STATUS = "status"
FIELD_FN = "fn_payload"
FIELD_PARAMS = "param_payload"
FIELD_RESULT = "result"
#: Optional scheduling hints, written by the gateway only when the client
#: supplied them (the reference contract has no analog; absent fields keep
#: hand-rolled reference-style clients fully interoperable).
FIELD_PRIORITY = "priority"  # int as str; higher = admitted first
FIELD_COST = "cost"  # float as str; estimated run-cost (scheduler pairing)
FIELD_TIMEOUT = "timeout"  # float as str; execution budget enforced in-child
#: Optional queue deadline (ABSOLUTE epoch seconds as str), computed by the
#: gateway from the client's relative ``deadline`` submit-TTL hint. A task
#: still QUEUED past this instant is shed to the terminal EXPIRED status by
#: the dispatcher that holds it, instead of being dispatched. Absolute on
#: the wire (not the relative TTL) so the decision survives dispatcher
#: restarts and re-announces without re-deriving the submit time.
FIELD_DEADLINE = "deadline"
#: Speculative-execution opt-in ("1" when set; tpu_faas/spec): the client
#: declares this task safe to execute more than once (idempotent side
#: effects), so a dispatcher running with ``--speculate-mult`` may hedge a
#: straggling execution with a replica on a second worker — the store's
#: first-wins finish_task arbitrates, the loser is killed via the CANCEL
#: plane. Absent (every legacy producer) = never hedged.
FIELD_SPECULATIVE = "speculative"
#: Content address (sha256 hex, core/payload.py) of the task's serialized
#: function, written by a payload-plane gateway in place of an inline
#: FIELD_FN body: the bytes live ONCE under the store's ``blob:<digest>``
#: key and every consumer (dispatcher blob cache, worker payload cache)
#: resolves them by digest. A record carrying this field may carry an
#: EMPTY FIELD_FN; legacy records (and every record from a
#: reference-style producer) carry the inline body and no digest —
#: dispatch falls back per record, so the two populations mix freely on
#: one store.
FIELD_FN_DIGEST = "fn_digest"
#: Content address (sha256 hex) of the task's serialized RESULT — the
#: result-blob plane's mirror of FIELD_FN_DIGEST. Written by finish_task
#: when a ``--result-blobs`` dispatcher records a digest-form result: the
#: record's FIELD_RESULT may then be EMPTY, the bytes staying in the
#: producing worker's result cache (and, once anything needed them, under
#: the store's ``blob:<digest>`` key — lazy materialization,
#: store/base.py BLOBREQ_ANNOUNCE_PREFIX). Absent on every legacy record
#: and whenever the plane is off, so reference-style readers that only
#: know FIELD_RESULT keep their contract byte for byte.
FIELD_RESULT_DIGEST = "result_digest"
#: Byte length of the digest-form result body (int as str), written in the
#: same terminal write as FIELD_RESULT_DIGEST: readers and the placement
#: tick's parent-locality lane can reason about result SIZE without
#: materializing the bytes.
FIELD_RESULT_SIZE = "result_size"

#: Written by finish_task alongside every terminal write (epoch seconds as
#: str) — lets the gateway's optional result-TTL sweeper age out consumed
#: records without a per-task client DELETE.
FIELD_FINISHED_AT = "finished_at"
#: Redundant copies of the result's terminal status and finish time,
#: written by finish_task in the same hash write as FIELD_STATUS /
#: FIELD_FINISHED_AT. They exist for exactly one interleaving: a cancel
#: whose pre-write status read said QUEUED while a sub-millisecond task ran
#: to completion inside the read->write window would otherwise clobber the
#: landed COMPLETED/FAILED (and its finish stamp) forever — the primary
#: fields alone can't say what they were. cancel_task re-reads these after
#: its write and restores the record — see store/base.py cancel_task.
FIELD_FINAL_STATUS = "final_status"
FIELD_FINAL_AT = "final_finished_at"

#: Optional submit stamp (epoch seconds as str), written by the gateway in
#: the create-task hash write. Feeds the first event of the per-task
#: lifecycle timeline (tpu_faas/obs/trace.py): the dispatcher reads it at
#: intake so queue-wait and end-to-end latency are measurable from the
#: client's submit, not just from announce receipt. Absent on tasks from
#: hand-rolled reference-style producers — the timeline simply starts at
#: its first dispatcher-side event.
FIELD_SUBMITTED_AT = "submitted_at"

#: Distributed trace context (tpu_faas/obs/tracectx.py): the trace id this
#: task's cross-process spans are keyed by (lowercase hex, minted by the
#: SDK — or by a trace-enabled gateway for legacy clients), plus the
#: optional parent span id of the submitting client. Absent on tasks from
#: reference-style producers and on trace-disabled gateways — every
#: consumer treats absence as "tracing off for this task" and changes
#: nothing.
FIELD_TRACE_ID = "trace_id"
FIELD_TRACE_PARENT = "trace_parent"

#: Tenant identity (tpu_faas/tenancy): which principal this task is
#: accounted to by the weighted-fair placement plane. Written by the
#: gateway from the ``X-Tenant-Id`` request header (validated — it becomes
#: a metrics-label candidate and a share-table key); ABSENT on tasks from
#: legacy/reference-style producers, which every consumer reads as the
#: default tenant — so tenancy-oblivious clients share one fair-queued
#: bucket and the whole plane is invisible until two tenants actually
#: coexist. Rides RECLAIM_FIELDS: a reclaimed task keeps its accounting.
FIELD_TENANT = "tenant"

#: SLO class (tpu_faas/obs/attribution.py): which latency class this task
#: is judged under by the per-class tail accounting — one of the CLOSED
#: vocabulary (interactive/batch/default; it becomes a histogram label).
#: Written by the gateway ONLY when the client declared one (``X-SLO-Class``
#: header / SDK ``slo_class=``); ABSENT otherwise — consumers derive the
#: effective class from the priority sign, so the submit surface stays
#: byte-identical for clients that never declare and legacy records need
#: no migration. Off-vocabulary values degrade to ``default`` at read.
FIELD_SLO_CLASS = "slo_class"

#: Written (epoch seconds as str) with every RUNNING mark and refreshed
#: periodically by the dispatcher that owns the task's worker. A RUNNING
#: record whose lease has gone stale has no live owner left — its worker
#: AND its dispatcher died — and may be adopted by a stranded-task rescan
#: (the reference loses such tasks forever: its purge only deletes
#: bookkeeping, task_dispatcher.py:241-249, README:262-264).
FIELD_LEASE_AT = "lease_at"

#: How many times this task has been reclaimed from a dead worker (int as
#: str), stamped on every re-dispatch RUNNING mark. In-memory retry counts
#: die with their dispatcher — without this stamp, a task that keeps
#: killing worker+dispatcher together would reset its poison-guard counter
#: every dispatcher generation and cycle forever instead of FAILing.
FIELD_RECLAIMS = "reclaim_count"

#: Atomic dispatch-ownership claim for SHARED fleets (several dispatchers
#: on one store+channel — each receives every announce, and without a
#: claim each would dispatch every task). Value is
#: "<dispatcher_id>:<epoch seconds>"; exactly one of N concurrent
#: dispatchers wins the setnx and dispatches. Adoptions of an owner that
#: died re-arbitrate on generation-scoped fields (``claim_field_for``).
FIELD_DISPATCH_CLAIM = "dispatch_claim"


#: Task-graph dependency edges (tpu_faas/graph): comma-joined parent task
#: ids on a WAITING node, written once at graph create and never mutated.
#: The sweeper's orphan repair re-derives a stranded node's fate from
#: these; the tpu-push frontier builds its device edge list from them.
FIELD_DEPS = "deps"
#: Countdown of not-yet-COMPLETED parents (int as str) on a WAITING node.
#: Decremented ATOMICALLY (store hincrby) by the promotion plane, exactly
#: once per parent (each decrement is gated by a write-once per-edge claim
#: field "dep_done:<parent>", so a zombie's duplicate terminal write can't
#: double-count). Hitting zero triggers WAITING -> QUEUED.
FIELD_PENDING_DEPS = "pending_deps"
#: Comma-joined child task ids on any graph node that other nodes depend
#: on — the forward edges the promotion plane walks on the parent's
#: terminal write. Absent on non-graph tasks, so the flat hot path never
#: pays a dependency probe.
FIELD_CHILDREN = "dep_children"
#: Write-once resolution claim on a WAITING node ("promote" or
#: "poison:<parent_id>"): exactly one resolver — the promotion plane, the
#: poison walk, or the gateway sweeper's orphan repair — ever moves the
#: node out of WAITING, so promote/poison cannot race each other into an
#: illegal status interleaving. A claim whose writer died before the
#: status write is re-applied idempotently by the sweeper.
FIELD_DEP_RESOLVED = "dep_resolved"

#: Per-edge decrement claim field for parent ``parent_id`` on a child's
#: hash — see FIELD_PENDING_DEPS.
def dep_done_field(parent_id: str) -> str:
    return f"dep_done:{parent_id}"


#: Result-message prefix of a dep-poisoned node's FAILED payload: the
#: serialized exception reads "dep_failed:<parent_id>: <detail>", so SDKs
#: can raise TaskDependencyError with the failed parent attached without
#: any dill class-identity coupling.
DEP_FAILED_PREFIX = "dep_failed:"


def claim_field_for(generation: int) -> str:
    """The dispatch-claim hash field for reclaim generation ``generation``
    (0 = the initial announce-time claim). Each generation is a fresh
    write-once field, so N dispatchers racing to ADOPT the same orphaned
    task arbitrate with the same setnx primitive as the initial dispatch —
    exactly one wins generation g."""
    return (
        FIELD_DISPATCH_CLAIM
        if generation == 0
        else f"{FIELD_DISPATCH_CLAIM}:g{generation}"
    )


def new_task_id() -> str:
    return str(uuid.uuid4())


def new_function_id() -> str:
    return str(uuid.uuid4())


@dataclass
class Task:
    """In-memory view of one task's store hash."""

    task_id: str
    status: TaskStatus = TaskStatus.QUEUED
    fn_payload: str = ""
    param_payload: str = ""
    result: str = "None"
    #: Scheduler-side metadata (not part of the reference contract): an
    #: estimated execution cost used to build the tasks x workers cost matrix.
    cost_estimate: float = field(default=1.0, compare=False)

    def to_fields(self) -> dict[str, str]:
        return {
            FIELD_STATUS: str(self.status),
            FIELD_FN: self.fn_payload,
            FIELD_PARAMS: self.param_payload,
            FIELD_RESULT: self.result,
        }

    @classmethod
    def from_fields(cls, task_id: str, fields: dict[str, str]) -> "Task":
        return cls(
            task_id=task_id,
            status=TaskStatus(fields.get(FIELD_STATUS, "QUEUED")),
            fn_payload=fields.get(FIELD_FN, ""),
            param_payload=fields.get(FIELD_PARAMS, ""),
            result=fields.get(FIELD_RESULT, "None"),
        )
