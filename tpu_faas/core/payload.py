"""Content addressing for serialized payloads: digests + a bounded LRU.

The payload plane (OPERATIONS.md "Payload plane") ships *references* to hot
payload bytes instead of the bytes themselves: a serialized function is
written once under a ``blob:<sha256>`` store key, task records and dispatch
messages carry the digest, and every hop keeps a bounded cache keyed by it.
This module holds the two primitives every layer shares — the digest
function (sha256 over the ASCII payload, hex; collision-safe content
addressing, stable across producers/hosts/restarts) and a byte-bounded LRU
used by the dispatcher's blob cache and the workers' payload cache.

Distinct from :func:`tpu_faas.sched.estimator.fn_digest` (a short blake2b
IDENTITY key for runtime learning): blob digests address CONTENT the system
will re-materialize from, so they use the full sha256 — a collision there
would execute the wrong function.
"""

from __future__ import annotations

from collections import OrderedDict

import hashlib


def payload_digest(payload: str) -> str:
    """sha256 hex digest of a serialized (ASCII) payload — the blob key
    suffix and the ``fn_digest`` task/wire field."""
    return hashlib.sha256(payload.encode("ascii", "replace")).hexdigest()


#: Result-blob plane (``--result-blobs``): the default minimum result size
#: that ships as a digest instead of a body. Below this the digest (64 hex
#: chars) plus the bookkeeping costs more than the bytes it replaces; the
#: default tracks the express lane's inline bound (store/base.py
#: RESULT_INLINE_MAX_BYTES) so "small enough to inline" and "too small to
#: blob" agree out of the box.
RESULT_BLOB_MIN_BYTES = 4096


class PayloadLRU:
    """Bounded digest -> payload cache, evicting least-recently-used.

    Bounded by TOTAL PAYLOAD BYTES, not entry count: one cache must serve
    both a thousand tiny lambdas and a handful of multi-MB model closures
    without the operator retuning it. A single payload larger than the
    whole budget is still admitted alone (refusing it would disable the
    cache exactly for the payloads that are most expensive to re-fetch).
    Not thread-safe; every owner drives it from one loop."""

    __slots__ = ("max_bytes", "_items", "_bytes", "hits", "misses")

    def __init__(self, max_bytes: int = 256 * 1024 * 1024) -> None:
        self.max_bytes = int(max_bytes)
        self._items: OrderedDict[str, str] = OrderedDict()
        self._bytes = 0
        self.hits = 0
        self.misses = 0

    def get(self, digest: str) -> str | None:
        payload = self._items.get(digest)
        if payload is None:
            self.misses += 1
            return None
        self._items.move_to_end(digest)
        self.hits += 1
        return payload

    def put(self, digest: str, payload: str) -> None:
        old = self._items.pop(digest, None)
        if old is not None:
            self._bytes -= len(old)
        self._items[digest] = payload
        self._bytes += len(payload)
        while self._bytes > self.max_bytes and len(self._items) > 1:
            _, evicted = self._items.popitem(last=False)
            self._bytes -= len(evicted)

    def __contains__(self, digest: str) -> bool:
        return digest in self._items

    def __len__(self) -> int:
        return len(self._items)

    @property
    def n_bytes(self) -> int:
        return self._bytes
