"""Columnar task arena: the host data plane's struct-of-arrays backbone.

The dispatcher's hot path historically re-materialized every task as a
per-item Python object at every stage — store record dict -> PendingTask ->
per-field list comprehensions feeding the device tick's arrays. At
sub-millisecond task granularity the per-task constant cost of that churn
IS the throughput ceiling (BENCH_r07/r11: ~2.6k tasks/s per process while
the device tick is ~1 ms at 50k x 4k).

:class:`TaskColumns` keeps task metadata in preallocated numpy columns from
intake through the tick's act phase instead: fixed capacity, free-slot
recycling, id<->row interning, and vectorized gathers that hand the tick
zero-copy column slices (the tick already thinks in arrays — intake stops
converting array -> dict -> array). :class:`RowTask` is the per-task view:
it duck-types ``dispatch.base.PendingTask`` (same attribute surface, same
``task_message_kwargs``/``size_estimate`` semantics) so every downstream
consumer — pending queues, frame builders, estimators — works unchanged,
while the batch-wide loops read whole columns.

Lifecycle: ``intake_flat`` parses a store record (flat [field, value, ...]
lists, bytes or str — the shape ``hgetall_many_raw`` returns) straight into
a free row and hands back a RowTask; ``RowTask.release()`` detaches the
view (field values are snapshotted into a small shadow dict) and recycles
the row. Detach-on-release makes release idempotent and use-after-release
safe by construction: a released RowTask still answers every attribute from
its snapshot, it just no longer occupies arena capacity. A FULL arena makes
``intake_flat`` return None and the caller falls back to the plain
PendingTask path — overload degrades to the dict plane, never to an error.

Value parsing mirrors ``PendingTask.from_fields`` exactly (defensive
clamps included); tests/test_columns.py property-tests the equivalence.
"""

from __future__ import annotations

import math

import numpy as np

from tpu_faas.core.task import (
    FIELD_COST,
    FIELD_DEADLINE,
    FIELD_FN,
    FIELD_FN_DIGEST,
    FIELD_PARAMS,
    FIELD_PRIORITY,
    FIELD_SLO_CLASS,
    FIELD_SPECULATIVE,
    FIELD_SUBMITTED_AT,
    FIELD_TENANT,
    FIELD_TIMEOUT,
    FIELD_TRACE_ID,
)
from tpu_faas.obs.attribution import class_of

#: row lifecycle codes (the ``status`` column)
STATUS_FREE = 0
STATUS_PENDING = 1
STATUS_DISPATCHED = 2

#: priority clamp, same bound as PendingTask.from_fields (int32 batch
#: build with negation headroom)
_PRIO_CLAMP = 2**30


def _to_str(value) -> str:
    """Column values arrive as bytes on the binary-batch store path and
    str everywhere else; string-typed columns normalize here (payloads are
    the ASCII serialize contract, but utf-8 decoding is strictly more
    permissive and matches the str path byte for byte)."""
    return value.decode("utf-8") if isinstance(value, bytes) else value


def _positive_finite(raw) -> float:
    """``dispatch.base._parse_positive_finite`` over bytes-or-str, with
    nan standing in for None (the column encoding of 'no hint')."""
    if raw is None:
        return math.nan
    try:
        value = float(raw)
    except ValueError:
        return math.nan
    return value if math.isfinite(value) and value > 0.0 else math.nan


def _nan_none(value: float) -> float | None:
    return None if math.isnan(value) else float(value)


class TaskColumns:
    """Fixed-capacity struct-of-arrays task arena (module docstring)."""

    def __init__(self, capacity: int = 8192) -> None:
        cap = int(capacity)
        if cap <= 0:
            raise ValueError(f"arena capacity must be positive, got {cap}")
        self.capacity = cap
        # string-typed columns (object dtype: variable-length payloads)
        self.task_id = np.empty(cap, dtype=object)
        self.fn_payload = np.empty(cap, dtype=object)
        self.param_payload = np.empty(cap, dtype=object)
        self.fn_digest = np.empty(cap, dtype=object)
        self.trace_id = np.empty(cap, dtype=object)
        self.tenant = np.empty(cap, dtype=object)
        self.slo_class = np.empty(cap, dtype=object)
        # numeric columns (nan = absent on the optional-hint floats)
        self.status = np.zeros(cap, dtype=np.int8)
        self.priority = np.zeros(cap, dtype=np.int32)
        self.retries = np.zeros(cap, dtype=np.int32)
        self.speculative = np.zeros(cap, dtype=bool)
        self.cost = np.full(cap, np.nan, dtype=np.float64)
        self.timeout = np.full(cap, np.nan, dtype=np.float64)
        self.learned = np.full(cap, np.nan, dtype=np.float64)
        self.submitted_at = np.full(cap, np.nan, dtype=np.float64)
        self.deadline_at = np.full(cap, np.nan, dtype=np.float64)
        #: len(fn_payload) + len(param_payload), cached at intake so the
        #: size-estimate gather never touches the object columns
        self.payload_bytes = np.zeros(cap, dtype=np.int64)
        #: monotonic stamp of the moment the act loop sent the row's task
        #: (0 = never dispatched) — the profile/diagnostics dispatch stamp
        self.dispatched_at = np.zeros(cap, dtype=np.float64)
        #: id -> row interning (latest acquisition wins)
        self.rows: dict[str, int] = {}
        self._free: list[int] = list(range(cap - 1, -1, -1))

    # -- slot management ---------------------------------------------------
    @property
    def occupancy(self) -> int:
        return self.capacity - len(self._free)

    def row_of(self, task_id: str) -> int | None:
        return self.rows.get(task_id)

    def acquire(self, task_id: str) -> int | None:
        """Claim a free row for ``task_id`` (None when the arena is full —
        the caller's cue to fall back to the dict plane). The row comes
        back clean: every release scrubs its columns."""
        if not self._free:
            return None
        row = self._free.pop()
        self.task_id[row] = task_id
        self.status[row] = STATUS_PENDING
        self.rows[task_id] = row
        return row

    def release(self, row: int) -> None:
        """Recycle one row. Columns are scrubbed on the way out so object
        references (payload strings can be large) don't outlive the task,
        and the next acquire starts from defaults."""
        tid = self.task_id[row]
        if self.status[row] == STATUS_FREE:
            return  # already recycled (idempotence lives in RowTask.release)
        if tid is not None and self.rows.get(tid) == row:
            del self.rows[tid]
        self.task_id[row] = None
        self.fn_payload[row] = None
        self.param_payload[row] = None
        self.fn_digest[row] = None
        self.trace_id[row] = None
        self.tenant[row] = None
        self.slo_class[row] = None
        self.status[row] = STATUS_FREE
        self.priority[row] = 0
        self.retries[row] = 0
        self.speculative[row] = False
        self.cost[row] = np.nan
        self.timeout[row] = np.nan
        self.learned[row] = np.nan
        self.submitted_at[row] = np.nan
        self.deadline_at[row] = np.nan
        self.payload_bytes[row] = 0
        self.dispatched_at[row] = 0.0
        self._free.append(row)

    # -- intake ------------------------------------------------------------
    def intake_flat(self, task_id: str, flat: list) -> "RowTask | None":
        """Parse one store record — the flat ``[field, value, ...]`` list
        ``hgetall_many_raw`` returns, elements bytes or str — straight
        into a free row, no intermediate dict. Returns the attached
        RowTask, or None when the arena is full. Parsing semantics are
        PendingTask.from_fields verbatim: malformed hints degrade to
        defaults, priority clamps into int32 range, empty-string digests/
        trace ids/tenants read as absent."""
        row = self.acquire(task_id)
        if row is None:
            return None
        fn = params = ""
        for i in range(0, len(flat) - 1, 2):
            f, v = flat[i], flat[i + 1]
            if isinstance(f, bytes):
                f = f.decode("utf-8")
            if f == FIELD_FN:
                fn = _to_str(v)
            elif f == FIELD_PARAMS:
                params = _to_str(v)
            elif f == FIELD_PRIORITY:
                try:
                    p = int(v)
                except ValueError:
                    p = 0
                self.priority[row] = max(-_PRIO_CLAMP, min(_PRIO_CLAMP, p))
            elif f == FIELD_COST:
                self.cost[row] = _positive_finite(v)
            elif f == FIELD_TIMEOUT:
                self.timeout[row] = _positive_finite(v)
            elif f == FIELD_SUBMITTED_AT:
                self.submitted_at[row] = _positive_finite(v)
            elif f == FIELD_DEADLINE:
                self.deadline_at[row] = _positive_finite(v)
            elif f == FIELD_FN_DIGEST:
                self.fn_digest[row] = _to_str(v) or None
            elif f == FIELD_TRACE_ID:
                self.trace_id[row] = _to_str(v) or None
            elif f == FIELD_TENANT:
                self.tenant[row] = _to_str(v) or None
            elif f == FIELD_SLO_CLASS:
                self.slo_class[row] = _to_str(v) or None
            elif f == FIELD_SPECULATIVE:
                self.speculative[row] = v in ("1", b"1")
        self.fn_payload[row] = fn
        self.param_payload[row] = params
        self.payload_bytes[row] = len(fn) + len(params)
        return RowTask(self, row)

    # -- vectorized gathers (the tick's batch-build reads) ------------------
    def gather_sizes(self, rows: np.ndarray) -> np.ndarray:
        """f32 size estimates for many rows in three vector ops — the
        column form of ``PendingTask.size_estimate``'s trust order:
        explicit cost hint, else learned estimate, else payload bytes."""
        cost = self.cost[rows]
        learned = self.learned[rows]
        fallback = np.where(
            np.isnan(learned), self.payload_bytes[rows].astype(np.float64),
            learned,
        )
        return np.where(np.isnan(cost), fallback, cost).astype(np.float32)

    def gather_priorities(self, rows: np.ndarray) -> np.ndarray:
        return self.priority[rows]

    def gather_deadlines(self, rows: np.ndarray) -> np.ndarray:
        """f64 absolute deadlines, nan = none."""
        return self.deadline_at[rows]

    def stamp_dispatched(self, row: int, now: float) -> None:
        self.dispatched_at[row] = now
        self.status[row] = STATUS_DISPATCHED


def _obj_prop(col: str, default=None):
    def get(self):
        sh = self._shadow
        if sh is not None:
            return sh[col]
        v = getattr(self._arena, col)[self._row]
        return default if v is None else v

    def set(self, value):
        sh = self._shadow
        if sh is not None:
            sh[col] = value
        else:
            getattr(self._arena, col)[self._row] = value

    return property(get, set)


def _optfloat_prop(col: str):
    def get(self):
        sh = self._shadow
        if sh is not None:
            return sh[col]
        return _nan_none(getattr(self._arena, col)[self._row])

    def set(self, value):
        sh = self._shadow
        if sh is not None:
            sh[col] = value
        else:
            getattr(self._arena, col)[self._row] = (
                math.nan if value is None else float(value)
            )

    return property(get, set)


def _int_prop(col: str):
    def get(self):
        sh = self._shadow
        if sh is not None:
            return sh[col]
        return int(getattr(self._arena, col)[self._row])

    def set(self, value):
        sh = self._shadow
        if sh is not None:
            sh[col] = value
        else:
            getattr(self._arena, col)[self._row] = value

    return property(get, set)


def _bool_prop(col: str):
    def get(self):
        sh = self._shadow
        if sh is not None:
            return sh[col]
        return bool(getattr(self._arena, col)[self._row])

    def set(self, value):
        sh = self._shadow
        if sh is not None:
            sh[col] = value
        else:
            getattr(self._arena, col)[self._row] = bool(value)

    return property(get, set)


class RowTask:
    """Arena-backed task view, duck-typing ``dispatch.base.PendingTask``.

    While attached, every attribute reads/writes its arena column — there
    is no per-task field storage at all. ``release()`` detaches: the field
    values are snapshotted into a small shadow dict and the row recycles,
    after which the view keeps answering (and absorbing) every attribute
    from the snapshot. Double release is a no-op; a leaked (never
    released) view merely occupies a row until the arena fills and intake
    falls back to the dict plane — observable on the occupancy gauge,
    never a correctness failure.
    """

    __slots__ = ("_arena", "_row", "_shadow", "task_id", "is_hedge", "avoid_row")

    def __init__(self, arena: TaskColumns, row: int) -> None:
        self._arena = arena
        self._row = row
        self._shadow: dict | None = None
        # the id is immutable for the life of the task and by far the
        # most-read field (traces, inflight bookkeeping, claim maps read
        # it several times per dispatch) — a plain slot, not a column
        # property, so those reads cost what a PendingTask attribute does
        self.task_id = arena.task_id[row]
        # hedge replicas are host-constructed PendingTasks, never arena
        # rows; these exist so generic pending-task consumers can read them
        self.is_hedge = False
        self.avoid_row = -1

    fn_payload = _obj_prop("fn_payload", default="")
    param_payload = _obj_prop("param_payload", default="")
    fn_digest = _obj_prop("fn_digest")
    trace_id = _obj_prop("trace_id")
    tenant = _obj_prop("tenant")
    slo_class = _obj_prop("slo_class")
    priority = _int_prop("priority")
    retries = _int_prop("retries")
    speculative = _bool_prop("speculative")
    cost = _optfloat_prop("cost")
    timeout = _optfloat_prop("timeout")
    learned = _optfloat_prop("learned")
    submitted_at = _optfloat_prop("submitted_at")
    deadline_at = _optfloat_prop("deadline_at")

    @property
    def row(self) -> int | None:
        """Arena row while attached, None once released."""
        return None if self._shadow is not None else self._row

    @property
    def attached(self) -> bool:
        return self._shadow is None

    @property
    def effective_class(self) -> str:
        """PendingTask.effective_class verbatim: declared class wins,
        else the priority sign decides."""
        return class_of(self.slo_class, self.priority)

    @property
    def size_estimate(self) -> float:
        """PendingTask.size_estimate's trust order, column-backed."""
        if self._shadow is None:
            a, r = self._arena, self._row
            c = a.cost[r]
            if not math.isnan(c):
                return float(c)
            l = a.learned[r]
            if not math.isnan(l):
                return float(l)
            return float(a.payload_bytes[r])
        sh = self._shadow
        if sh["cost"] is not None:
            return sh["cost"]
        if sh["learned"] is not None:
            return sh["learned"]
        return float(len(sh["fn_payload"]) + len(sh["param_payload"]))

    def task_message_kwargs(self, blob: bool = False, trace: bool = False) -> dict:
        """PendingTask.task_message_kwargs verbatim — the ONE place the
        columnar plane materializes a per-task dict, because this dict IS
        the legacy-worker wire contract. Attached views read their columns
        directly (this runs once per dispatched task; six property hops
        here were a measurable slice of the serve loop)."""
        sh = self._shadow
        if sh is None:
            a, r = self._arena, self._row
            fn_digest = a.fn_digest[r]
            fn_payload = a.fn_payload[r]
            param_payload = a.param_payload[r]
            timeout = a.timeout[r]
            trace_id = a.trace_id[r]
        else:
            fn_digest = sh["fn_digest"]
            fn_payload = sh["fn_payload"]
            param_payload = sh["param_payload"]
            timeout = sh["timeout"]
            trace_id = sh["trace_id"]
        out = {  # faas: allow(eventloop.hot-loop-dict-churn) the TASK frame's wire payload: this dict IS the worker message contract, materialized once per dispatch at the legacy boundary
            "task_id": self.task_id,
            "param_payload": "" if param_payload is None else param_payload,
        }
        if blob and fn_digest:
            out["fn_digest"] = fn_digest
        else:
            out["fn_payload"] = "" if fn_payload is None else fn_payload
            if fn_digest:
                out["fn_digest"] = fn_digest
        if timeout is not None and not (
            isinstance(timeout, float) and math.isnan(timeout)
        ):
            out["timeout"] = float(timeout)
        if trace and trace_id:
            out["trace_id"] = trace_id
        return out

    def release(self) -> None:
        """Detach from the arena and recycle the row (idempotent). The
        snapshot keeps the view fully functional afterwards — parked or
        re-queued copies of a task that already left the arena behave
        exactly like plain PendingTasks."""
        if self._shadow is not None:
            return
        a, r = self._arena, self._row
        self._shadow = {
            "fn_payload": a.fn_payload[r] or "",
            "param_payload": a.param_payload[r] or "",
            "fn_digest": a.fn_digest[r],
            "trace_id": a.trace_id[r],
            "tenant": a.tenant[r],
            "slo_class": a.slo_class[r],
            "priority": int(a.priority[r]),
            "retries": int(a.retries[r]),
            "speculative": bool(a.speculative[r]),
            "cost": _nan_none(a.cost[r]),
            "timeout": _nan_none(a.timeout[r]),
            "learned": _nan_none(a.learned[r]),
            "submitted_at": _nan_none(a.submitted_at[r]),
            "deadline_at": _nan_none(a.deadline_at[r]),
        }
        a.release(r)

    #: post-discard field values: a discarded view answers defaults, not
    #: its last column state (see discard)
    _DISCARD_SHADOW = {
        "fn_payload": "",
        "param_payload": "",
        "fn_digest": None,
        "trace_id": None,
        "tenant": None,
        "slo_class": None,
        "priority": 0,
        "retries": 0,
        "speculative": False,
        "cost": None,
        "timeout": None,
        "learned": None,
        "submitted_at": None,
        "deadline_at": None,
    }

    def discard(self) -> None:
        """Detach WITHOUT the field snapshot — for views whose fate is
        sealed (the task is on the wire and a reclaim rebuilds from the
        store record, never from this object). The row recycles exactly
        as in :meth:`release`, but the 14-field snapshot — measurable at
        dispatch rates — is replaced by a template copy: ``task_id``
        survives (it is a slot), every other field reads as its default.
        Idempotent, and interchangeable with release() for double-detach
        (whichever runs first wins)."""
        if self._shadow is not None:
            return
        self._shadow = dict(self._DISCARD_SHADOW)
        self._arena.release(self._row)

    def __repr__(self) -> str:  # pragma: no cover - debugging aid
        state = "detached" if self._shadow is not None else f"row={self._row}"
        return f"<RowTask {self.task_id!r} {state}>"
