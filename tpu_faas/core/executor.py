"""The function executor: deserialize, call, catch everything, reserialize.

Capability contract (reference helper_functions.py:11-28):

- params decode to a pair ``(args_tuple, kwargs_dict)`` and the call is
  ``fn(*args, **kwargs)``;
- ANY exception — raised while deserializing the function, deserializing the
  params, or running the function — yields status FAILED with the serialized
  exception as the result; success yields COMPLETED with the serialized
  return value;
- the return triple ``(task_id, status, ser_result)`` is what worker pools
  hand back to their drain loops.

This function is the unit every execution backend shares: the local
dispatcher pool, pull workers, and push workers all ``apply_async`` it
(reference task_dispatcher.py:83-86, pull_worker.py:63-72, push_worker.py:117-123).
"""

from __future__ import annotations

from typing import NamedTuple

from tpu_faas.core.serialize import deserialize, serialize
from tpu_faas.core.task import TaskStatus


class ExecutionResult(NamedTuple):
    task_id: str
    status: str  # plain string: "COMPLETED" | "FAILED" (wire/store form)
    result: str  # serialized payload (value or exception)


def execute_fn(task_id: str, ser_fn: str, ser_params: str) -> ExecutionResult:
    """Execute one task; never raises.

    Runs in worker pool child processes — keep it dependency-light and make
    sure every outcome is expressible as a serializable (status, result) pair.
    """
    try:
        fn = deserialize(ser_fn)
        params = deserialize(ser_params)
        args, kwargs = params  # contract: (args_tuple, kwargs_dict)
        result = fn(*args, **kwargs)
        return ExecutionResult(task_id, str(TaskStatus.COMPLETED), serialize(result))
    except Exception as exc:  # catch-all FAILED semantics
        try:
            payload = serialize(exc)
            deserialize(payload)  # exception must round-trip for the client
        except Exception:
            # exception not round-trippable (holds a lock/socket, or is a
            # class the consumer can't reconstruct): degrade to its repr
            # rather than hand the client an unloadable payload
            payload = serialize(RuntimeError(repr(exc)))
        return ExecutionResult(task_id, str(TaskStatus.FAILED), payload)


def pack_params(*args: object, **kwargs: object) -> str:
    """Serialize a call's params in the wire format ``(args_tuple, kwargs_dict)``."""
    return serialize((args, kwargs))
