"""The function executor: deserialize, call, catch everything, reserialize.

Capability contract (reference helper_functions.py:11-28):

- params decode to a pair ``(args_tuple, kwargs_dict)`` and the call is
  ``fn(*args, **kwargs)``;
- ANY exception — raised while deserializing the function, deserializing the
  params, or running the function — yields status FAILED with the serialized
  exception as the result; success yields COMPLETED with the serialized
  return value;
- the return triple ``(task_id, status, ser_result)`` is what worker pools
  hand back to their drain loops.

This function is the unit every execution backend shares: the local
dispatcher pool, pull workers, and push workers all ``apply_async`` it
(reference task_dispatcher.py:83-86, pull_worker.py:63-72, push_worker.py:117-123).
"""

from __future__ import annotations

import signal
import threading
from collections import OrderedDict
from typing import NamedTuple

from tpu_faas.core.serialize import deserialize, serialize
from tpu_faas.core.task import TaskStatus

#: Child-side cache of DESERIALIZED functions keyed by content digest
#: (core/payload.py sha256, carried on TASK messages as ``fn_digest``).
#: Lives in the pool child's module globals — each forkserver child keeps
#: its own — so steady-state execution of a repeated function pays ZERO
#: dill decode: the decode cost moves from per-task to per-(child,
#: function). Entry-bounded, not byte-bounded: the cached values are live
#: Python callables whose footprint dill can't meaningfully size.
_FN_CACHE_CAP = 64
_FN_CACHE: OrderedDict[str, object] = OrderedDict()


def _cached_fn(ser_fn: str, fn_digest: str | None):
    """Deserialize ``ser_fn``, through the digest-keyed cache when the
    caller supplied a digest. Trusting the digest (not re-hashing) is
    deliberate: it came from the same content-addressed plane that
    produced the payload, and hashing per task would give back a third of
    the decode saving."""
    if fn_digest is None:
        return deserialize(ser_fn)
    fn = _FN_CACHE.get(fn_digest)
    if fn is None:
        fn = deserialize(ser_fn)
        _FN_CACHE[fn_digest] = fn
        while len(_FN_CACHE) > _FN_CACHE_CAP:
            _FN_CACHE.popitem(last=False)
    else:
        _FN_CACHE.move_to_end(fn_digest)
    return fn


#: Child-side parent-result delivery (result-blob plane): while a graph
#: child executes, the serialized results of its confirmed parents —
#: shipped on the TASK frame as digests or bodies and resolved through
#: the worker's result cache — sit here. Plain module global: a pool
#: child executes one task at a time, and execute_fn scopes it to the
#: call. None everywhere the plane is off, so flat tasks and legacy
#: deployments never see it.
_DEP_RESULTS: dict[str, str] | None = None


def dep_results() -> dict[str, str]:
    """The executing graph child's parent results, parent task id ->
    SERIALIZED body; {} for flat tasks and delivery-off deployments.
    Functions opt in by calling this — graph edges stay ordering-only
    (examples/task_graphs.py) for everyone else."""
    return dict(_DEP_RESULTS) if _DEP_RESULTS else {}


def dep_values() -> dict[str, object]:
    """:func:`dep_results` with every body deserialized — the convenient
    form for fan-in consumers (``sum(dep_values().values())``-style)."""
    return {pid: deserialize(body) for pid, body in dep_results().items()}


class ExecutionResult(NamedTuple):
    task_id: str
    #: plain string, wire/store form: "COMPLETED" | "FAILED" | "CANCELLED"
    #: (the last only from a force-cancel interrupt, worker/pool.py)
    status: str
    result: str  # serialized payload (value or exception)
    #: wall seconds the execution took IN THE POOL CHILD (deserialize +
    #: call + serialize), measured at the source so it carries no pool
    #: queueing or transport time; rides the RESULT message as `elapsed`
    #: and feeds the dispatcher's runtime estimator (sched/estimator.py).
    #: None on paths that never executed (cancelled futures, broken pools).
    elapsed: float | None = None
    #: epoch seconds when the child began executing; rides the RESULT
    #: message as `started_at` so the dispatcher's task timeline
    #: (tpu_faas/obs/trace.py) gets exec_start/exec_end events measured at
    #: the source. `started_at + elapsed` is the exec-end stamp. None on
    #: paths that never executed.
    started_at: float | None = None


class TaskTimeout(BaseException):
    """Raised inside a pool child when a task exceeds its time budget.

    Deliberately a BaseException: runaway tasks are very often shaped like
    ``while True: try: work() except Exception: continue`` — an
    Exception-derived timeout would be swallowed by that loop (and the
    one-shot itimer never fires again), silently re-creating the wedged
    slot the feature exists to prevent. User code that catches
    BaseException defeats this, like it defeats KeyboardInterrupt; that
    residual case is the operator-kill path.
    """


class TaskCancelledInterrupt(BaseException):
    """Raised inside a pool child when a FORCE cancel interrupts the task
    mid-run (worker/pool.py's SIGUSR1 handler — the externally-triggered
    sibling of the SIGALRM timeout above, same BaseException rationale).
    Surfaces as a terminal CANCELLED result, not FAILED: the caller asked
    for exactly this outcome."""


#: Arm-time cap (~194 days): setitimer raises OverflowError far above this
#: (platform time_t), and no task budget is legitimately this long.
_MAX_TIMEOUT_S = float(2**24)


def execute_fn(
    task_id: str,
    ser_fn: str,
    ser_params: str,
    timeout: float | None = None,
    fn_digest: str | None = None,
    dep_results: dict[str, str] | None = None,
) -> ExecutionResult:
    """Execute one task; never raises.

    Runs in worker pool child processes — keep it dependency-light and make
    sure every outcome is expressible as a serializable (status, result) pair.

    ``timeout`` (seconds, client's ``timeout`` hint) bounds the call with a
    SIGALRM-based interrupt in the child: a runaway pure-Python task raises
    :class:`TaskTimeout` -> FAILED and RELEASES its process slot (without
    this, one infinite loop permanently eats a slot — a capacity leak the
    dispatcher's poison guard can't see, since the worker stays alive and
    heartbeating). Limitations, by design: POSIX main-thread only (elsewhere
    it degrades to no enforcement), and C-extension code that never yields
    to the interpreter can't be interrupted — that residual case needs an
    operator killing the worker (purge + re-dispatch then recover the task).
    """
    import time

    global _DEP_RESULTS
    t0_wall = time.time()
    t0 = time.perf_counter()
    _DEP_RESULTS = dep_results
    try:
        res = _execute_guarded(task_id, ser_fn, ser_params, timeout, fn_digest)
    except TaskTimeout as exc:
        # the alarm landed in the narrow window between an exception being
        # caught and the timer disarm: still a clean FAILED, never a raise
        res = ExecutionResult(task_id, str(TaskStatus.FAILED), serialize(exc))
    except TaskCancelledInterrupt as exc:
        # same narrow window for a force cancel's interrupt — and unlike a
        # fired (one-shot, self-disarming) alarm, the itimer may still be
        # ARMED here (the interrupt escaped between an exception being
        # caught and _execute_guarded's disarm): a stale alarm firing into
        # the child's NEXT task would fail it with the old task's budget
        if hasattr(signal, "setitimer"):
            try:
                signal.setitimer(signal.ITIMER_REAL, 0)
            except Exception:
                pass
        res = ExecutionResult(
            task_id, str(TaskStatus.CANCELLED), serialize(exc)
        )
    finally:
        # scope the delivery to this call: a later plane-off task in the
        # same child must see {} from dep_results(), not stale parents
        _DEP_RESULTS = None
    return res._replace(
        elapsed=time.perf_counter() - t0, started_at=t0_wall
    )


def _execute_guarded(
    task_id: str,
    ser_fn: str,
    ser_params: str,
    timeout: float | None,
    fn_digest: str | None = None,
) -> ExecutionResult:
    timer_armed = False
    try:
        # arming INSIDE the try: setitimer itself can raise (OverflowError
        # on absurd values — additionally clamped here), and a tiny budget's
        # alarm may fire before the user code even starts; both must follow
        # the normal FAILED path, not escape
        if timeout is not None and timeout > 0:
            if threading.current_thread() is threading.main_thread() and hasattr(
                signal, "setitimer"
            ):
                def _alarm(signum, frame):
                    raise TaskTimeout(
                        f"task {task_id} exceeded its {timeout}s time budget"
                    )

                signal.signal(signal.SIGALRM, _alarm)
                signal.setitimer(
                    signal.ITIMER_REAL, min(timeout, _MAX_TIMEOUT_S)
                )
                timer_armed = True
        fn = _cached_fn(ser_fn, fn_digest)
        params = deserialize(ser_params)
        args, kwargs = params  # contract: (args_tuple, kwargs_dict)
        result = fn(*args, **kwargs)
        # disarm BEFORE serializing: a late alarm firing inside the success
        # path would turn a finished task into a spurious FAILED
        if timer_armed:
            signal.setitimer(signal.ITIMER_REAL, 0)
            timer_armed = False
        return ExecutionResult(task_id, str(TaskStatus.COMPLETED), serialize(result))
    except TaskCancelledInterrupt as exc:
        # a force cancel interrupted the call: terminal CANCELLED, slot
        # freed — the one non-FAILED exceptional outcome (the caller asked
        # for exactly this)
        if timer_armed:
            signal.setitimer(signal.ITIMER_REAL, 0)
        return ExecutionResult(
            task_id, str(TaskStatus.CANCELLED), serialize(exc)
        )
    except (Exception, TaskTimeout) as exc:  # catch-all FAILED semantics
        if timer_armed:
            signal.setitimer(signal.ITIMER_REAL, 0)
        try:
            payload = serialize(exc)
            deserialize(payload)  # exception must round-trip for the client
        except Exception:
            # exception not round-trippable (holds a lock/socket, or is a
            # class the consumer can't reconstruct): degrade to its repr
            # rather than hand the client an unloadable payload
            payload = serialize(RuntimeError(repr(exc)))
        return ExecutionResult(task_id, str(TaskStatus.FAILED), payload)


def pack_params(*args: object, **kwargs: object) -> str:
    """Serialize a call's params in the wire format ``(args_tuple, kwargs_dict)``."""
    return serialize((args, kwargs))
