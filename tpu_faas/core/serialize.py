"""Object <-> string serialization for functions, params, results, and messages.

Capability contract (reference helper_functions.py:5-9): any Python object is
dill-pickled and base64-encoded into a plain ASCII string; the inverse decodes.
Everything on the wire — registered functions, call params, results, and every
ZMQ message body — travels as such strings. A deliberate consequence (reference
SURVEY §3.3): because payloads cross multiprocessing pipes as *strings*, lambdas
and closures survive the pool boundary even though the stdlib pickler used by
multiprocessing cannot pickle them directly.
"""

from __future__ import annotations

import base64
import pickle

import dill

#: leaf types the wire-envelope fast path accepts. Deliberately closed:
#: anything else (functions, arbitrary objects) must keep dill's
#: by-VALUE pickling — C-pickle would "succeed" on a module-level
#: function by REFERENCE, silently breaking the lambdas-survive-the-wire
#: capability contract above.
_WIRE_PRIMITIVES = (str, bytes, int, float, bool, type(None))


def _wire_safe(obj: object) -> bool:
    if isinstance(obj, _WIRE_PRIMITIVES):
        return True
    if isinstance(obj, (list, tuple)):
        return all(_wire_safe(x) for x in obj)
    if isinstance(obj, dict):
        return all(
            isinstance(k, _WIRE_PRIMITIVES) and _wire_safe(v)
            for k, v in obj.items()
        )
    return False


def dumps_wire(obj: object) -> bytes:
    """Pickle bytes for WIRE ENVELOPES ({type, data} message dicts whose
    payload leaves are already-serialized strings): the stdlib C pickler
    when every leaf is a primitive — two orders of magnitude faster than
    dill, which pins the pure-Python pickler — and dill for anything
    else. Either way the output is a standard pickle stream, so
    ``dill.loads`` (every decoder in the fleet, reference-era workers
    included) reads both identically. Profiled at the config-9 bench
    shape, per-frame dill encode was the single largest host cost of the
    serve loop; this fast path removes it without touching the contract.

    The primitive walk costs a few microseconds against the ~200us dill
    encode it replaces; the closed type set (see _WIRE_PRIMITIVES) is
    what keeps function payloads on dill's by-value semantics."""
    if _wire_safe(obj):
        return pickle.dumps(obj, protocol=4)
    return dill.dumps(obj, recurse=True)


def serialize(obj: object) -> str:
    """Serialize any Python object to an ASCII-safe string (dill -> base64)."""
    return base64.b64encode(dill.dumps(obj, recurse=True)).decode("ascii")


def serialize_wire(obj: object) -> str:
    """ASCII form of :func:`dumps_wire` — same base64 envelope as
    :func:`serialize`, decoded by the same :func:`deserialize`."""
    return base64.b64encode(dumps_wire(obj)).decode("ascii")


def deserialize(payload: str) -> object:
    """Inverse of :func:`serialize`.

    Raises whatever dill/base64 raise on malformed input; callers that need
    the catch-all FAILED semantics wrap this (see core.executor.execute_fn).
    """
    return dill.loads(base64.b64decode(payload.encode("ascii")))
