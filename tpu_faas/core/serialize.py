"""Object <-> string serialization for functions, params, results, and messages.

Capability contract (reference helper_functions.py:5-9): any Python object is
dill-pickled and base64-encoded into a plain ASCII string; the inverse decodes.
Everything on the wire — registered functions, call params, results, and every
ZMQ message body — travels as such strings. A deliberate consequence (reference
SURVEY §3.3): because payloads cross multiprocessing pipes as *strings*, lambdas
and closures survive the pool boundary even though the stdlib pickler used by
multiprocessing cannot pickle them directly.
"""

from __future__ import annotations

import base64

import dill


def serialize(obj: object) -> str:
    """Serialize any Python object to an ASCII-safe string (dill -> base64)."""
    return base64.b64encode(dill.dumps(obj, recurse=True)).decode("ascii")


def deserialize(payload: str) -> object:
    """Inverse of :func:`serialize`.

    Raises whatever dill/base64 raise on malformed input; callers that need
    the catch-all FAILED semantics wrap this (see core.executor.execute_fn).
    """
    return dill.loads(base64.b64decode(payload.encode("ascii")))
