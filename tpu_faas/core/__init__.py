"""Execution core: serialization, task model, and the function executor.

Equivalent capability surface to the reference's helper_functions.py
(serialize/deserialize/execute_fn, reference helper_functions.py:5-28).
"""

from tpu_faas.core.serialize import serialize, deserialize
from tpu_faas.core.task import TaskStatus, Task, new_task_id
from tpu_faas.core.executor import execute_fn, ExecutionResult

__all__ = [
    "serialize",
    "deserialize",
    "TaskStatus",
    "Task",
    "new_task_id",
    "execute_fn",
    "ExecutionResult",
]
