"""Push worker: DEALER socket + local process pool.

Capability parity with reference PushWorker (push_worker.py:10-140): register
with ``num_processes`` (the dispatcher does admission control — the worker
never refuses a task, reference README:231), execute whatever arrives, ship
results as they finish. With ``--hb``: send a heartbeat every
``heartbeat_period`` seconds and answer the dispatcher's ``reconnect``
request with the current free-process count (reference push_worker.py:76-82).

Reference bugs fixed, not copied (SURVEY §7.5): the heartbeat timestamp is
actually updated after sending (the reference never updates
``last_sent_heartbeat`` so it spams one per loop iteration,
push_worker.py:61-62), and registration happens exactly once
(the reference's start_heartbeat registers twice, :47+53).

CLI: ``python -m tpu_faas.worker.push_worker N tcp://host:port [--hb]``
(reference push_worker.py:143-166).
"""

from __future__ import annotations

import argparse
import time
import uuid

import zmq

from tpu_faas.core.payload import PayloadLRU, payload_digest
from tpu_faas.core.serialize import serialize
from tpu_faas.core.task import TaskStatus
from tpu_faas.utils.logging import get_logger, log_ctx
from tpu_faas.worker import messages as m
from tpu_faas.worker.pool import (
    FN_CACHE_HITS,
    FN_CACHE_MISSES,
    RESULT_CACHE_HITS,
    RESULT_CACHE_MISSES,
    TaskPool,
)

log = get_logger("push_worker")

#: How long a parked task waits on an unanswered BLOB_MISS before the
#: worker re-asks (fills ride the same lossy transport as everything else).
_MISS_RESEND_S = 2.0


class PushWorker:
    def __init__(
        self,
        num_processes: int,
        dispatcher_url: str,
        heartbeat: bool = False,
        heartbeat_period: float = 1.0,
        poll_timeout_ms: int = 10,
        token: str | None = None,
        caps: tuple[str, ...] = m.WORKER_CAPS,
        fn_cache_bytes: int = 256 * 1024 * 1024,
        result_cache_bytes: int = 256 * 1024 * 1024,
    ) -> None:
        self.num_processes = num_processes
        #: stable identity for the estimator's speed grades: carried on
        #: REGISTER and RECONNECT so the grade survives socket churn and
        #: dispatcher restarts; a supervisor (worker/deploy.py) passes a
        #: slot-stable token so even a crash-respawned worker keeps the
        #: machine's grade. A self-minted uuid default is flagged EPHEMERAL
        #: on the wire: it will never be presented again after this
        #: process dies, so the dispatcher grades it in memory only (no
        #: store persistence, forgotten on purge) — otherwise every ad-hoc
        #: restart leaks one WORKER_STATS_KEY entry forever
        self.token_is_ephemeral = token is None
        self.token = token or uuid.uuid4().hex
        self.heartbeat = heartbeat
        self.heartbeat_period = heartbeat_period
        self.poll_timeout_ms = poll_timeout_ms
        #: protocol capabilities advertised on REGISTER/RECONNECT (payload
        #: plane); () runs the pure reference contract — used by tests and
        #: as an operator escape hatch
        self.caps: tuple[str, ...] = tuple(caps)
        #: digest -> serialized body: the parent-side half of the codec
        #: cache (the child-side half caches DESERIALIZED functions,
        #: core/executor.py). Filled by BLOB_FILLs and by inline payloads
        #: seen with a digest attached.
        self.fn_cache = PayloadLRU(fn_cache_bytes)
        #: digest -> serialized RESULT body (result-blob plane): filled by
        #: this worker's own completed results that shipped digest-only,
        #: and by BLOB_FILLs answering a dep-digest miss. The dispatcher's
        #: locality lane steers graph children here, and dispatcher->worker
        #: BLOB_MISS pulls materialize store copies from it on demand.
        self.result_cache = PayloadLRU(result_cache_bytes)
        #: task_id -> rblob_min carried on that task's TASK frame: the
        #: dispatcher's per-task proof + threshold that ITS completed
        #: result may ship digest-only (set only for graph-consumed tasks)
        self._task_rblob: dict[str, int] = {}
        #: digest -> which cache a BLOB_FILL for it belongs to ("result"
        #: for dep-digest misses; absent = "fn", the historical default)
        self._miss_kind: dict[str, str] = {}
        #: task_id -> distributed trace id (TASK ``trace_id``, present only
        #: when this worker advertised CAP_TRACE to a tracing dispatcher):
        #: stamped into logs and echoed on the matching RESULT; entries
        #: live exactly as long as the task is held here
        self._task_trace: dict[str, str] = {}
        #: digest -> TASK payload dicts parked on an outstanding miss
        self._awaiting: dict[str, list[dict]] = {}
        #: digest -> monotonic time the last BLOB_MISS went out
        self._miss_sent: dict[str, float] = {}
        #: True once a binary frame arrived from the dispatcher — proof it
        #: decodes them; our own sends switch to binary from then on
        self._peer_bin = False
        #: True once a TASK_BATCH frame arrived — proof the dispatcher
        #: speaks the batched data plane; the result drain then coalesces
        #: multi-result shipments into RESULT_BATCH frames (same
        #: asymmetric negotiation as binary framing: advertising CAP_BATCH
        #: alone never changes this worker's sends)
        self._peer_batch = False
        self.pool = TaskPool(num_processes)
        self.ctx = zmq.Context.instance()
        self.socket = self.ctx.socket(zmq.DEALER)
        self.socket.setsockopt(zmq.LINGER, 0)
        self.socket.connect(dispatcher_url)
        self.poller = zmq.Poller()
        self.poller.register(self.socket, zmq.POLLIN)
        # pool-completion wakeup: a finished task pokes this fd, so the
        # serving loop drains + ships results the moment they land instead
        # of waiting out poll_timeout — the worker-side analog of the
        # dispatcher's event-driven (express) intake
        self.poller.register(self.pool.wakeup_fd, zmq.POLLIN)
        self._stopping = False
        self._draining = False
        #: fault-injection seams (tpu_faas/chaos), None when
        #: TPU_FAAS_CHAOS is unset: the wire seam wraps _send (drop/dup/
        #: delay on this worker's frames — heartbeats included, which is
        #: how gray network paths are modeled), the exec seam runs
        #: before pool submission (slow / crash_before) and after
        #: results ship (crash_after). REGISTER stays un-injected: it is
        #: the instance's birth certificate, and a scenario that wants a
        #: never-registering worker simply doesn't start one.
        from tpu_faas import chaos as _chaos

        _plan = _chaos.from_env()
        self._chaos_wire = _plan.wire() if _plan is not None else None
        self._chaos_exec = _plan.execution() if _plan is not None else None

    def stop(self) -> None:
        self._stopping = True

    def drain(self) -> None:
        """Graceful shutdown: deregister (dispatcher stops assigning), keep
        serving until every in-flight task's result has shipped, then exit.
        Contrast with a hard kill, where in-flight tasks are recovered only
        by heartbeat-timeout purge + re-dispatch."""
        self._draining = True

    def _send(self, msg_type: str, **data: object) -> None:
        """Frame per the negotiated state: binary once the dispatcher has
        proven (by sending one) that it decodes binary frames, ASCII until
        then — so a reference-style dispatcher never sees a frame it can't
        decode. The one worker->dispatcher send point: the chaos wire
        seam lives here (dup is safe — results are at-least-once and the
        dispatcher's from_owner/terminal checks already tolerate
        replays)."""
        payload = m.encode_for(self._peer_bin, msg_type, **data)
        if self._chaos_wire is not None:
            self._chaos_wire.send(payload, self.socket.send)
            return
        self.socket.send(payload)

    def register(self) -> None:
        # REGISTER always rides the ASCII contract (first contact: the
        # peer's decoder is unknown); the caps list inside it is what
        # unlocks digest shipping + binary framing from the other side
        self.socket.send(
            m.encode(
                m.REGISTER,
                num_processes=self.num_processes,
                token=self.token,
                ephemeral=self.token_is_ephemeral,
                caps=list(self.caps),
            )
        )

    # -- payload plane -----------------------------------------------------
    def _submit_task(
        self, data: dict, from_fill: bool = False, collect: list | None = None
    ) -> bool:
        """Resolve one TASK message's function body and put it on the
        pool. Digest-only tasks (payload plane) hit the parent cache; a
        miss parks the task and asks the dispatcher with BLOB_MISS —
        False means parked, not submitted. ``from_fill`` (the fill
        handler resubmitting a parked task) skips the hit/miss counters:
        that resolution was already counted as its original miss.
        ``collect`` (the TASK_BATCH path): a resolved task is appended as
        a pool-submit tuple instead of submitted, so the caller can bundle
        the whole batch into O(1) pool IPC messages — parking semantics
        are unchanged (a parked task misses its bundle and rides a
        classic submit when its fill lands)."""
        digest = data.get("fn_digest")
        trace_id = data.get("trace_id")
        if isinstance(trace_id, str) and trace_id:
            self._task_trace[data["task_id"]] = trace_id
            log.debug(
                "task received", extra=log_ctx(
                    task_id=data["task_id"], trace_id=trace_id
                ),
            )
        payload = data.get("fn_payload")
        if payload is None:
            payload = self.fn_cache.get(digest) if digest else None
            if payload is None:
                if not from_fill:
                    FN_CACHE_MISSES.inc()
                self._awaiting.setdefault(digest, []).append(data)
                if digest not in self._miss_sent:
                    self._send(m.BLOB_MISS, digest=digest)
                    self._miss_sent[digest] = time.monotonic()
                return False
            if not from_fill:
                FN_CACHE_HITS.inc()
        elif digest:
            # inline body with a digest attached: warm the cache so a
            # later digest-only TASK (dispatcher upgraded mid-stream)
            # needs no fill round
            self.fn_cache.put(digest, payload)
        ok, deps = self._resolve_deps(data, from_fill)
        if not ok:
            return False
        rb = data.get("rblob_min")
        if isinstance(rb, int) and rb > 0 and m.CAP_RESULT_BLOB in self.caps:
            # the dispatcher's per-task digest-ship permission: remember it
            # until this task's result is framed
            self._task_rblob[data["task_id"]] = rb
        if self._chaos_exec is not None:
            # exec chaos (slow / crash_before) runs in the serve thread,
            # ahead of pool handoff: a gray worker stalls its whole
            # intake (the failure shape the health plane must catch),
            # and a crash kills the WORKER — the dispatcher's liveness
            # machinery reclaims, so no task reaches a terminal FAILED
            self._chaos_exec.before_task(data["task_id"])
        if collect is not None:
            item = (
                data["task_id"],
                payload,
                data["param_payload"],
                data.get("timeout"),
                digest,
            )
            # 6th element only when parents were delivered: flat tasks keep
            # the historical 5-tuple shape
            collect.append(item if deps is None else item + (deps,))
            return True
        self.pool.submit(
            data["task_id"],
            payload,
            data["param_payload"],
            timeout=data.get("timeout"),
            fn_digest=digest,
            dep_results=deps,
        )
        return True

    def _resolve_deps(self, data: dict, from_fill: bool):
        """Resolve a graph child's delivered parent results (result-blob
        plane): ``dep_results`` bodies ride the frame as-is;
        ``dep_digests`` hit the result cache, and the FIRST missing digest
        parks the task (BLOB_MISS with kind=result) — fills re-resolve
        incrementally, so a multi-miss child serializes its fetches (rare
        by construction: the dispatcher only ships digests it believes
        this cache already holds). Returns (ok, deps); ok False = parked.
        """
        bodies = data.get("dep_results")
        digests = data.get("dep_digests")
        if not bodies and not digests:
            return True, None
        deps: dict[str, str] = dict(bodies) if isinstance(bodies, dict) else {}
        if isinstance(digests, dict):
            for pid, dg in digests.items():
                if not isinstance(dg, str) or not dg:
                    continue
                body = self.result_cache.get(dg)
                if body is None:
                    if not from_fill:
                        RESULT_CACHE_MISSES.inc()
                    self._miss_kind[dg] = "result"
                    self._awaiting.setdefault(dg, []).append(data)
                    if dg not in self._miss_sent:
                        self._send(m.BLOB_MISS, digest=dg)
                        self._miss_sent[dg] = time.monotonic()
                    return False, None
                if not from_fill:
                    RESULT_CACHE_HITS.inc()
                deps[pid] = body
        return True, deps or None

    # -- batched data plane ------------------------------------------------
    def _on_task_batch(self, data: dict) -> None:
        """One TASK_BATCH frame: resolve every element (identical per-task
        semantics — digest cache, BLOB_MISS parking, trace stamping), then
        spread the ready set over the pool's free children as bundles, so
        K tasks cost ~min(K, free) pool IPC messages instead of K.
        Receiving this frame is also the negotiation proof that flips this
        worker's own result drain to RESULT_BATCH framing."""
        self._peer_batch = True
        ready: list[tuple] = []
        for item in data.get("tasks", ()):
            if isinstance(item, dict) and "task_id" in item:
                self._submit_task(item, collect=ready)
        self._submit_bundles(ready)

    #: floor on bundle size when chunking a TASK_BATCH across free pool
    #: children: below this, per-task pool IPC dominates sub-ms execution
    #: and splitting buys nothing — the dispatcher already bounds a frame
    #: at the worker's free slots, so free-proportional chunking alone
    #: would degenerate every frame into singletons
    _MIN_BUNDLE = 4

    def _submit_bundles(self, ready: list[tuple]) -> None:
        """Chunk resolved tasks into bundles balancing the two costs:
        bundling amortizes pool IPC (the batched plane's point), while
        one huge bundle would serialize everything through a single child.
        The batch splits into min(free_children, K // _MIN_BUNDLE)
        contiguous bundles (at least one) — large frames still fan out
        across children in >= _MIN_BUNDLE chunks, small frames ride one
        bundle whose sequential execution is cheaper than per-task IPC.
        Sequential-in-child is the deliberate tradeoff batching buys its
        throughput with: for the sub-ms functions the plane targets, a
        bundle's serial execution is noise next to the saved per-task
        overhead, while long-running functions should keep --batch-max
        off/small dispatcher-side (documented in OPERATIONS.md)."""
        if not ready:
            return
        n_bundles = max(
            1, min(self.pool.free, len(ready) // self._MIN_BUNDLE)
        )
        size = -(-len(ready) // n_bundles)  # ceil
        for lo in range(0, len(ready), size):
            self.pool.submit_bundle(ready[lo:lo + size])

    def _on_blob_fill(self, data: dict) -> None:
        digest = data.get("digest")
        if not isinstance(digest, str) or not digest:
            return
        kind = self._miss_kind.get(digest, "fn")
        body = data.get("data")
        if isinstance(body, str):
            cache = self.result_cache if kind == "result" else self.fn_cache
            cache.put(digest, body)
            self._miss_kind.pop(digest, None)
            self._miss_sent.pop(digest, None)
            for parked in self._awaiting.pop(digest, ()):
                self._submit_task(parked, from_fill=True)
        elif data.get("missing"):
            # the blob is gone from the store too: nothing will ever fill
            # this digest — FAIL the parked tasks so their records
            # converge instead of waiting forever
            what = "parent result" if kind == "result" else "function"
            self._miss_kind.pop(digest, None)
            self._miss_sent.pop(digest, None)
            for parked in self._awaiting.pop(digest, ()):
                self._task_rblob.pop(parked["task_id"], None)
                extra: dict = {}
                trace_id = self._task_trace.pop(parked["task_id"], None)
                if trace_id:
                    extra["trace_id"] = trace_id
                self._send(
                    m.RESULT,
                    task_id=parked["task_id"],
                    status=str(TaskStatus.FAILED),
                    result=serialize(
                        RuntimeError(
                            f"{what} blob {digest[:16]}... missing from "
                            "the store"
                        )
                    ),
                    **extra,
                )
        # an empty fill (no data, no missing) means "store outage, retry":
        # the parked tasks stay and the resend timer re-asks

    def _result_item(self, res) -> dict:
        """One result's wire fields (shared by the per-task RESULT form
        and the RESULT_BATCH elements). A COMPLETED result at least
        ``rblob_min`` bytes whose TASK frame carried that marker ships
        DIGEST-ONLY (result-blob plane): the body stays in the result
        cache, keyed by content digest, until someone pulls it — failures
        always carry their body (error payloads must stay materializable
        without this worker)."""
        rb = self._task_rblob.pop(res.task_id, None)
        if (
            rb
            and res.status == str(TaskStatus.COMPLETED)
            and isinstance(res.result, str)
            and len(res.result) >= rb
        ):
            digest = payload_digest(res.result)
            self.result_cache.put(digest, res.result)
            item = {
                "task_id": res.task_id,
                "status": res.status,
                "result_digest": digest,
                "result_size": len(res.result),
                "elapsed": res.elapsed,
                "started_at": res.started_at,
            }
        else:
            item = {
                "task_id": res.task_id,
                "status": res.status,
                "result": res.result,
                "elapsed": res.elapsed,
                "started_at": res.started_at,
            }
        trace_id = self._task_trace.pop(res.task_id, None)
        if trace_id:
            item["trace_id"] = trace_id
        log.debug(
            "shipped result %s", res.status,
            extra=log_ctx(task_id=res.task_id, trace_id=trace_id),
        )
        return item

    def _ship_results(self, results) -> int:
        """Ship one drain's results: a multi-result drain toward a
        batch-negotiated dispatcher coalesces into ONE RESULT_BATCH frame
        (misfires total rides once at the top level); everything else —
        single results, and every peer that never sent a TASK_BATCH —
        keeps the per-task RESULT wire byte for byte."""
        if not results:
            return 0
        if self._peer_batch and len(results) > 1:
            self._send(
                m.RESULT_BATCH,
                results=[self._result_item(res) for res in results],
                misfires=self.pool.n_misfires,
            )
        else:
            for res in results:
                # field order matches the historical per-task send exactly
                # (trace_id last, after misfires): the serialized frame
                # must stay byte-identical for non-batch peers
                item = self._result_item(res)
                trace_id = item.pop("trace_id", None)
                item["misfires"] = self.pool.n_misfires
                if trace_id:
                    item["trace_id"] = trace_id
                self._send(m.RESULT, **item)
        if self._chaos_exec is not None:
            # crash_after fires once results are on the wire: the
            # dispatcher must tolerate the purge racing already-shipped
            # (possibly duplicated) results
            self._chaos_exec.after_result(results[-1].task_id)
        return len(results)

    def _on_blob_pull(self, data: dict) -> None:
        """Dispatcher->worker BLOB_MISS (result-blob plane, the REVERSE of
        the function-blob flow): serve a result body out of the result
        cache so the dispatcher can materialize it — into the store for a
        legacy reader, or onward to a cache-cold child worker.
        ``missing=True`` when the entry was evicted: the dispatcher
        surfaces that as the documented result-gone failure mode."""
        digest = data.get("digest")
        if not isinstance(digest, str) or not digest:
            return
        body = self.result_cache.get(digest)
        if body is not None:
            self._send(m.BLOB_FILL, digest=digest, data=body)
        else:
            self._send(m.BLOB_FILL, digest=digest, missing=True)

    def _resend_stale_misses(self, now: float) -> None:
        for digest in list(self._awaiting):
            if now - self._miss_sent.get(digest, 0.0) >= _MISS_RESEND_S:
                self._send(m.BLOB_MISS, digest=digest)
                self._miss_sent[digest] = now

    def run(self, max_tasks: int | None = None) -> int:
        shipped = 0
        # spawn pool children BEFORE announcing capacity: the first pool use
        # otherwise blocks the loop for seconds and the heartbeat silence
        # gets the worker falsely purged
        self.pool.warmup()
        self.register()
        last_heartbeat = time.monotonic()
        deregistered = False
        quiet_since: float | None = None
        try:
            while not self._stopping:
                if self._draining and not deregistered:
                    self._send(m.DEREGISTER)
                    deregistered = True
                    log.info(
                        "draining: %d task(s) in flight", self.pool.busy
                    )
                now = time.monotonic()
                # Keep heartbeating WHILE TASKS ARE IN FLIGHT even after
                # deregistering — going silent would let a drain longer than
                # time_to_expire trigger a false purge + duplicate execution
                # (the dispatcher's record still exists until the last
                # result lands, so these heartbeats only refresh it). Only
                # once the pool is empty do heartbeats stop: the record is
                # dropped with the final result, and a further heartbeat
                # would make the unknown-sender handshake resurrect it.
                if (
                    self.heartbeat
                    and (not deregistered or self.pool.busy > 0)
                    and now - last_heartbeat >= self.heartbeat_period
                ):
                    self._send(m.HEARTBEAT)
                    last_heartbeat = now  # the fix for reference :61-62
                if self._awaiting:
                    self._resend_stale_misses(now)
                if self._chaos_wire is not None:
                    # chaos-delayed frames whose hold expired go out now
                    self._chaos_wire.flush(self.socket.send)
                events = dict(self.poller.poll(self.poll_timeout_ms))
                if self.socket in events:
                    while True:
                        try:
                            raw = self.socket.recv(flags=zmq.NOBLOCK)
                        except zmq.Again:
                            break
                        if not self._peer_bin and m.is_binary(raw):
                            # the dispatcher frames in binary: negotiation
                            # complete — our sends switch too
                            self._peer_bin = True
                        msg_type, data = m.decode(raw)
                        if msg_type == m.TASK:
                            # no admission gate: dispatcher controls load
                            self._submit_task(data)
                        elif msg_type == m.TASK_BATCH:
                            self._on_task_batch(data)
                        elif msg_type == m.BLOB_FILL:
                            self._on_blob_fill(data)
                        elif msg_type == m.BLOB_MISS:
                            # reverse pull: the dispatcher wants a result
                            # body this worker's cache holds
                            self._on_blob_pull(data)
                        elif msg_type == m.CANCEL:
                            # force-cancel: interrupt mid-run or drop
                            # pre-start; the CANCELLED result ships via the
                            # normal drain below. False = task not held
                            # here (already finished — its real result
                            # shipped or is about to; nothing to do)
                            tid = data.get("task_id", "")
                            if self.pool.cancel(tid):
                                log.info(
                                    "force-cancelling task %s", tid,
                                    extra={"task_id": tid},
                                )
                        elif msg_type == m.RECONNECT:
                            # a draining worker reports zero capacity: it
                            # must not be handed new work. rblob workers
                            # also advertise their result-cache occupancy:
                            # rcache_n == 0 tells a (re)connecting
                            # dispatcher to clear any stale holdings
                            # mirror it kept for this worker (restart
                            # detection for the locality lane).
                            rc: dict = {}
                            if m.CAP_RESULT_BLOB in self.caps:
                                rc = {
                                    "rcache_n": len(self.result_cache),
                                    "rcache_bytes":
                                        self.result_cache.n_bytes,
                                }
                            self._send(
                                m.RECONNECT,
                                free_processes=(
                                    0 if self._draining else self.pool.free
                                ),
                                token=self.token,
                                ephemeral=self.token_is_ephemeral,
                                caps=list(self.caps),
                                **rc,
                            )
                shipped += self._ship_results(self.pool.drain())
                if max_tasks is not None and shipped >= max_tasks:
                    break
                if deregistered and self.pool.busy == 0:
                    # linger briefly: a TASK dispatched before the
                    # dispatcher processed our DEREGISTER may still be on
                    # the wire (anything later falls back to the normal
                    # purge + re-dispatch recovery)
                    if quiet_since is None:
                        quiet_since = now
                    elif now - quiet_since >= 0.25:
                        break
                else:
                    quiet_since = None
        finally:
            self.pool.close()
            self.socket.close(linger=0)
        return shipped


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(description="tpu-faas push worker")
    ap.add_argument("num_processes", type=int)
    ap.add_argument("dispatcher_url")
    ap.add_argument("--hb", action="store_true", help="enable heartbeats")
    ap.add_argument(
        "--hb-period", type=float, default=1.0, help="heartbeat period (s)"
    )
    ap.add_argument(
        "--token",
        default=None,
        help="stable worker identity for persisted speed grades "
        "(default: minted per process)",
    )
    ns = ap.parse_args(argv)
    log.info(
        "push worker: %d processes -> %s (hb=%s)",
        ns.num_processes,
        ns.dispatcher_url,
        ns.hb,
    )
    from tpu_faas.worker.drain import install_drain_signals

    worker = PushWorker(
        ns.num_processes, ns.dispatcher_url, ns.hb, ns.hb_period,
        token=ns.token,
    )
    install_drain_signals(worker)
    worker.run()


if __name__ == "__main__":
    main()
