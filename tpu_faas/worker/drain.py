"""Graceful-shutdown signal wiring shared by both worker binaries.

First SIGTERM/SIGINT: drain — deregister, finish in-flight tasks, ship their
results, exit 0. Second signal: stop immediately — the drain may be stuck
behind a hung or very long task (the poison case), and an operator's repeat
Ctrl-C / a supervisor's escalation must still work without resorting to
SIGKILL. Signals arriving before the handlers are installed (interpreter
startup) take the default action and kill outright; that is the crash path,
which heartbeat-timeout purge + re-dispatch already recovers.
"""

from __future__ import annotations

import signal


def install_drain_signals(worker) -> None:
    """``worker`` needs ``drain()``, ``stop()``, and ``_draining``."""

    def handler(signum, frame) -> None:
        if worker._draining:
            worker.stop()  # second signal: exit now; `finally` cleans up
        else:
            worker.drain()

    signal.signal(signal.SIGTERM, handler)
    signal.signal(signal.SIGINT, handler)
