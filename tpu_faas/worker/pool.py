"""Worker-side process pool with broken-pool recovery and force-cancel.

Wraps a ProcessPoolExecutor (forkserver context) around `execute_fn` with the
same failure semantics the local dispatcher has: a child killed by user code
surfaces as a FAILED result for that task and the pool is rebuilt, instead of
the reference's silent slot leak (its workers count busy slots in the parent
and a vanished child never decrements, pull_worker.py:63-72).

Force-cancel (:meth:`TaskPool.cancel`): interrupt a task MID-RUN without
killing its child process, by reusing the shape of the per-task SIGALRM
timeout (core/executor.py) with SIGUSR1. Children report (task_id, pid)
start/end events on a queue; the parent signals the pid its bookkeeping says
runs the target, and the child's handler raises
:class:`~tpu_faas.core.executor.TaskCancelledInterrupt` into whatever is
currently running — producing a terminal CANCELLED result and freeing the
slot in place (no pool rebuild). The event queue is necessarily a little
stale, so a signal CAN land after the child switched tasks; the handler
cannot know the parent's intent (signals carry no payload), so
:meth:`TaskPool.drain` repairs misfires internally: a CANCELLED result
for a task nobody asked to cancel is resubmitted — it never reported
anything externally, so re-running it is invisible. Same reach limits as
the timeout: POSIX main-thread children; C code that never yields can't
be interrupted.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import queue
import signal as _signal
import threading
from concurrent.futures import Future, ProcessPoolExecutor, wait
from concurrent.futures.process import BrokenProcessPool

from tpu_faas.core.executor import (
    ExecutionResult,
    TaskCancelledInterrupt,
    execute_fn,
)
from tpu_faas.core.serialize import serialize
from tpu_faas.core.task import TaskStatus
from tpu_faas.obs import REGISTRY
from tpu_faas.utils.logging import get_logger, log_ctx

log = get_logger("worker.pool")

#: Process-wide pool counters (the worker process's share of the unified
#: metric catalog): every drained result by terminal status, plus the
#: misfire repairs — the one at-least-once execution in the system — as a
#: first-class series instead of a buried log line.
_TASKS_TOTAL = REGISTRY.counter(
    "tpu_faas_worker_pool_tasks_total",
    "Results drained from this process's task pools, by terminal status",
    ("status",),
)
_MISFIRES_TOTAL = REGISTRY.counter(
    "tpu_faas_worker_pool_misfires_total",
    "Cancel interrupts that landed on a bystander task and were repaired "
    "by resubmission (at-least-once executions)",
)

#: Parent-side blob-cache counters, shared by both worker kinds (they
#: both import the pool) and split by cache KIND — ``fn`` is the payload
#: cache (digest-shipped TASK functions), ``result`` the result cache
#: (digest-shipped parent results, ``--result-blobs``): the
#: operator-visible proof that steady state ships digests, not bodies,
#: with the two planes separately triageable.
BLOB_CACHE_HITS = REGISTRY.counter(
    "tpu_faas_worker_blob_cache_hit_total",
    "Digest resolutions served from this worker's blob caches, by cache "
    "kind (fn = payload cache, result = result cache)",
    ("kind",),
)
BLOB_CACHE_MISSES = REGISTRY.counter(
    "tpu_faas_worker_blob_cache_miss_total",
    "Digest resolutions that needed a BLOB_MISS/BLOB_FILL round, by "
    "cache kind (fn = payload cache, result = result cache)",
    ("kind",),
)
#: the function-cache children, under their historical import names (both
#: workers increment these on the TASK fn_digest path)
FN_CACHE_HITS = BLOB_CACHE_HITS.labels(kind="fn")
FN_CACHE_MISSES = BLOB_CACHE_MISSES.labels(kind="fn")
#: the result-cache children (rblob workers, dep_digests resolution)
RESULT_CACHE_HITS = BLOB_CACHE_HITS.labels(kind="result")
RESULT_CACHE_MISSES = BLOB_CACHE_MISSES.labels(kind="result")

#: Batched data plane (worker side): bundle sizes and pool IPC volume.
#: ipc_total / tasks_total is the O(1)-pool-wakeups-per-bundle proof the
#: bench asserts on — a K-task bundle pays ONE executor submit.
BUNDLE_SIZE = REGISTRY.histogram(
    "tpu_faas_worker_bundle_size",
    "Tasks per pool submission (1 = the classic per-task path; larger "
    "values are TASK_BATCH bundles executing K tasks on one pool IPC "
    "message)",
    buckets=(1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0),
)
POOL_IPC = REGISTRY.counter(
    "tpu_faas_worker_pool_ipc_total",
    "Pool IPC submissions (executor round trips): a K-task bundle "
    "counts 1, so ipc/tasks << 1 is the bundling win",
)

#: done-queue key marking a bundle future's completion (the payload is a
#: list of ExecutionResults, one per member)
_BUNDLE = object()

#: child-side: the task id currently executing in THIS child (None between
#: tasks) — consulted by the SIGUSR1 handler, plain memory only (a signal
#: handler must never do IPC)
_CURRENT_TASK: str | None = None
#: child-side: the start/end event queue back to the parent
_EVENTS = None


def _on_cancel_signal(signum, frame):
    global _CURRENT_TASK
    tid = _CURRENT_TASK
    if tid is not None:
        # close the window BEFORE raising: a duplicate signal (client
        # retry, two relays racing) landing while the first interrupt is
        # still unwinding must no-op — a raise inside _run_reported's
        # except block would escape as the future's exception and turn a
        # deliberate CANCELLED into a spurious FAILED
        _CURRENT_TASK = None
        raise TaskCancelledInterrupt(f"task {tid} force-cancelled mid-run")


def _child_init(events) -> None:
    """Pool-child initializer: stash the event queue, install the cancel
    handler (main thread of the child; mirrors the SIGALRM arming in
    execute_fn)."""
    global _EVENTS
    _EVENTS = events
    if hasattr(_signal, "SIGUSR1"):
        _signal.signal(_signal.SIGUSR1, _on_cancel_signal)


def _run_reported(
    task_id: str,
    ser_fn: str,
    ser_params: str,
    timeout: float | None,
    fn_digest: str | None = None,
    dep_results: dict[str, str] | None = None,
) -> ExecutionResult:
    """execute_fn wrapped with start/end reporting + the cancel window.

    The WHOLE window — from opening `_CURRENT_TASK` through execute_fn's
    return — sits inside one try, so an interrupt can never escape as the
    future's exception (that would report FAILED, leak the child's window
    permanently open, and let the next stray signal kill the executor's
    worker loop). `_CURRENT_TASK` is set before the start event ships: a
    deferred interrupt fired on seeing that event must find the window
    open. An interrupt landing AFTER execute_fn returned keeps the real
    result — the task beat the signal, and discarding a computed
    COMPLETED for a raced CANCELLED would break the documented force-
    cancel contract."""
    global _CURRENT_TASK
    res: ExecutionResult | None = None
    end_sent = False
    try:
        try:
            _CURRENT_TASK = task_id
            if _EVENTS is not None:
                _EVENTS.put(("start", task_id, os.getpid()))
            # interrupts DURING the call are handled inside execute_fn
            # itself (its except clauses return a CANCELLED result)
            res = execute_fn(
                task_id, ser_fn, ser_params, timeout, fn_digest, dep_results
            )
        except TaskCancelledInterrupt as exc:
            if res is None:
                # landed before execute_fn produced anything: a pre-start
                # cancel (the handler already closed the window)
                res = ExecutionResult(
                    task_id, str(TaskStatus.CANCELLED), serialize(exc)
                )
        finally:
            _CURRENT_TASK = None
            if _EVENTS is not None:
                _EVENTS.put(("end", task_id, 0))
                end_sent = True
    except TaskCancelledInterrupt as exc:
        # the signal landed in the sliver between the try body completing
        # and the finally's window close — the handler cleared the window
        # before raising, so no further interrupt can arrive; keep the
        # real result if one exists (the task beat the signal) and make
        # sure the end event still ships
        if res is None:
            res = ExecutionResult(
                task_id, str(TaskStatus.CANCELLED), serialize(exc)
            )
        if _EVENTS is not None and not end_sent:
            _EVENTS.put(("end", task_id, 0))
    return res


def _run_bundle(items) -> list[ExecutionResult]:
    """Bundle form of _run_reported: K tasks ride ONE pool IPC message and
    execute sequentially in this child — one wakeup, one result shipment,
    and a repeated function pays its digest-cache lookup against a warm
    entry for every element after the first. Each element keeps the full
    per-task contract (own timeout arm, own cancel window, own start/end
    events), so a mid-bundle force-cancel interrupts exactly the element
    the parent's event mirror says is running. ``items`` is a list of
    (task_id, ser_fn, ser_params, timeout, fn_digest[, dep_results])
    tuples."""
    return [_run_reported(*item) for item in items]


def _warm() -> None:
    """No-op run in each child to force its spawn (must be module-level to
    pickle)."""


class TaskPool:
    def __init__(self, num_processes: int) -> None:
        self.num_processes = num_processes
        self._done: queue.Queue[tuple[str, Future]] = queue.Queue()
        self._busy = 0
        #: parent-side mirror of the children's start/end events:
        #: task_id -> child pid, maintained by _drain_events
        self._running_pids: dict[str, int] = {}
        #: in-flight bookkeeping for force-cancel: the future (so a task
        #: still queued in the executor can be cancelled without a signal),
        #: the submitted payloads (so a misfired interrupt can resubmit),
        #: and which tasks a cancel was actually requested for
        self._futures: dict[str, Future] = {}
        self._args: dict[
            str, tuple[str, str, float | None, str | None, dict | None]
        ] = {}
        #: bundle future -> member task ids (batched data plane): members
        #: share ONE future, so cancel() must never fut.cancel() a bundle
        #: (it would cancel the innocent siblings) — bundled pre-start
        #: cancels ride the deferred-kill path instead
        self._bundle_members: dict[Future, list[str]] = {}
        self._want_cancel: set[str] = set()
        #: cancels for tasks sitting in the executor's CALL QUEUE (future
        #: no longer .cancel()-able, child not started): the interrupt is
        #: deferred until the task's start event arrives
        self._deferred_kill: set[str] = set()
        #: cumulative misfire repairs (a cancel interrupt that landed on a
        #: bystander task, repaired by resubmission — the one at-least-once
        #: execution in the system). Surfaced by the workers on their
        #: RESULT messages and aggregated into dispatcher /stats, so
        #: doubled side effects are operator-visible without log scraping.
        self.n_misfires = 0
        #: completion wakeup pipe: the done callback (executor thread)
        #: pokes it so a serving loop parked in a poller wakes the moment
        #: a result is ready instead of waiting out its poll timeout —
        #: the worker-side analog of the dispatcher's event-driven intake.
        #: Register ``wakeup_fd`` for POLLIN; drain() clears it.
        self._wake_r, self._wake_w = os.pipe()
        os.set_blocking(self._wake_r, False)
        os.set_blocking(self._wake_w, False)
        #: serializes done-callback pokes against close(): without it, a
        #: callback that snapshotted the fd pre-close could write into a
        #: since-reused descriptor number (one uncontended acquire per
        #: POOL round trip, not per task — bundles amortize it too)
        self._wake_lock = threading.Lock()
        self._executor = self._make()

    def _make(self) -> ProcessPoolExecutor:
        ctx = mp.get_context("forkserver")
        self._events = ctx.SimpleQueue()
        self._running_pids.clear()
        return ProcessPoolExecutor(
            max_workers=self.num_processes,
            mp_context=ctx,
            initializer=_child_init,
            initargs=(self._events,),
        )

    @property
    def wakeup_fd(self) -> int:
        """Readable fd that becomes ready when a result lands in the done
        queue (level-cleared by drain())."""
        return self._wake_r

    def _on_done(self, key, fut) -> None:
        """Done callback (runs on an executor thread): enqueue + poke the
        wakeup pipe. A full pipe is fine — the byte already in it wakes
        the reader, which drains everything level-triggered. The poke
        holds _wake_lock so it cannot race close(): a straggler callback
        either sees the live fd (close hasn't started) or -1 (close won
        the lock) — never a closed-and-reused descriptor number."""
        self._done.put((key, fut))
        with self._wake_lock:
            w = self._wake_w
            if w < 0:
                return
            try:
                os.write(w, b"\0")
            except (BlockingIOError, OSError):
                pass

    def _drain_events(self) -> None:
        while not self._events.empty():
            kind, tid, pid = self._events.get()
            if kind == "start":
                self._running_pids[tid] = pid
                if tid in self._deferred_kill:
                    # a cancel arrived while this task sat in the call
                    # queue: interrupt it the moment it starts (the child
                    # opens its cancel window BEFORE shipping this event)
                    self._deferred_kill.discard(tid)
                    try:
                        os.kill(pid, _signal.SIGUSR1)
                    except (ProcessLookupError, PermissionError):
                        pass
            else:
                self._running_pids.pop(tid, None)

    def cancel(self, task_id: str) -> bool:
        """Best-effort force-cancel of ``task_id``. True when the task will
        surface as a CANCELLED result from :meth:`drain` — either its
        future was still queued in the executor (cancelled without a
        signal) or an interrupt was sent to the child the event stream
        says runs it. False when it is not held here (finished, shipped,
        or never seen). The event stream lags reality by design, so an
        interrupt CAN land on a child that already switched tasks; drain()
        repairs such misfires internally by resubmitting the wrongly
        interrupted task — see the module docstring."""
        fut = self._futures.get(task_id)
        bundled = fut is not None and fut in self._bundle_members
        if fut is not None and not bundled and fut.cancel():
            # never handed to a child: the done-callback queues the
            # cancelled future and drain() reports terminal CANCELLED
            self._want_cancel.add(task_id)
            return True
        if not hasattr(_signal, "SIGUSR1"):
            return False
        self._drain_events()
        pid = self._running_pids.get(task_id)
        if pid is None:
            if fut is not None and not fut.done():
                # in the executor's call queue: no child to signal yet —
                # defer the interrupt to the task's start event
                self._deferred_kill.add(task_id)
                self._want_cancel.add(task_id)
                return True
            return False
        try:
            os.kill(pid, _signal.SIGUSR1)
        except (ProcessLookupError, PermissionError):
            return False
        self._want_cancel.add(task_id)
        return True

    @property
    def busy(self) -> int:
        return self._busy

    @property
    def free(self) -> int:
        return self.num_processes - self._busy

    def warmup(self, timeout: float = 120.0) -> None:
        """Force the lazy child-process spawn NOW, off the serving path.

        The executor spawns children on first submit; with forkserver that
        first submit blocks for seconds (forkserver boot + module re-import).
        A worker that pays this inside its serving loop goes heartbeat-silent
        long enough to be falsely purged — so workers warm up BEFORE
        registering with the dispatcher."""
        wait(
            [self._executor.submit(_warm) for _ in range(self.num_processes)],
            timeout=timeout,
        )

    def submit(
        self,
        task_id: str,
        fn_payload: str,
        param_payload: str,
        timeout: float | None = None,
        fn_digest: str | None = None,
        dep_results: dict[str, str] | None = None,
    ) -> None:
        """``fn_digest`` (payload plane): content digest of ``fn_payload``,
        keying the child-side deserialized-function cache so a repeated
        function pays dill decode once per child, not once per task.
        ``dep_results`` (result-blob plane): the graph child's resolved
        parent bodies {parent_id: serialized result}, exposed to the
        executing function via core/executor.dep_results()."""
        try:
            fut = self._executor.submit(
                _run_reported, task_id, fn_payload, param_payload, timeout,
                fn_digest, dep_results,
            )
        except BrokenProcessPool:
            self._executor.shutdown(wait=False, cancel_futures=True)
            self._executor = self._make()
            fut = self._executor.submit(
                _run_reported, task_id, fn_payload, param_payload, timeout,
                fn_digest, dep_results,
            )
        fut.add_done_callback(lambda f, tid=task_id: self._on_done(tid, f))
        self._futures[task_id] = fut
        self._args[task_id] = (
            fn_payload, param_payload, timeout, fn_digest, dep_results
        )
        self._busy += 1
        POOL_IPC.inc()
        BUNDLE_SIZE.observe(1.0)

    def submit_bundle(self, items) -> None:
        """Submit K tasks as ONE pool IPC message (batched data plane):
        ``items`` is a list of (task_id, fn_payload, param_payload,
        timeout, fn_digest[, dep_results]) tuples that execute
        sequentially in one child.
        Every per-task semantic is preserved element-wise — own timeout,
        own cancel window (deferred-kill interrupts exactly the running
        element), own misfire repair — but the bundle costs one executor
        round trip and one drain entry instead of K of each. A singleton
        falls through to the classic submit."""
        if not items:
            return
        if len(items) == 1:
            self.submit(*items[0])
            return
        items = list(items)
        try:
            fut = self._executor.submit(_run_bundle, items)
        except BrokenProcessPool:
            self._executor.shutdown(wait=False, cancel_futures=True)
            self._executor = self._make()
            fut = self._executor.submit(_run_bundle, items)
        fut.add_done_callback(lambda f: self._on_done(_BUNDLE, f))
        self._bundle_members[fut] = [it[0] for it in items]
        for it in items:
            task_id = it[0]
            self._futures[task_id] = fut
            self._args[task_id] = (
                it[1], it[2], it[3], it[4],
                it[5] if len(it) > 5 else None,
            )
        self._busy += len(items)
        POOL_IPC.inc()
        BUNDLE_SIZE.observe(float(len(items)))

    def _pop_member(self, task_id: str):
        """Shared per-task bookkeeping pop as a result is drained: busy
        slot, future/args maps, deferred-kill note. Returns (wanted,
        args)."""
        self._busy -= 1
        self._futures.pop(task_id, None)
        self._deferred_kill.discard(task_id)
        args = self._args.pop(task_id, None)
        wanted = task_id in self._want_cancel
        self._want_cancel.discard(task_id)
        return wanted, args

    @staticmethod
    def _terminal(task_id: str, status: TaskStatus, exc: BaseException) -> ExecutionResult:
        """Synthesized terminal result for a task whose future never
        produced one (pre-start cancel, rebuild-cancelled, dead child) —
        ONE construction point so the per-task and bundle drain paths
        cannot diverge."""
        _TASKS_TOTAL.labels(status=str(status)).inc()
        return ExecutionResult(task_id, str(status), serialize(exc))

    def _deliver(
        self, task_id: str, res: ExecutionResult, wanted: bool, args, out
    ) -> None:
        """Terminal-result delivery with misfire repair (shared by the
        per-task and bundle drain paths): a CANCELLED result nobody asked
        for is a misfired interrupt — resubmit instead of delivering."""
        if (
            res.status == str(TaskStatus.CANCELLED)
            and not wanted
            and args is not None
        ):
            log.warning(
                "misfired cancel interrupt hit task %s; resubmitting it",
                task_id,
                extra=log_ctx(task_id=task_id),
            )
            self.n_misfires += 1
            _MISFIRES_TOTAL.inc()
            self.submit(task_id, *args)
            return
        _TASKS_TOTAL.labels(status=res.status).inc()
        out.append(res)

    def drain(self) -> list[ExecutionResult]:
        """Non-blocking: collect all finished results. Force-cancel
        semantics live here: a cancelled-before-start future becomes a
        terminal CANCELLED result; a CANCELLED result nobody requested (an
        interrupt that landed after its child switched tasks) is repaired
        by resubmitting the task instead of delivering — the wrongly
        interrupted run reported nothing externally, so the re-execution
        is invisible to every consumer."""
        self._drain_events()  # keep the task->pid mirror bounded + fresh
        r = self._wake_r
        if r >= 0:
            try:
                while os.read(r, 4096):  # clear the wakeup pipe
                    pass
            except (BlockingIOError, OSError):
                pass
        out: list[ExecutionResult] = []
        while True:
            try:
                task_id, fut = self._done.get_nowait()
            except queue.Empty:
                return out
            if task_id is _BUNDLE:
                self._drain_bundle(fut, out)
                continue
            wanted, args = self._pop_member(task_id)
            if fut.cancelled():
                if wanted:
                    # deliberate pre-start cancel: terminal CANCELLED
                    out.append(
                        self._terminal(
                            task_id,
                            TaskStatus.CANCELLED,
                            TaskCancelledInterrupt(
                                f"task {task_id} cancelled before start"
                            ),
                        )
                    )
                    continue
                # future cancelled by a broken-pool rebuild: .exception()
                # would RAISE CancelledError; report the task as FAILED
                exc: BaseException | None = RuntimeError(
                    "task cancelled: worker pool died and was rebuilt"
                )
            else:
                exc = fut.exception()
            if exc is None:
                # misfire repair lives in _deliver: the one at-least-once
                # execution in the system, logged + counted there
                self._deliver(task_id, fut.result(), wanted, args, out)
            else:
                out.append(
                    self._terminal(
                        task_id, TaskStatus.FAILED, RuntimeError(str(exc))
                    )
                )

    def _drain_bundle(self, fut: Future, out: list[ExecutionResult]) -> None:
        """Drain one completed bundle future into ``out``. The happy path
        delivers each member through the shared misfire-repair gate; a
        future-level failure (pool rebuild cancelled it, or a member
        killed the child — the executor fails the WHOLE submission) fails
        every member, exactly what K per-task futures sharing the dead
        child's queue would have reported."""
        members = self._bundle_members.pop(fut, [])
        was_cancelled = fut.cancelled()
        if was_cancelled:
            exc: BaseException | None = RuntimeError(
                "task cancelled: worker pool died and was rebuilt"
            )
        else:
            exc = fut.exception()
        if exc is None:
            by_id = {res.task_id: res for res in fut.result()}
            for task_id in members:
                wanted, args = self._pop_member(task_id)
                res = by_id.get(task_id)
                if res is None:  # defensive: a child must answer every item
                    res = ExecutionResult(
                        task_id,
                        str(TaskStatus.FAILED),
                        serialize(
                            RuntimeError("bundle returned no result")
                        ),
                    )
                self._deliver(task_id, res, wanted, args, out)
        else:
            for task_id in members:
                wanted, _ = self._pop_member(task_id)
                if was_cancelled and wanted:
                    # per-task parity: a rebuild-cancelled future whose
                    # member had a deliberate cancel pending reports
                    # terminal CANCELLED, exactly like the single-task
                    # drain's cancelled+wanted branch
                    out.append(
                        self._terminal(
                            task_id,
                            TaskStatus.CANCELLED,
                            TaskCancelledInterrupt(
                                f"task {task_id} cancelled before start"
                            ),
                        )
                    )
                    continue
                out.append(
                    self._terminal(
                        task_id, TaskStatus.FAILED, RuntimeError(str(exc))
                    )
                )

    def close(self) -> None:
        self._executor.shutdown(wait=False, cancel_futures=True)
        # park-then-close UNDER the wake lock: a straggler done callback
        # (the shutdown above does not wait) either ran its poke before
        # we took the lock or sees -1 after — the descriptor is never
        # closed (and possibly reused) under a callback's feet
        with self._wake_lock:
            r, w = self._wake_r, self._wake_w
            self._wake_r = self._wake_w = -1
            for fd in (r, w):
                if fd >= 0:
                    try:
                        os.close(fd)
                    except OSError:
                        pass
