"""Worker-side process pool with broken-pool recovery and force-cancel.

Wraps a ProcessPoolExecutor (forkserver context) around `execute_fn` with the
same failure semantics the local dispatcher has: a child killed by user code
surfaces as a FAILED result for that task and the pool is rebuilt, instead of
the reference's silent slot leak (its workers count busy slots in the parent
and a vanished child never decrements, pull_worker.py:63-72).

Force-cancel (:meth:`TaskPool.cancel`): interrupt a task MID-RUN without
killing its child process, by reusing the shape of the per-task SIGALRM
timeout (core/executor.py) with SIGUSR1. Children report (task_id, pid)
start/end events on a queue; the parent signals the pid its bookkeeping says
runs the target, and the child's handler raises
:class:`~tpu_faas.core.executor.TaskCancelledInterrupt` into whatever is
currently running — producing a terminal CANCELLED result and freeing the
slot in place (no pool rebuild). The event queue is necessarily a little
stale, so a signal CAN land after the child switched tasks; the handler
cannot know the parent's intent (signals carry no payload), so
:meth:`TaskPool.drain` repairs misfires internally: a CANCELLED result
for a task nobody asked to cancel is resubmitted — it never reported
anything externally, so re-running it is invisible. Same reach limits as
the timeout: POSIX main-thread children; C code that never yields can't
be interrupted.
"""

from __future__ import annotations

import multiprocessing as mp
import os
import queue
import signal as _signal
from concurrent.futures import Future, ProcessPoolExecutor, wait
from concurrent.futures.process import BrokenProcessPool

from tpu_faas.core.executor import (
    ExecutionResult,
    TaskCancelledInterrupt,
    execute_fn,
)
from tpu_faas.core.serialize import serialize
from tpu_faas.core.task import TaskStatus
from tpu_faas.obs import REGISTRY
from tpu_faas.utils.logging import get_logger, log_ctx

log = get_logger("worker.pool")

#: Process-wide pool counters (the worker process's share of the unified
#: metric catalog): every drained result by terminal status, plus the
#: misfire repairs — the one at-least-once execution in the system — as a
#: first-class series instead of a buried log line.
_TASKS_TOTAL = REGISTRY.counter(
    "tpu_faas_worker_pool_tasks_total",
    "Results drained from this process's task pools, by terminal status",
    ("status",),
)
_MISFIRES_TOTAL = REGISTRY.counter(
    "tpu_faas_worker_pool_misfires_total",
    "Cancel interrupts that landed on a bystander task and were repaired "
    "by resubmission (at-least-once executions)",
)

#: Parent-side payload-cache counters, shared by both worker kinds (they
#: both import the pool): the operator-visible proof that steady state
#: ships digests, not bodies.
FN_CACHE_HITS = REGISTRY.counter(
    "tpu_faas_worker_fn_cache_hits_total",
    "Digest-shipped TASKs resolved from the worker's payload cache",
)
FN_CACHE_MISSES = REGISTRY.counter(
    "tpu_faas_worker_fn_cache_misses_total",
    "Digest-shipped TASKs that needed a BLOB_MISS/BLOB_FILL round",
)

#: child-side: the task id currently executing in THIS child (None between
#: tasks) — consulted by the SIGUSR1 handler, plain memory only (a signal
#: handler must never do IPC)
_CURRENT_TASK: str | None = None
#: child-side: the start/end event queue back to the parent
_EVENTS = None


def _on_cancel_signal(signum, frame):
    global _CURRENT_TASK
    tid = _CURRENT_TASK
    if tid is not None:
        # close the window BEFORE raising: a duplicate signal (client
        # retry, two relays racing) landing while the first interrupt is
        # still unwinding must no-op — a raise inside _run_reported's
        # except block would escape as the future's exception and turn a
        # deliberate CANCELLED into a spurious FAILED
        _CURRENT_TASK = None
        raise TaskCancelledInterrupt(f"task {tid} force-cancelled mid-run")


def _child_init(events) -> None:
    """Pool-child initializer: stash the event queue, install the cancel
    handler (main thread of the child; mirrors the SIGALRM arming in
    execute_fn)."""
    global _EVENTS
    _EVENTS = events
    if hasattr(_signal, "SIGUSR1"):
        _signal.signal(_signal.SIGUSR1, _on_cancel_signal)


def _run_reported(
    task_id: str,
    ser_fn: str,
    ser_params: str,
    timeout: float | None,
    fn_digest: str | None = None,
) -> ExecutionResult:
    """execute_fn wrapped with start/end reporting + the cancel window.

    The WHOLE window — from opening `_CURRENT_TASK` through execute_fn's
    return — sits inside one try, so an interrupt can never escape as the
    future's exception (that would report FAILED, leak the child's window
    permanently open, and let the next stray signal kill the executor's
    worker loop). `_CURRENT_TASK` is set before the start event ships: a
    deferred interrupt fired on seeing that event must find the window
    open. An interrupt landing AFTER execute_fn returned keeps the real
    result — the task beat the signal, and discarding a computed
    COMPLETED for a raced CANCELLED would break the documented force-
    cancel contract."""
    global _CURRENT_TASK
    res: ExecutionResult | None = None
    end_sent = False
    try:
        try:
            _CURRENT_TASK = task_id
            if _EVENTS is not None:
                _EVENTS.put(("start", task_id, os.getpid()))
            # interrupts DURING the call are handled inside execute_fn
            # itself (its except clauses return a CANCELLED result)
            res = execute_fn(task_id, ser_fn, ser_params, timeout, fn_digest)
        except TaskCancelledInterrupt as exc:
            if res is None:
                # landed before execute_fn produced anything: a pre-start
                # cancel (the handler already closed the window)
                res = ExecutionResult(
                    task_id, str(TaskStatus.CANCELLED), serialize(exc)
                )
        finally:
            _CURRENT_TASK = None
            if _EVENTS is not None:
                _EVENTS.put(("end", task_id, 0))
                end_sent = True
    except TaskCancelledInterrupt as exc:
        # the signal landed in the sliver between the try body completing
        # and the finally's window close — the handler cleared the window
        # before raising, so no further interrupt can arrive; keep the
        # real result if one exists (the task beat the signal) and make
        # sure the end event still ships
        if res is None:
            res = ExecutionResult(
                task_id, str(TaskStatus.CANCELLED), serialize(exc)
            )
        if _EVENTS is not None and not end_sent:
            _EVENTS.put(("end", task_id, 0))
    return res


def _warm() -> None:
    """No-op run in each child to force its spawn (must be module-level to
    pickle)."""


class TaskPool:
    def __init__(self, num_processes: int) -> None:
        self.num_processes = num_processes
        self._done: queue.Queue[tuple[str, Future]] = queue.Queue()
        self._busy = 0
        #: parent-side mirror of the children's start/end events:
        #: task_id -> child pid, maintained by _drain_events
        self._running_pids: dict[str, int] = {}
        #: in-flight bookkeeping for force-cancel: the future (so a task
        #: still queued in the executor can be cancelled without a signal),
        #: the submitted payloads (so a misfired interrupt can resubmit),
        #: and which tasks a cancel was actually requested for
        self._futures: dict[str, Future] = {}
        self._args: dict[str, tuple[str, str, float | None, str | None]] = {}
        self._want_cancel: set[str] = set()
        #: cancels for tasks sitting in the executor's CALL QUEUE (future
        #: no longer .cancel()-able, child not started): the interrupt is
        #: deferred until the task's start event arrives
        self._deferred_kill: set[str] = set()
        #: cumulative misfire repairs (a cancel interrupt that landed on a
        #: bystander task, repaired by resubmission — the one at-least-once
        #: execution in the system). Surfaced by the workers on their
        #: RESULT messages and aggregated into dispatcher /stats, so
        #: doubled side effects are operator-visible without log scraping.
        self.n_misfires = 0
        self._executor = self._make()

    def _make(self) -> ProcessPoolExecutor:
        ctx = mp.get_context("forkserver")
        self._events = ctx.SimpleQueue()
        self._running_pids.clear()
        return ProcessPoolExecutor(
            max_workers=self.num_processes,
            mp_context=ctx,
            initializer=_child_init,
            initargs=(self._events,),
        )

    def _drain_events(self) -> None:
        while not self._events.empty():
            kind, tid, pid = self._events.get()
            if kind == "start":
                self._running_pids[tid] = pid
                if tid in self._deferred_kill:
                    # a cancel arrived while this task sat in the call
                    # queue: interrupt it the moment it starts (the child
                    # opens its cancel window BEFORE shipping this event)
                    self._deferred_kill.discard(tid)
                    try:
                        os.kill(pid, _signal.SIGUSR1)
                    except (ProcessLookupError, PermissionError):
                        pass
            else:
                self._running_pids.pop(tid, None)

    def cancel(self, task_id: str) -> bool:
        """Best-effort force-cancel of ``task_id``. True when the task will
        surface as a CANCELLED result from :meth:`drain` — either its
        future was still queued in the executor (cancelled without a
        signal) or an interrupt was sent to the child the event stream
        says runs it. False when it is not held here (finished, shipped,
        or never seen). The event stream lags reality by design, so an
        interrupt CAN land on a child that already switched tasks; drain()
        repairs such misfires internally by resubmitting the wrongly
        interrupted task — see the module docstring."""
        fut = self._futures.get(task_id)
        if fut is not None and fut.cancel():
            # never handed to a child: the done-callback queues the
            # cancelled future and drain() reports terminal CANCELLED
            self._want_cancel.add(task_id)
            return True
        if not hasattr(_signal, "SIGUSR1"):
            return False
        self._drain_events()
        pid = self._running_pids.get(task_id)
        if pid is None:
            if fut is not None and not fut.done():
                # in the executor's call queue: no child to signal yet —
                # defer the interrupt to the task's start event
                self._deferred_kill.add(task_id)
                self._want_cancel.add(task_id)
                return True
            return False
        try:
            os.kill(pid, _signal.SIGUSR1)
        except (ProcessLookupError, PermissionError):
            return False
        self._want_cancel.add(task_id)
        return True

    @property
    def busy(self) -> int:
        return self._busy

    @property
    def free(self) -> int:
        return self.num_processes - self._busy

    def warmup(self, timeout: float = 120.0) -> None:
        """Force the lazy child-process spawn NOW, off the serving path.

        The executor spawns children on first submit; with forkserver that
        first submit blocks for seconds (forkserver boot + module re-import).
        A worker that pays this inside its serving loop goes heartbeat-silent
        long enough to be falsely purged — so workers warm up BEFORE
        registering with the dispatcher."""
        wait(
            [self._executor.submit(_warm) for _ in range(self.num_processes)],
            timeout=timeout,
        )

    def submit(
        self,
        task_id: str,
        fn_payload: str,
        param_payload: str,
        timeout: float | None = None,
        fn_digest: str | None = None,
    ) -> None:
        """``fn_digest`` (payload plane): content digest of ``fn_payload``,
        keying the child-side deserialized-function cache so a repeated
        function pays dill decode once per child, not once per task."""
        try:
            fut = self._executor.submit(
                _run_reported, task_id, fn_payload, param_payload, timeout,
                fn_digest,
            )
        except BrokenProcessPool:
            self._executor.shutdown(wait=False, cancel_futures=True)
            self._executor = self._make()
            fut = self._executor.submit(
                _run_reported, task_id, fn_payload, param_payload, timeout,
                fn_digest,
            )
        fut.add_done_callback(lambda f, tid=task_id: self._done.put((tid, f)))
        self._futures[task_id] = fut
        self._args[task_id] = (fn_payload, param_payload, timeout, fn_digest)
        self._busy += 1

    def drain(self) -> list[ExecutionResult]:
        """Non-blocking: collect all finished results. Force-cancel
        semantics live here: a cancelled-before-start future becomes a
        terminal CANCELLED result; a CANCELLED result nobody requested (an
        interrupt that landed after its child switched tasks) is repaired
        by resubmitting the task instead of delivering — the wrongly
        interrupted run reported nothing externally, so the re-execution
        is invisible to every consumer."""
        self._drain_events()  # keep the task->pid mirror bounded + fresh
        out: list[ExecutionResult] = []
        while True:
            try:
                task_id, fut = self._done.get_nowait()
            except queue.Empty:
                return out
            self._busy -= 1
            self._futures.pop(task_id, None)
            self._deferred_kill.discard(task_id)
            args = self._args.pop(task_id, None)
            wanted = task_id in self._want_cancel
            self._want_cancel.discard(task_id)
            if fut.cancelled():
                if wanted:
                    # deliberate pre-start cancel: terminal CANCELLED
                    _TASKS_TOTAL.labels(status=str(TaskStatus.CANCELLED)).inc()
                    out.append(
                        ExecutionResult(
                            task_id,
                            str(TaskStatus.CANCELLED),
                            serialize(
                                TaskCancelledInterrupt(
                                    f"task {task_id} cancelled before start"
                                )
                            ),
                        )
                    )
                    continue
                # future cancelled by a broken-pool rebuild: .exception()
                # would RAISE CancelledError; report the task as FAILED
                exc: BaseException | None = RuntimeError(
                    "task cancelled: worker pool died and was rebuilt"
                )
            else:
                exc = fut.exception()
            if exc is None:
                res: ExecutionResult = fut.result()
                if (
                    res.status == str(TaskStatus.CANCELLED)
                    and not wanted
                    and args is not None
                ):
                    # misfire: the interrupt landed on this task after its
                    # child switched away from the intended one — re-run
                    # it. Logged: this is the one at-least-once execution
                    # in the system, and an operator chasing doubled side
                    # effects needs the trace.
                    log.warning(
                        "misfired cancel interrupt hit task %s; "
                        "resubmitting it", task_id,
                        extra=log_ctx(task_id=task_id),
                    )
                    self.n_misfires += 1
                    _MISFIRES_TOTAL.inc()
                    self.submit(task_id, *args)
                    continue
                _TASKS_TOTAL.labels(status=res.status).inc()
                out.append(res)
            else:
                _TASKS_TOTAL.labels(status=str(TaskStatus.FAILED)).inc()
                out.append(
                    ExecutionResult(
                        task_id,
                        str(TaskStatus.FAILED),
                        serialize(RuntimeError(str(exc))),
                    )
                )

    def close(self) -> None:
        self._executor.shutdown(wait=False, cancel_futures=True)
