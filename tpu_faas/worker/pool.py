"""Worker-side process pool with broken-pool recovery.

Wraps a ProcessPoolExecutor (forkserver context) around `execute_fn` with the
same failure semantics the local dispatcher has: a child killed by user code
surfaces as a FAILED result for that task and the pool is rebuilt, instead of
the reference's silent slot leak (its workers count busy slots in the parent
and a vanished child never decrements, pull_worker.py:63-72).
"""

from __future__ import annotations

import multiprocessing as mp
import queue
from concurrent.futures import Future, ProcessPoolExecutor, wait
from concurrent.futures.process import BrokenProcessPool

from tpu_faas.core.executor import ExecutionResult, execute_fn
from tpu_faas.core.serialize import serialize
from tpu_faas.core.task import TaskStatus


def _warm() -> None:
    """No-op run in each child to force its spawn (must be module-level to
    pickle)."""


class TaskPool:
    def __init__(self, num_processes: int) -> None:
        self.num_processes = num_processes
        self._done: queue.Queue[tuple[str, Future]] = queue.Queue()
        self._busy = 0
        self._executor = self._make()

    def _make(self) -> ProcessPoolExecutor:
        return ProcessPoolExecutor(
            max_workers=self.num_processes,
            mp_context=mp.get_context("forkserver"),
        )

    @property
    def busy(self) -> int:
        return self._busy

    @property
    def free(self) -> int:
        return self.num_processes - self._busy

    def warmup(self, timeout: float = 120.0) -> None:
        """Force the lazy child-process spawn NOW, off the serving path.

        The executor spawns children on first submit; with forkserver that
        first submit blocks for seconds (forkserver boot + module re-import).
        A worker that pays this inside its serving loop goes heartbeat-silent
        long enough to be falsely purged — so workers warm up BEFORE
        registering with the dispatcher."""
        wait(
            [self._executor.submit(_warm) for _ in range(self.num_processes)],
            timeout=timeout,
        )

    def submit(
        self,
        task_id: str,
        fn_payload: str,
        param_payload: str,
        timeout: float | None = None,
    ) -> None:
        try:
            fut = self._executor.submit(
                execute_fn, task_id, fn_payload, param_payload, timeout
            )
        except BrokenProcessPool:
            self._executor.shutdown(wait=False, cancel_futures=True)
            self._executor = self._make()
            fut = self._executor.submit(
                execute_fn, task_id, fn_payload, param_payload, timeout
            )
        fut.add_done_callback(lambda f, tid=task_id: self._done.put((tid, f)))
        self._busy += 1

    def drain(self) -> list[ExecutionResult]:
        """Non-blocking: collect all finished results."""
        out: list[ExecutionResult] = []
        while True:
            try:
                task_id, fut = self._done.get_nowait()
            except queue.Empty:
                return out
            self._busy -= 1
            if fut.cancelled():
                # future cancelled by a broken-pool rebuild: .exception()
                # would RAISE CancelledError; report the task as FAILED
                exc: BaseException | None = RuntimeError(
                    "task cancelled: worker pool died and was rebuilt"
                )
            else:
                exc = fut.exception()
            if exc is None:
                out.append(fut.result())
            else:
                out.append(
                    ExecutionResult(
                        task_id,
                        str(TaskStatus.FAILED),
                        serialize(RuntimeError(str(exc))),
                    )
                )

    def close(self) -> None:
        self._executor.shutdown(wait=False, cancel_futures=True)
