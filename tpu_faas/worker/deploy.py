"""Worker-fleet deployer: spawn, supervise, and cleanly stop N worker nodes.

The reference kept a throwaway multi-worker launcher with SIGINT cleanup in
its scrap heap (reference old/deploy_workers.py:9-108, including an inverted
``--nh`` flag at :34 — not reproduced); this is the production version:

- spawns N worker subprocesses (push or pull protocol) against one
  dispatcher URL;
- optional supervision (``--restart``): a worker that *crashes* is respawned
  after a short backoff — combined with heartbeat purge + in-flight
  re-dispatch on the dispatcher side this gives the fleet self-healing the
  reference lacks (its dead workers stay dead, SURVEY §5.3);
- SIGTERM/SIGINT forward a graceful drain to every worker (deregister,
  finish in-flight, exit 0 — worker/drain.py) and wait; workers that ignore
  the drain are killed after ``--stop-grace`` seconds. A worker that exits 0
  on its own (e.g. drained by an operator) is NOT respawned;
- optional queue-driven autoscaling (``--stats-url`` + ``--min``/``--max``):
  the fleet grows one node per decision while the dispatcher reports
  pending work and gracefully drains a node after a sustained quiet period
  (:class:`AutoScaler`).

Usage::

    python -m tpu_faas.worker.deploy 4 2 tcp://host:5555 --hb --restart \
        --stats-url http://127.0.0.1:9100/stats --min 2 --max 16
"""

from __future__ import annotations

import argparse
import hashlib
import math
import os
import signal
import socket as _socket
import subprocess
import sys
import time

from tpu_faas.utils.logging import get_logger

log = get_logger("worker.deploy")


def fleet_id(dispatcher_url: str) -> str:
    """Short stable id of the fleet a supervisor serves, derived from its
    dispatcher URL. Namespaces the durable worker tokens: two supervisors
    on ONE host serving DIFFERENT dispatchers used to mint identical
    hostname/slot tokens, merging their workers' speed grades in the
    estimator (ADVICE r5) — a machine can be fast for one fleet's
    workload and slow for another's."""
    return hashlib.blake2b(
        dispatcher_url.encode("utf-8", "replace"), digest_size=4
    ).hexdigest()


class WorkerFleet:
    """Owns N worker subprocesses. Not thread-safe; drive from one thread."""

    def __init__(
        self,
        n_workers: int,
        num_processes: int,
        dispatcher_url: str,
        protocol: str = "push",
        heartbeat: bool = False,
        hb_period: float = 1.0,
        delay: float = 0.01,
        restart: bool = False,
        restart_backoff: float = 1.0,
        stop_grace: float = 10.0,
    ) -> None:
        if protocol not in ("push", "pull"):
            raise ValueError(f"unknown protocol {protocol!r}")
        self.n_workers = n_workers
        self.num_processes = num_processes
        self.dispatcher_url = dispatcher_url
        self.protocol = protocol
        self.heartbeat = heartbeat
        self.hb_period = hb_period
        self.delay = delay
        self.restart = restart
        self.restart_backoff = restart_backoff
        self.stop_grace = stop_grace
        self.procs: list[subprocess.Popen | None] = [None] * n_workers
        self.restarts = 0
        self._stopping = False
        #: slot -> monotonic time when its crashed worker may respawn;
        #: non-blocking backoff, so shutdown never waits behind N sleeps
        self._respawn_at: dict[int, float] = {}
        #: slot -> drain deadline (scale_down escalation bookkeeping)
        self._draining: dict[int, float] = {}

    def _command(self, slot: int) -> list[str]:
        mod = f"tpu_faas.worker.{self.protocol}_worker"
        cmd = [sys.executable, "-m", mod, str(self.num_processes), self.dispatcher_url]
        if self.protocol == "push":
            if self.heartbeat:
                cmd += ["--hb", "--hb-period", str(self.hb_period)]
            # host-stable identity: a respawned worker — whether the crash
            # was the worker's OR the whole supervisor's — re-registers
            # under the SAME token, so the estimator's learned speed for
            # this machine slot survives (sched/estimator.py worker
            # grades) instead of relearning from the 1.0 prior. The fleet
            # id (hash of the dispatcher URL) keeps two supervisors on one
            # host from minting colliding tokens and merging grades.
            cmd += [
                "--token",
                f"{_socket.gethostname()}-{fleet_id(self.dispatcher_url)}"
                f"-{self.protocol}{self.num_processes}-slot{slot}",
            ]
        else:
            cmd += ["--delay", str(self.delay)]
        return cmd

    def _spawn(self, slot: int) -> subprocess.Popen:
        # own process group per worker: its pool children + mp helper
        # processes can all be reaped with one killpg if it crashes (a bare
        # SIGKILL on the leader orphans them to pid 1, where they pile up)
        p = subprocess.Popen(
            self._command(slot), cwd=os.getcwd(), start_new_session=True
        )
        log.info("worker[%d] pid %d: %s", slot, p.pid, " ".join(self._command(slot)))
        self.procs[slot] = p
        return p

    @staticmethod
    def _killpg(p: subprocess.Popen) -> None:
        """SIGKILL a worker's whole process group (children + helpers); the
        group persists while any member lives, even after the leader died."""
        try:
            os.killpg(p.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            if p.poll() is None:
                p.kill()

    def start(self) -> None:
        for i in range(self.n_workers):
            self._spawn(i)

    # -- elastic sizing (used by AutoScaler) --------------------------------
    def scale_up(self) -> int:
        """Add one worker node NOW; returns its slot index. Reuses a free
        slot if one exists, else grows the table."""
        for i, p in enumerate(self.procs):
            if p is None and i not in self._respawn_at:
                self._spawn(i)
                return i
        self.procs.append(None)
        slot = len(self.procs) - 1
        self._spawn(slot)
        return slot

    def scale_down(self) -> int | None:
        """Gracefully drain one worker (SIGTERM -> deregister + finish
        in-flight + exit 0, which poll() does NOT respawn). Returns the
        drained slot, or None if nothing (new) could be drained.

        Slots already draining are skipped — re-terminating the same
        wedged worker forever would both block further shrink and inflate
        the caller's counters — and a drain that outlives ``stop_grace``
        escalates to a group kill."""
        now = time.monotonic()
        for slot, deadline in list(self._draining.items()):
            p = self.procs[slot] if slot < len(self.procs) else None
            if p is None or p.poll() is not None:
                del self._draining[slot]  # exited; poll() reaps it
            elif now >= deadline:
                log.warning(
                    "scale-down: worker[%d] ignored drain; killing", slot
                )
                self._killpg(p)
                del self._draining[slot]
        for i in range(len(self.procs) - 1, -1, -1):
            p = self.procs[i]
            if p is not None and p.poll() is None and i not in self._draining:
                p.terminate()
                self._draining[i] = now + self.stop_grace
                log.info("scale-down: draining worker[%d] pid %d", i, p.pid)
                return i
        return None

    def poll(self) -> int:
        """Reap exited workers; respawn crashed ones (after their backoff)
        when supervising. Returns the number of currently-live workers."""
        now = time.monotonic()
        for slot in list(self._respawn_at):
            if self._stopping or not self.restart:
                del self._respawn_at[slot]
            elif now >= self._respawn_at[slot]:
                del self._respawn_at[slot]
                self.restarts += 1
                self._spawn(slot)
        live = 0
        for i, p in enumerate(self.procs):
            if p is None:
                continue
            rc = p.poll()
            if rc is None:
                live += 1
                continue
            self.procs[i] = None
            if rc != 0:
                # ANY crash (supervised or not) reaps the dead leader's
                # orphaned pool children/helpers — the pid-1 pile-up this
                # module exists to prevent; a clean exit (rc=0) drained its
                # own pool and needs no group kill
                self._killpg(p)
            if rc == 0 or self._stopping or not self.restart:
                # clean exit (operator drained it) or shutdown: don't revive
                log.info("worker[%d] exited rc=%d", i, rc)
                continue
            log.warning(
                "worker[%d] crashed rc=%d; respawning in %.1fs",
                i, rc, self.restart_backoff,
            )
            self._respawn_at[i] = now + self.restart_backoff
        return live

    def stop(self) -> None:
        """Graceful drain: SIGTERM everyone (workers deregister + finish
        in-flight), wait up to stop_grace, then SIGKILL stragglers."""
        self._stopping = True
        self._respawn_at.clear()
        for p in self.procs:
            if p is not None and p.poll() is None:
                p.terminate()
        deadline = time.monotonic() + self.stop_grace
        for p in self.procs:
            if p is None:
                continue
            remaining = max(0.0, deadline - time.monotonic())
            try:
                p.wait(timeout=remaining)
            except subprocess.TimeoutExpired:
                log.warning("worker pid %d ignored drain; killing", p.pid)
                self._killpg(p)
                p.wait()
            else:
                if p.returncode != 0:
                    # leader died before the drain without cleaning up:
                    # reap its surviving group members too (the timeout
                    # branch above already group-killed)
                    self._killpg(p)
        self.procs = [None] * len(self.procs)

    @property
    def n_live(self) -> int:
        return sum(1 for p in self.procs if p is not None and p.poll() is None)


class AutoScaler:
    """Queue-driven elastic sizing on top of a :class:`WorkerFleet`.

    Policy (deliberately simple and oscillation-resistant):

    - scale UP when the dispatcher reports pending work (``pending > 0``)
      and the fleet is below ``max_workers`` — the backlog signal already
      accounts for free capacity, because the dispatcher drains pending
      into free slots before stats are read. One node per decision by
      default; when the dispatcher also reports ``backlog_est_s`` (the
      estimator's learned-runtime drain time, tpu-push
      ``_backlog_estimate_s``), enough nodes to drain the backlog within
      ``drain_target_s`` are added at once — a 10-minute estimated backlog
      should not grow the fleet one node per polling period;
    - scale DOWN one node after ``idle_decisions`` consecutive observations
      of a completely quiet system (no pending, nothing in flight) while
      above ``min_workers`` — draining is graceful (SIGTERM), so shrink
      never kills running work.

    ``step(stats)`` takes the dispatcher's ``/stats`` JSON (see
    ``TaskDispatcher.serve_stats``) and returns "up", "down", or None, so
    the policy is unit-testable without HTTP; the CLI feeds it from
    ``--stats-url`` each supervision loop.
    """

    def __init__(
        self,
        fleet: WorkerFleet,
        min_workers: int,
        max_workers: int,
        idle_decisions: int = 5,
        drain_target_s: float = 30.0,
    ) -> None:
        if not 0 < min_workers <= max_workers:
            raise ValueError("need 0 < min_workers <= max_workers")
        self.fleet = fleet
        self.min_workers = min_workers
        self.max_workers = max_workers
        self.idle_decisions = idle_decisions
        #: aim to drain a reported learned-runtime backlog within this many
        #: seconds; only engages when the dispatcher serves backlog_est_s
        self.drain_target_s = float(drain_target_s)
        self._idle_streak = 0
        self._warned_no_queue_stats = False
        self.scale_ups = 0
        self.scale_downs = 0

    def step(self, stats: dict) -> str | None:
        if "pending" not in stats or "inflight" not in stats:
            # stats from a dispatcher that doesn't report queue depth (the
            # classic push/pull modes serve only the base dict): treating
            # absent as 0 would read a loaded fleet as idle and drain it —
            # refuse to decide instead
            if not self._warned_no_queue_stats:
                self._warned_no_queue_stats = True
                log.warning(
                    "stats endpoint reports no pending/inflight (not a "
                    "tpu-push dispatcher?); autoscaling is inert"
                )
            return None
        live = self.fleet.n_live
        pending = int(stats.get("pending", 0))
        inflight = int(stats.get("inflight", 0))
        if live < self.min_workers:
            # enforce the floor even while idle (a crashed worker without
            # --restart must not leave the fleet below --min forever)
            self.fleet.scale_up()
            self.scale_ups += 1
            log.info("autoscale floor: live=%d->%d", live, live + 1)
            return "up"
        if pending > 0:
            self._idle_streak = 0
            if live < self.max_workers:
                # learned-runtime sizing: add enough nodes to drain the
                # estimated backlog within drain_target_s, one node when
                # the dispatcher reports no estimate (estimator off /
                # nothing learned). The desired TOTAL is computed from the
                # dispatcher's REGISTERED worker count — backlog_est_s is
                # measured against registered capacity, while `live`
                # counts locally-spawned processes that may not have
                # registered yet; sizing against `live` would re-multiply
                # an already-grown fleet every decision period until the
                # new nodes register (spawn+register > scale-period jumps
                # straight to max)
                backlog_s = stats.get("backlog_est_s")
                reg = stats.get("workers_registered")
                n_up = 1
                if (
                    isinstance(backlog_s, (int, float))
                    and backlog_s > self.drain_target_s
                    and isinstance(reg, int)
                    and reg > 0
                ):
                    want_total = math.ceil(
                        reg * backlog_s / self.drain_target_s
                    )
                    # current capacity = max(registered, locally spawned):
                    # reg < live while local spawns are still registering
                    # (don't re-count them); reg > live when workers
                    # OUTSIDE this supervisor are registered (don't spawn
                    # the whole cluster's shortfall locally on top of them)
                    n_up = want_total - max(reg, live)
                n_up = min(n_up, self.max_workers - live)
                if n_up <= 0:
                    # provisioned ahead of the (stale) backlog estimate:
                    # wait for the spawned nodes to register
                    return None
                for _ in range(n_up):
                    self.fleet.scale_up()
                self.scale_ups += n_up
                log.info(
                    "autoscale up: pending=%d backlog_est=%s live=%d->%d",
                    pending, stats.get("backlog_est_s"), live, live + n_up,
                )
                return "up"
            return None
        if inflight > 0:
            self._idle_streak = 0
            return None
        self._idle_streak += 1
        if self._idle_streak >= self.idle_decisions and live > self.min_workers:
            self._idle_streak = 0
            if self.fleet.scale_down() is not None:
                self.scale_downs += 1
                log.info("autoscale down: idle, live=%d->%d", live, live - 1)
                return "down"
        return None


def _fetch_stats(url: str) -> dict | None:
    """GET the dispatcher stats JSON; None on any failure (the autoscaler
    simply skips that decision — a dispatcher restart must not kill the
    supervisor)."""
    import json
    import urllib.request

    try:
        with urllib.request.urlopen(url, timeout=2.0) as r:
            return json.loads(r.read())
    except Exception:
        return None


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(description="tpu-faas worker fleet deployer")
    ap.add_argument("n_workers", type=int, help="worker nodes to spawn")
    ap.add_argument("num_processes", type=int, help="pool size per worker")
    ap.add_argument("dispatcher_url", help="tcp://host:port of the dispatcher")
    ap.add_argument("--protocol", choices=["push", "pull"], default="push")
    ap.add_argument("--hb", action="store_true", help="push: heartbeats on")
    ap.add_argument("--hb-period", type=float, default=1.0)
    ap.add_argument("--delay", type=float, default=0.01, help="pull pacing")
    ap.add_argument(
        "--restart", action="store_true",
        help="respawn crashed (non-zero-exit) workers",
    )
    ap.add_argument("--restart-backoff", type=float, default=1.0)
    ap.add_argument("--stop-grace", type=float, default=10.0)
    ap.add_argument(
        "--stats-url",
        help="dispatcher stats endpoint (http://host:port/stats) — enables "
        "queue-driven autoscaling between --min and --max workers",
    )
    ap.add_argument("--min", type=int, default=None, help="autoscale floor")
    ap.add_argument("--max", type=int, default=None, help="autoscale ceiling")
    ap.add_argument(
        "--scale-period", type=float, default=2.0,
        help="seconds between autoscale decisions",
    )
    ap.add_argument(
        "--drain-target", type=float, default=30.0,
        help="autoscale sizing goal: drain the dispatcher's learned-"
        "runtime backlog estimate within this many seconds (engages only "
        "when the stats report backlog_est_s)",
    )
    ns = ap.parse_args(argv)

    fleet = WorkerFleet(
        ns.n_workers,
        ns.num_processes,
        ns.dispatcher_url,
        protocol=ns.protocol,
        heartbeat=ns.hb,
        hb_period=ns.hb_period,
        delay=ns.delay,
        restart=ns.restart,
        restart_backoff=ns.restart_backoff,
        stop_grace=ns.stop_grace,
    )

    stop_requested = False

    def on_signal(signum, frame):
        nonlocal stop_requested
        stop_requested = True
        # a foreground Ctrl-C delivers SIGINT to the whole process group:
        # the workers die with rc!=0 at the same instant, and a poll() racing
        # this handler must treat those as shutdown, not crashes to respawn
        fleet._stopping = True

    signal.signal(signal.SIGTERM, on_signal)
    signal.signal(signal.SIGINT, on_signal)

    scaler = None
    if ns.stats_url:
        scaler = AutoScaler(
            fleet,
            min_workers=ns.min if ns.min is not None else ns.n_workers,
            max_workers=ns.max if ns.max is not None else ns.n_workers * 4,
            drain_target_s=ns.drain_target,
        )

    fleet.start()
    log.info(
        "%d %s workers x %d processes -> %s (restart=%s, autoscale=%s)",
        ns.n_workers, ns.protocol, ns.num_processes, ns.dispatcher_url,
        ns.restart, bool(scaler),
    )
    last_scale = 0.0
    try:
        while not stop_requested:
            live = fleet.poll()
            if live == 0 and not ns.restart and scaler is None:
                log.info("all workers exited; deployer done")
                return
            if scaler is not None and time.monotonic() - last_scale >= ns.scale_period:
                stats = _fetch_stats(ns.stats_url)
                if stats is not None:
                    scaler.step(stats)
                last_scale = time.monotonic()
            time.sleep(0.2)
    finally:
        log.info("draining fleet (%d live)", fleet.n_live)
        fleet.stop()


if __name__ == "__main__":
    main()
