"""Pull worker: REQ socket + local process pool.

Capability parity with reference PullWorker (pull_worker.py:10-123): register
with the dispatcher, then loop — pace by ``delay`` (load-bearing for REP/REQ
fairness across many workers, reference :131-132), ask for work when a pool
slot is free, ship finished results. Every request is answered with ``task``
or ``wait`` (the REP/REQ lockstep), and a reply to a ``result`` message may
itself carry the next task, so a busy fleet never wastes a round trip
(the reference's inline re-listen trick, pull_worker.py:108-111, made
structural here: every transaction handles its reply uniformly).

CLI: ``python -m tpu_faas.worker.pull_worker N tcp://host:port [--delay s]``
(reference pull_worker.py:126-137).
"""

from __future__ import annotations

import argparse
import time
import uuid

import zmq

from tpu_faas.core.payload import PayloadLRU, payload_digest
from tpu_faas.core.serialize import serialize
from tpu_faas.core.task import TaskStatus
from tpu_faas.utils.backoff import BackoffPolicy
from tpu_faas.utils.logging import get_logger, log_ctx
from tpu_faas.worker import messages as m
from tpu_faas.worker.pool import (
    FN_CACHE_HITS,
    FN_CACHE_MISSES,
    RESULT_CACHE_HITS,
    RESULT_CACHE_MISSES,
    TaskPool,
)

log = get_logger("pull_worker")

#: Blob-fetch retry schedule: gentle growth capped at 1 s — the loop is
#: also this worker's liveness traffic during an outage, so sleeps must
#: stay short enough that request-stamped last_seen never ages past tte.
_BLOB_BACKOFF = BackoffPolicy(floor_s=0.2, factor=1.5, cap_s=1.0)


class PullWorker:
    def __init__(
        self,
        num_processes: int,
        dispatcher_url: str,
        delay: float = 0.01,
        recv_timeout_ms: int = 10_000,
        keepalive_period: float = 1.0,
        caps: tuple[str, ...] = m.WORKER_CAPS,
        fn_cache_bytes: int = 256 * 1024 * 1024,
        result_cache_bytes: int = 256 * 1024 * 1024,
    ) -> None:
        self.worker_id = str(uuid.uuid4())
        #: max silence while saturated before sending a WAIT-bound keepalive
        #: (must be well under the dispatcher's time_to_expire)
        self.keepalive_period = keepalive_period
        self.num_processes = num_processes
        self.delay = delay
        #: payload-plane capabilities advertised on REGISTER; () = pure
        #: reference contract
        self.caps: tuple[str, ...] = tuple(caps)
        #: digest -> serialized body (parent-side codec cache; REQ/REP
        #: resolves misses synchronously with a BLOB_MISS transaction)
        self.fn_cache = PayloadLRU(fn_cache_bytes)
        #: digest -> serialized RESULT body (result-blob plane): this
        #: worker's own digest-shipped results plus dep-digest fills.
        #: REQ/REP resolves result-digest misses synchronously, exactly
        #: like fn blobs — there is no reverse-pull lane on this transport
        #: (the dispatcher can only answer, never ask).
        self.result_cache = PayloadLRU(result_cache_bytes)
        #: task_id -> rblob_min from that task's TASK reply (per-task
        #: digest-ship permission + threshold)
        self._task_rblob: dict[str, int] = {}
        #: True after the dispatcher's first binary reply — sends switch
        self._peer_bin = False
        #: task_id -> distributed trace id (TASK ``trace_id``): stamped
        #: into logs and echoed on the matching RESULT
        self._task_trace: dict[str, str] = {}
        self.pool = TaskPool(num_processes)
        self.ctx = zmq.Context.instance()
        self.socket = self.ctx.socket(zmq.REQ)
        self.socket.setsockopt(zmq.RCVTIMEO, recv_timeout_ms)
        self.socket.setsockopt(zmq.LINGER, 0)
        # survive a dropped reply (dispatcher restart) without wedging the
        # REQ state machine
        self.socket.setsockopt(zmq.REQ_RELAXED, 1)
        self.socket.setsockopt(zmq.REQ_CORRELATE, 1)
        self.socket.connect(dispatcher_url)
        self._stopping = False
        self._draining = False
        #: fault-injection seams (tpu_faas/chaos), None when
        #: TPU_FAAS_CHAOS is unset. The REQ/REP lockstep constrains the
        #: wire seam: only wire.delay is expressible here (as a blocking
        #: sleep before the request) — drop would wedge the mandatory
        #: recv, dup would desync reply correlation.
        from tpu_faas import chaos as _chaos

        _plan = _chaos.from_env()
        self._chaos_wire = _plan.wire() if _plan is not None else None
        self._chaos_exec = _plan.execution() if _plan is not None else None

    def stop(self) -> None:
        self._stopping = True

    def drain(self) -> None:
        """Graceful shutdown: stop asking for work (and flag result messages
        ``no_task`` so their mandatory replies are WAIT, never a new task),
        ship what's in flight, then exit."""
        self._draining = True

    # -- one REQ/REP transaction ------------------------------------------
    def _transact(self, msg_type: str, **data: object) -> tuple[str, dict]:
        """Send one message, receive the mandatory reply, and if the reply
        carries a task, put it on the pool. Force-cancels ride the reply
        too (``cancel_ids``): a pull worker cannot be pushed to, so the
        dispatcher piggy-backs kill requests for tasks THIS worker runs on
        whatever reply goes out next — TASK or WAIT. Returns the reply."""
        payload = m.encode_for(self._peer_bin, msg_type, **data)
        if self._chaos_wire is not None:
            # lockstep socket: delay-as-sleep only (see __init__)
            self._chaos_wire.send(
                payload, self.socket.send,
                dup_ok=False, defer_ok=False, drop_ok=False,
            )
        else:
            self.socket.send(payload)
        raw = self.socket.recv()
        if not self._peer_bin and m.is_binary(raw):
            self._peer_bin = True  # binary negotiation complete
        reply_type, reply = m.decode(raw)
        for tid in reply.get("cancel_ids", ()):
            if self.pool.cancel(tid):
                log.info(
                    "force-cancelling task %s", tid,
                    extra={"task_id": tid, "worker_id": self.worker_id},
                )
        if reply_type == m.TASK:
            self._submit_task(reply)
        # WAIT: nothing to do
        return reply_type, reply

    def _submit_task(self, reply: dict) -> None:
        """Resolve the function body (payload plane: digest-only TASKs hit
        the cache, a miss is resolved SYNCHRONOUSLY with a BLOB_MISS
        transaction — REQ/REP gives us a mandatory reply to ride) and
        submit to the pool."""
        digest = reply.get("fn_digest")
        trace_id = reply.get("trace_id")
        if isinstance(trace_id, str) and trace_id:
            self._task_trace[reply["task_id"]] = trace_id
            log.debug(
                "task received", extra=log_ctx(
                    task_id=reply["task_id"],
                    worker_id=self.worker_id,
                    trace_id=trace_id,
                ),
            )
        payload = reply.get("fn_payload")
        if payload is None and digest:
            payload = self.fn_cache.get(digest)
            if payload is None:
                FN_CACHE_MISSES.inc()
                payload = self._fetch_blob(digest)
            else:
                FN_CACHE_HITS.inc()
            if payload is None:
                # unfillable (blob gone) or store outage at the
                # dispatcher: FAIL the task via the ordinary result path
                # rather than dropping it silently — REQ/REP has no
                # parked-task structure to wait in
                fail_extra: dict = {}
                fail_trace = self._task_trace.pop(reply["task_id"], None)
                if fail_trace:
                    fail_extra["trace_id"] = fail_trace
                self._transact(
                    m.RESULT,
                    worker_id=self.worker_id,
                    task_id=reply["task_id"],
                    status=str(TaskStatus.FAILED),
                    result=serialize(
                        RuntimeError(
                            f"function blob {str(digest)[:16]}... "
                            "unresolvable at dispatch"
                        )
                    ),
                    no_task=True,
                    **fail_extra,
                )
                return
        elif payload is not None and digest:
            self.fn_cache.put(digest, payload)
        deps = self._resolve_deps(reply)
        if deps is False:
            return  # a parent body was unresolvable; the task FAILED above
        rb = reply.get("rblob_min")
        if isinstance(rb, int) and rb > 0 and m.CAP_RESULT_BLOB in self.caps:
            self._task_rblob[reply["task_id"]] = rb
        if self._chaos_exec is not None:
            # slow / crash_before ahead of pool handoff (same seam shape
            # as the push worker — see its _submit_task comment)
            self._chaos_exec.before_task(reply["task_id"])
        self.pool.submit(
            reply["task_id"],
            payload,
            reply["param_payload"],
            timeout=reply.get("timeout"),
            fn_digest=digest,
            dep_results=deps or None,
        )

    def _resolve_deps(self, reply: dict):
        """Resolve a graph child's delivered parent results (result-blob
        plane): ``dep_results`` bodies ride the reply as-is;
        ``dep_digests`` hit the result cache, with misses fetched
        SYNCHRONOUSLY via BLOB_MISS transactions like fn blobs (REQ/REP
        has no parking structure). Returns the deps dict (None when the
        task carries none) or False after FAILing the task on an
        unresolvable parent body."""
        bodies = reply.get("dep_results")
        digests = reply.get("dep_digests")
        if not bodies and not digests:
            return None
        deps: dict[str, str] = dict(bodies) if isinstance(bodies, dict) else {}
        if isinstance(digests, dict):
            for pid, dg in digests.items():
                if not isinstance(dg, str) or not dg:
                    continue
                body = self.result_cache.get(dg)
                if body is None:
                    RESULT_CACHE_MISSES.inc()
                    body = self._fetch_blob(dg, cache=self.result_cache)
                else:
                    RESULT_CACHE_HITS.inc()
                if body is None:
                    self._task_rblob.pop(reply["task_id"], None)
                    fail_extra: dict = {}
                    fail_trace = self._task_trace.pop(reply["task_id"], None)
                    if fail_trace:
                        fail_extra["trace_id"] = fail_trace
                    self._transact(
                        m.RESULT,
                        worker_id=self.worker_id,
                        task_id=reply["task_id"],
                        status=str(TaskStatus.FAILED),
                        result=serialize(
                            RuntimeError(
                                f"parent result blob {dg[:16]}... "
                                "unresolvable at dispatch"
                            )
                        ),
                        no_task=True,
                        **fail_extra,
                    )
                    return False
                deps[pid] = body
        return deps

    def _fetch_blob(
        self, digest: str, retries: int = 40, cache: PayloadLRU | None = None
    ) -> str | None:
        """One or more BLOB_MISS transactions; an EMPTY fill (dispatcher
        store outage) backs off and retries — the ``_BLOB_BACKOFF``
        budget (~37 s at the default, sleeps capped at 1 s) rides out
        the store blips the rest of the system parks through, since
        REQ/REP has no parked-task structure to wait in asynchronously.
        ``missing`` (the blob is gone from the store too) gives up
        immediately."""
        for attempt in range(retries):
            # worker_id rides along: pull-mode liveness is request-stamped
            # (demand IS the heartbeat), and during an outage this retry
            # loop is the only traffic this worker emits — an anonymous
            # MISS would let last_seen age past tte and get the live
            # worker purged mid-resolution (its in-flight tasks would
            # re-dispatch and double-execute)
            self.socket.send(
                m.encode_for(
                    self._peer_bin,
                    m.BLOB_MISS,
                    digest=digest,
                    worker_id=self.worker_id,
                )
            )
            raw = self.socket.recv()
            if not self._peer_bin and m.is_binary(raw):
                self._peer_bin = True
            reply_type, reply = m.decode(raw)
            if reply_type != m.BLOB_FILL:
                return None  # protocol surprise: treat as unresolvable
            body = reply.get("data")
            if isinstance(body, str):
                (cache if cache is not None else self.fn_cache).put(
                    digest, body
                )
                return body
            if reply.get("missing"):
                return None
            time.sleep(_BLOB_BACKOFF.delay(attempt))  # dispatcher outage
        return None

    def run(self, max_tasks: int | None = None) -> int:
        """Main loop; returns number of results shipped (for tests)."""
        shipped = 0
        self.pool.warmup()  # pay the child-spawn cost before taking work
        self._transact(
            m.REGISTER, worker_id=self.worker_id, caps=list(self.caps)
        )
        last_transact = time.monotonic()
        try:
            while not self._stopping:
                time.sleep(self.delay)
                # ship every finished result; each reply may carry new work
                # (unless draining, where no_task forces a WAIT reply)
                for res in self.pool.drain():
                    extra_kw: dict = {}
                    trace_id = self._task_trace.pop(res.task_id, None)
                    if trace_id:
                        extra_kw["trace_id"] = trace_id
                    # digest-only ship (result-blob plane): COMPLETED
                    # results >= the task's rblob_min marker keep their
                    # body in the result cache and send the digest
                    rb = self._task_rblob.pop(res.task_id, None)
                    if (
                        rb
                        and res.status == str(TaskStatus.COMPLETED)
                        and isinstance(res.result, str)
                        and len(res.result) >= rb
                    ):
                        dg = payload_digest(res.result)
                        self.result_cache.put(dg, res.result)
                        body_kw: dict = {
                            "result_digest": dg,
                            "result_size": len(res.result),
                        }
                    else:
                        body_kw = {"result": res.result}
                    self._transact(
                        m.RESULT,
                        worker_id=self.worker_id,
                        task_id=res.task_id,
                        status=res.status,
                        **body_kw,
                        elapsed=res.elapsed,
                        started_at=res.started_at,
                        misfires=self.pool.n_misfires,
                        no_task=self._draining,
                        **extra_kw,
                    )
                    shipped += 1
                    last_transact = time.monotonic()
                    if self._chaos_exec is not None:
                        # crash_after: the result (and its mandatory
                        # reply) is done — the worker dies holding
                        # nothing the dispatcher hasn't seen
                        self._chaos_exec.after_result(res.task_id)
                # ask for work while slots are free
                if not self._draining and self.pool.free > 0:
                    self._transact(m.READY, worker_id=self.worker_id)
                    last_transact = time.monotonic()
                elif (
                    time.monotonic() - last_transact > self.keepalive_period
                ):
                    # saturated on long tasks: demand is the liveness signal
                    # in pull mode, so a worker that stops asking looks dead
                    # and would get its in-flight tasks re-queued under it.
                    # no_task forces a WAIT reply (we have no free slot).
                    self._transact(
                        m.READY, worker_id=self.worker_id, no_task=True
                    )
                    last_transact = time.monotonic()
                if max_tasks is not None and shipped >= max_tasks:
                    break
                if self._draining and self.pool.busy == 0:
                    break  # REQ/REP is synchronous: nothing can be in flight
        finally:
            self.pool.close()
            self.socket.close(linger=0)
        return shipped


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(description="tpu-faas pull worker")
    ap.add_argument("num_processes", type=int)
    ap.add_argument("dispatcher_url")
    ap.add_argument("-d", "--delay", type=float, default=0.01)
    ns = ap.parse_args(argv)
    log.info(
        "pull worker: %d processes -> %s", ns.num_processes, ns.dispatcher_url
    )
    from tpu_faas.worker.drain import install_drain_signals

    worker = PullWorker(ns.num_processes, ns.dispatcher_url, ns.delay)
    install_drain_signals(worker)
    worker.run()


if __name__ == "__main__":
    main()
