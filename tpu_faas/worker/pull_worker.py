"""Pull worker: REQ socket + local process pool.

Capability parity with reference PullWorker (pull_worker.py:10-123): register
with the dispatcher, then loop — pace by ``delay`` (load-bearing for REP/REQ
fairness across many workers, reference :131-132), ask for work when a pool
slot is free, ship finished results. Every request is answered with ``task``
or ``wait`` (the REP/REQ lockstep), and a reply to a ``result`` message may
itself carry the next task, so a busy fleet never wastes a round trip
(the reference's inline re-listen trick, pull_worker.py:108-111, made
structural here: every transaction handles its reply uniformly).

CLI: ``python -m tpu_faas.worker.pull_worker N tcp://host:port [--delay s]``
(reference pull_worker.py:126-137).
"""

from __future__ import annotations

import argparse
import time
import uuid

import zmq

from tpu_faas.utils.logging import get_logger
from tpu_faas.worker import messages as m
from tpu_faas.worker.pool import TaskPool

log = get_logger("pull_worker")


class PullWorker:
    def __init__(
        self,
        num_processes: int,
        dispatcher_url: str,
        delay: float = 0.01,
        recv_timeout_ms: int = 10_000,
        keepalive_period: float = 1.0,
    ) -> None:
        self.worker_id = str(uuid.uuid4())
        #: max silence while saturated before sending a WAIT-bound keepalive
        #: (must be well under the dispatcher's time_to_expire)
        self.keepalive_period = keepalive_period
        self.num_processes = num_processes
        self.delay = delay
        self.pool = TaskPool(num_processes)
        self.ctx = zmq.Context.instance()
        self.socket = self.ctx.socket(zmq.REQ)
        self.socket.setsockopt(zmq.RCVTIMEO, recv_timeout_ms)
        self.socket.setsockopt(zmq.LINGER, 0)
        # survive a dropped reply (dispatcher restart) without wedging the
        # REQ state machine
        self.socket.setsockopt(zmq.REQ_RELAXED, 1)
        self.socket.setsockopt(zmq.REQ_CORRELATE, 1)
        self.socket.connect(dispatcher_url)
        self._stopping = False
        self._draining = False

    def stop(self) -> None:
        self._stopping = True

    def drain(self) -> None:
        """Graceful shutdown: stop asking for work (and flag result messages
        ``no_task`` so their mandatory replies are WAIT, never a new task),
        ship what's in flight, then exit."""
        self._draining = True

    # -- one REQ/REP transaction ------------------------------------------
    def _transact(self, msg_type: str, **data: object) -> None:
        """Send one message, receive the mandatory reply, and if the reply
        carries a task, put it on the pool. Force-cancels ride the reply
        too (``cancel_ids``): a pull worker cannot be pushed to, so the
        dispatcher piggy-backs kill requests for tasks THIS worker runs on
        whatever reply goes out next — TASK or WAIT."""
        self.socket.send(m.encode(msg_type, **data))
        reply_type, reply = m.decode(self.socket.recv())
        for tid in reply.get("cancel_ids", ()):
            if self.pool.cancel(tid):
                log.info(
                    "force-cancelling task %s", tid,
                    extra={"task_id": tid, "worker_id": self.worker_id},
                )
        if reply_type == m.TASK:
            self.pool.submit(
                reply["task_id"],
                reply["fn_payload"],
                reply["param_payload"],
                timeout=reply.get("timeout"),
            )
        # WAIT: nothing to do

    def run(self, max_tasks: int | None = None) -> int:
        """Main loop; returns number of results shipped (for tests)."""
        shipped = 0
        self.pool.warmup()  # pay the child-spawn cost before taking work
        self._transact(m.REGISTER, worker_id=self.worker_id)
        last_transact = time.monotonic()
        try:
            while not self._stopping:
                time.sleep(self.delay)
                # ship every finished result; each reply may carry new work
                # (unless draining, where no_task forces a WAIT reply)
                for res in self.pool.drain():
                    self._transact(
                        m.RESULT,
                        worker_id=self.worker_id,
                        task_id=res.task_id,
                        status=res.status,
                        result=res.result,
                        elapsed=res.elapsed,
                        started_at=res.started_at,
                        misfires=self.pool.n_misfires,
                        no_task=self._draining,
                    )
                    shipped += 1
                    last_transact = time.monotonic()
                # ask for work while slots are free
                if not self._draining and self.pool.free > 0:
                    self._transact(m.READY, worker_id=self.worker_id)
                    last_transact = time.monotonic()
                elif (
                    time.monotonic() - last_transact > self.keepalive_period
                ):
                    # saturated on long tasks: demand is the liveness signal
                    # in pull mode, so a worker that stops asking looks dead
                    # and would get its in-flight tasks re-queued under it.
                    # no_task forces a WAIT reply (we have no free slot).
                    self._transact(
                        m.READY, worker_id=self.worker_id, no_task=True
                    )
                    last_transact = time.monotonic()
                if max_tasks is not None and shipped >= max_tasks:
                    break
                if self._draining and self.pool.busy == 0:
                    break  # REQ/REP is synchronous: nothing can be in flight
        finally:
            self.pool.close()
            self.socket.close(linger=0)
        return shipped


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(description="tpu-faas pull worker")
    ap.add_argument("num_processes", type=int)
    ap.add_argument("dispatcher_url")
    ap.add_argument("-d", "--delay", type=float, default=0.01)
    ns = ap.parse_args(argv)
    log.info(
        "pull worker: %d processes -> %s", ns.num_processes, ns.dispatcher_url
    )
    from tpu_faas.worker.drain import install_drain_signals

    worker = PullWorker(ns.num_processes, ns.dispatcher_url, ns.delay)
    install_drain_signals(worker)
    worker.run()


if __name__ == "__main__":
    main()
