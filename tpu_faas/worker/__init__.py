"""Worker runtime: pull (REQ) and push (DEALER) worker nodes.

Capability parity with reference pull_worker.py / push_worker.py: each worker
owns a local process pool executing `execute_fn` and speaks the dict-envelope
ZMQ protocol (SURVEY §2.3) to its dispatcher.
"""

from tpu_faas.worker.pool import TaskPool

__all__ = ["TaskPool"]
