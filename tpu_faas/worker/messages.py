"""Wire envelope for dispatcher <-> worker ZMQ messages.

Same shape as the reference's vocabulary (SURVEY §2.3): every payload is a
dict ``{"type": ..., "data": {...}}`` run through the core serializer, so
arbitrary Python values (including results that are themselves serialized
strings) travel safely.

Message vocabulary:

worker -> dispatcher:
    REGISTER   data: worker_id (pull) | num_processes (push)
    RESULT     data: task_id, status, result [, elapsed: float — execution
               wall seconds measured in the pool child, feeding the
               dispatcher's runtime estimator; absent from reference-era
               workers and handled as such] [, started_at: float — epoch
               seconds the child began executing, measured at the source;
               with `elapsed` it gives the dispatcher's task timeline its
               exec_start/exec_end events (tpu_faas/obs/trace.py)]
               [, misfires: int — the pool's cumulative misfire-repair
               counter] [, no_task=True while draining
               (pull): the mandatory reply must be WAIT, never a new task]
    READY      (pull only) data: worker_id
    HEARTBEAT  (push hb) data: {}
    RECONNECT  (push hb) data: free_processes
    DEREGISTER (push) data: {} — graceful drain: stop assigning to me; my
               in-flight results still follow, then I exit

dispatcher -> worker:
    TASK       data: task_id, fn_payload, param_payload [, timeout: float —
               execution budget the worker enforces in its pool child
               (SIGALRM); absent = unbounded, the reference contract]
               [, cancel_ids: list — pull only, see WAIT]
    WAIT       (pull only) [, cancel_ids: list — force-cancels for tasks
               THIS worker runs, piggy-backed on the mandatory reply
               because a REQ/REP worker cannot be pushed to; a saturated
               worker's keepalive transactions bound the delivery latency]
    RECONNECT  (push hb; request for the worker to re-announce itself)
    CANCEL     (push) data: task_id — force-cancel a dispatched task: the
               worker interrupts it mid-run (pool SIGUSR1, the externally
               triggered sibling of the timeout) or drops it pre-start,
               and ships a normal RESULT with status CANCELLED; a task
               that already finished just ships its real result. Best
               effort by design — reference-era workers ignore unknown
               message types and fields, and the record then converges
               via the ordinary result path.
"""

from __future__ import annotations

from tpu_faas.core.serialize import deserialize, serialize

REGISTER = "register"
DEREGISTER = "deregister"
RESULT = "result"
READY = "ready"
HEARTBEAT = "heartbeat"
RECONNECT = "reconnect"
TASK = "task"
WAIT = "wait"
CANCEL = "cancel"


def encode(msg_type: str, **data: object) -> bytes:
    return serialize({"type": msg_type, "data": data}).encode("ascii")


def decode(raw: bytes) -> tuple[str, dict]:
    msg = deserialize(raw.decode("ascii"))
    if not isinstance(msg, dict) or "type" not in msg:
        raise ValueError(f"malformed worker message: {msg!r}")
    return msg["type"], msg.get("data", {})
