"""Wire envelope for dispatcher <-> worker ZMQ messages.

Same shape as the reference's vocabulary (SURVEY §2.3): every payload is a
dict ``{"type": ..., "data": {...}}`` run through the core serializer, so
arbitrary Python values (including results that are themselves serialized
strings) travel safely.

Message vocabulary:

worker -> dispatcher:
    REGISTER   data: worker_id (pull) | num_processes (push)
               [, caps: list[str] — protocol capabilities this worker
               understands: "blob" (digest-addressed TASK payloads +
               BLOB_MISS/BLOB_FILL resolution), "bin" (binary frames).
               Absent from reference-era workers, which therefore get the
               full inline-payload ASCII contract unchanged]
    RESULT     data: task_id, status, result [, elapsed: float — execution
               wall seconds measured in the pool child, feeding the
               dispatcher's runtime estimator; absent from reference-era
               workers and handled as such] [, started_at: float — epoch
               seconds the child began executing, measured at the source;
               with `elapsed` it gives the dispatcher's task timeline its
               exec_start/exec_end events (tpu_faas/obs/trace.py)]
               [, misfires: int — the pool's cumulative misfire-repair
               counter] [, no_task=True while draining
               (pull): the mandatory reply must be WAIT, never a new task]
    READY      (pull only) data: worker_id
    HEARTBEAT  (push hb) data: {}
    RECONNECT  (push hb) data: free_processes
    DEREGISTER (push) data: {} — graceful drain: stop assigning to me; my
               in-flight results still follow, then I exit

worker -> dispatcher (payload plane, "blob"-capable workers only):
    BLOB_MISS  data: digest [, worker_id — pull workers include it: their
               liveness is request-stamped, and a blob-fetch retry loop
               may be the only traffic they emit during an outage] — the
               worker holds tasks whose TASK message carried ``fn_digest``
               with no body and its payload cache missed; the dispatcher
               answers with BLOB_FILL. Push workers re-send on a timer
               while tasks stay parked (a FILL, like everything on this
               transport, can be lost); pull workers retry in place on
               their mandatory-reply socket.

dispatcher -> worker:
    TASK       data: task_id, fn_payload, param_payload [, timeout: float —
               execution budget the worker enforces in its pool child
               (SIGALRM); absent = unbounded, the reference contract]
               [, cancel_ids: list — pull only, see WAIT]
    WAIT       (pull only) [, cancel_ids: list — force-cancels for tasks
               THIS worker runs, piggy-backed on the mandatory reply
               because a REQ/REP worker cannot be pushed to; a saturated
               worker's keepalive transactions bound the delivery latency]
    RECONNECT  (push hb; request for the worker to re-announce itself)
    CANCEL     (push) data: task_id — force-cancel a dispatched task: the
               worker interrupts it mid-run (pool SIGUSR1, the externally
               triggered sibling of the timeout) or drops it pre-start,
               and ships a normal RESULT with status CANCELLED; a task
               that already finished just ships its real result. Best
               effort by design — reference-era workers ignore unknown
               message types and fields, and the record then converges
               via the ordinary result path.
    TASK (payload plane) may carry ``fn_digest`` INSTEAD of
               ``fn_payload`` when the worker registered the "blob"
               capability: the worker resolves the body from its payload
               cache, or parks the task and asks with BLOB_MISS.
    TASK (tracing) carries ``trace_id`` when the worker registered the
               "trace" capability (distributed trace context,
               tpu_faas/obs/tracectx.py); the worker stamps it into its
               logs and echoes it on the matching RESULT. Reference-era
               workers never receive the field.
    TASK_BATCH data: tasks: list — each element a full TASK ``data`` dict
               (task_id/fn_payload-or-fn_digest/param_payload/timeout/
               trace_id, exactly the per-task vocabulary above). ONE frame
               carries a whole tick's assignments for this worker, sent
               only to workers that advertised the "batch" capability and
               only by dispatchers with batching enabled (``--batch-max``
               >= 2) — everyone else keeps the per-task TASK contract
               byte for byte. Per-task semantics (blob resolution,
               parking, cancel, tracing) are element-wise identical to K
               separate TASK frames.
    RESULT_BATCH (worker -> dispatcher) data: results: list — each element
               a full RESULT ``data`` dict (task_id/status/result/elapsed/
               started_at/trace_id) — plus one top-level ``misfires``
               total. A worker switches to this form only after RECEIVING
               a TASK_BATCH (proof the dispatcher decodes it), the same
               asymmetric negotiation as binary framing; a K-result drain
               then costs one frame instead of K.
    BLOB_FILL  data: digest, data (the ASCII payload body) — answers a
               BLOB_MISS; ``missing=True`` (no data) when the blob is
               gone from the store too, telling the worker to FAIL the
               parked tasks instead of waiting forever.

result-blob plane ("rblob"-capable workers under ``--result-blobs``
dispatchers only; every field below is absent otherwise):
    TASK/TASK_BATCH elements may carry ``rblob_min`` (int, byte
               threshold): proof the dispatcher decodes digest-form
               results, and permission for THIS task's completed result to
               ship digest-only when it is at least that large (the
               dispatcher marks exactly the tasks whose results it knows
               to be graph-consumed). They may also carry parent results
               for graph children: ``dep_digests`` {parent_id: digest}
               for bodies the target worker's result cache should already
               hold (cache miss -> BLOB_MISS, exactly like fn blobs), and
               ``dep_results`` {parent_id: body} for cold targets (also
               the ``--dep-results`` store-mediated form). The worker
               exposes resolved parent bodies to the executing function
               via core/executor.py dep_results().
    RESULT/RESULT_BATCH elements may carry ``result_digest`` (sha256 hex)
               + ``result_size`` (int) INSTEAD of ``result``: the body
               stays in the worker's result cache under that digest, and
               the dispatcher records the digest-form terminal write. Only
               COMPLETED results ever ship digest-only — failures always
               carry their body (error payloads must stay materializable
               without the producing worker).
    BLOB_MISS  (dispatcher -> worker, the REVERSE direction) data: digest —
               asks the worker for a result body its cache holds; the
               worker answers with a BLOB_FILL (``missing=True`` when
               evicted). This is how a digest-only result is materialized
               into the store after the fact (lazy materialization for
               legacy readers, child-worker cache misses).

Framing: the reference contract is ASCII — base64(dill(message)) — and
stays the default. Peers that BOTH understand the "bin" capability switch
to raw binary frames (``_BIN_MAGIC`` + dill bytes, no base64: ~25% less
wire volume on every payload-carrying hop). Negotiation is asymmetric on
purpose: a worker advertises ``caps=["bin"]`` on its (always-ASCII)
REGISTER/RECONNECT, the dispatcher then frames everything to that worker
in binary, and the worker switches its own sends only after RECEIVING a
binary frame — proof the peer decodes them. ``decode`` sniffs the magic,
so mixed fleets (reference workers beside new ones) share one socket.
"""

from __future__ import annotations

import dill

from tpu_faas.core.serialize import (
    deserialize,
    dumps_wire,
    serialize_wire,
)

REGISTER = "register"
DEREGISTER = "deregister"
RESULT = "result"
READY = "ready"
HEARTBEAT = "heartbeat"
RECONNECT = "reconnect"
TASK = "task"
TASK_BATCH = "task_batch"
RESULT_BATCH = "result_batch"
WAIT = "wait"
CANCEL = "cancel"
BLOB_MISS = "blob_miss"
BLOB_FILL = "blob_fill"

#: capability tokens carried in REGISTER/RECONNECT ``caps``
CAP_BLOB = "blob"
CAP_BIN = "bin"
#: distributed tracing: a trace-capable worker receives the task's
#: ``trace_id`` on TASK messages (stamped into its logs via log_ctx) and
#: echoes it on the matching RESULT. Capability-gated like blob/bin so
#: reference-era workers never see the field.
CAP_TRACE = "trace"
#: batched data plane: a batch-capable worker may receive TASK_BATCH
#: frames (one frame per worker per tick) and, once it has seen one,
#: coalesces its own result drain into RESULT_BATCH frames. Negotiated
#: like blob/bin/trace, and additionally gated dispatcher-side on
#: ``--batch-max`` — batching off means the per-task wire is untouched
#: even between capable peers.
CAP_BATCH = "batch"
#: result-blob plane: an rblob-capable worker keeps a byte-bounded RESULT
#: cache keyed by content digest and, for tasks whose TASK frame carried
#: ``rblob_min`` (the dispatcher's ``--result-blobs`` proof + threshold),
#: ships completed results >= that size as DIGEST-ONLY RESULT frames
#: (``result_digest``/``result_size``, no ``result`` body). It also
#: resolves parent-result digests on child TASK frames (``dep_digests``)
#: from that cache, and answers dispatcher->worker BLOB_MISS pulls from
#: it (the reverse of the function-blob flow — the dispatcher
#: materializes a body it never shipped). Negotiated like blob/bin/trace:
#: no rblob advertisement, or ``--result-blobs`` off, leaves every frame
#: byte-identical to the reference-era contract.
CAP_RESULT_BLOB = "rblob"
#: what a current-generation worker advertises
WORKER_CAPS = (CAP_BLOB, CAP_BIN, CAP_TRACE, CAP_BATCH, CAP_RESULT_BLOB)

#: binary-frame magic: never a valid first byte of the ASCII contract
#: (base64's alphabet is [A-Za-z0-9+/=]), so one-byte sniffing is exact
_BIN_MAGIC = b"\x00TF1"


def encode(msg_type: str, **data: object) -> bytes:
    """The reference ASCII contract: base64(pickle({type, data})).

    The envelope pickles through the C fast path when every leaf is a
    primitive (the whole documented vocabulary — payload bodies are
    already-serialized strings by the time they reach the envelope) and
    through dill otherwise; both are standard pickle streams, so every
    decoder — reference-era dill.loads included — reads them identically
    (core/serialize.dumps_wire)."""
    return serialize_wire({"type": msg_type, "data": data}).encode("ascii")


def encode_bin(msg_type: str, **data: object) -> bytes:
    """Binary frame: magic + raw pickle bytes — skips the ~33% base64
    inflation on internal hops. Send only to peers that negotiated
    CAP_BIN (see the module docstring). Same C-pickler envelope fast
    path as :func:`encode`."""
    return _BIN_MAGIC + dumps_wire({"type": msg_type, "data": data})


def encode_for(bin_capable: bool, msg_type: str, **data: object) -> bytes:
    """Frame for a specific peer: binary when negotiated, ASCII else."""
    if bin_capable:
        return encode_bin(msg_type, **data)
    return encode(msg_type, **data)


def is_binary(raw: bytes) -> bool:
    return raw.startswith(_BIN_MAGIC)


def decode(raw: bytes) -> tuple[str, dict]:
    """Decode either framing (magic-sniffed)."""
    if raw.startswith(_BIN_MAGIC):
        msg = dill.loads(raw[len(_BIN_MAGIC):])
    else:
        msg = deserialize(raw.decode("ascii"))
    if not isinstance(msg, dict) or "type" not in msg:
        raise ValueError(f"malformed worker message: {msg!r}")
    return msg["type"], msg.get("data", {})


def caps_of(data: dict) -> frozenset[str]:
    """The capability set a REGISTER/RECONNECT payload advertises;
    empty for reference-era workers (and anything malformed)."""
    raw = data.get("caps")
    if not isinstance(raw, (list, tuple)):
        return frozenset()
    return frozenset(c for c in raw if isinstance(c, str))
