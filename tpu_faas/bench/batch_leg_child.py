"""One full-stack leg of the batched-data-plane bench (config 17), as a
real OS process.

The config compares two serve-loop configurations of the SAME stack
(batched vs per-task worker wire). Run as threads of one parent process,
the second leg measurably inherits the first's teardown tail (dying
forkserver children, allocator/GC state, asyncio loop remains) — identical
reps were observed 6x apart purely by leg order on a small box. Each leg
therefore runs in a fresh child process (config-14 precedent: processes,
not threads, for anything whose serve loop is being compared).

The child builds the whole stack — RESP store server, gateway, an express
tpu-push dispatcher with the requested ``--batch-max``/``--batch-window-ms``,
and real PushWorkers as threads of this child (their pool children are
separate processes; keeping the worker parents in-child makes the pool
counters readable) — drives a no-op burst through the real submit path,
probes solo express latency on the then-idle stack, scrapes /metrics
against the strict exposition grammar mid-run, and prints ONE JSON row on
stdout.

The dispatcher's serve loop runs under cProfile (both legs pay the same
overhead, so cross-leg ratios stay honest) and the row carries a
``host_profile`` block — top-10 cumulative functions — attributing where
the host cycles went. ``--columnar`` flips the dispatcher onto the
columnar arena intake + binbatch store wire (core/columns.py);
``--safety-poll-s`` pins the gateway's announce-loss safety poll, which
otherwise floors solo wait latency at its default when an announce is
dropped.

Run: ``python -m tpu_faas.bench.batch_leg_child --batch-max 16
--batch-window-ms 2 --tasks 2000 --workers 2 --procs 4 --solo 30``
"""

from __future__ import annotations

import argparse
import cProfile
import json
import pstats
import sys
import threading
import time
import urllib.request


def _top_profile(prof: cProfile.Profile, limit: int = 10) -> list[dict]:
    """Top ``limit`` functions by cumulative time, as JSON-able rows."""
    import os

    st = pstats.Stats(prof)
    st.sort_stats("cumulative")
    out: list[dict] = []
    for func in st.fcn_list or []:
        _cc, nc, tt, ct, _callers = st.stats[func]
        fname, line, name = func
        out.append(
            {
                "func": f"{os.path.basename(fname)}:{line}({name})",
                "cum_s": round(ct, 4),
                "tot_s": round(tt, 4),
                "calls": int(nc),
            }
        )
        if len(out) >= limit:
            break
    return out


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(
        description="batched-data-plane bench leg child"
    )
    ap.add_argument("--batch-max", type=int, default=0)
    ap.add_argument("--batch-window-ms", type=float, default=0.0)
    ap.add_argument("--tasks", type=int, required=True)
    ap.add_argument("--workers", type=int, default=2)
    ap.add_argument("--procs", type=int, default=4)
    ap.add_argument("--solo", type=int, default=30)
    ap.add_argument("--columnar", action="store_true")
    ap.add_argument("--safety-poll-s", type=float, default=2.0)
    ns = ap.parse_args(argv)

    # persistent XLA compile cache, same as fleet_child/the dispatcher
    # CLI: a cold child re-compiling the device tick mid-burst stalls the
    # serve loop long enough to trip heartbeat purges of its own workers
    import os

    cache_dir = os.environ.get(
        "TPU_FAAS_COMPILE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "tpu_faas_xla"),
    )
    if cache_dir:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from tpu_faas.client import FaaSClient
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.gateway import start_gateway_thread
    from tpu_faas.obs.expofmt import parse_exposition, require_series
    from tpu_faas.store.launch import make_store, start_store_thread
    from tpu_faas.utils.logging import percentile
    from tpu_faas.worker.pool import POOL_IPC
    from tpu_faas.worker.push_worker import PushWorker
    from tpu_faas.workloads import no_op

    required_series = [
        "tpu_faas_dispatcher_tasks_dispatched_total",
        "tpu_faas_dispatcher_task_frames_total",
        "tpu_faas_dispatch_batch_size",
        "tpu_faas_worker_bundle_size",
        "tpu_faas_worker_pool_ipc_total",
        "tpu_faas_dispatcher_results_total",
    ]

    n_tasks = ns.tasks
    handle = start_store_thread()
    gw = start_gateway_thread(
        make_store(handle.url), wait_safety_poll_s=ns.safety_poll_s
    )
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=make_store(handle.url, binbatch=ns.columnar),
        max_workers=max(64, ns.workers * 2),
        max_pending=4096,
        max_inflight=max(4 * n_tasks, 1024),
        max_slots=ns.procs,
        tick_period=0.005,
        recover_queued=False,
        express=True,
        batch_max=ns.batch_max,
        batch_window_ms=ns.batch_window_ms,
        columnar=ns.columnar,
    )
    # profile the serve loop from inside its own thread (cProfile is
    # per-thread); stats are read only after the thread joins
    serve_profile = cProfile.Profile()

    def _serve() -> None:
        serve_profile.enable()
        try:
            disp.start()
        finally:
            serve_profile.disable()

    disp_thread = threading.Thread(target=_serve, daemon=True)
    disp_thread.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        PushWorker(ns.procs, url, heartbeat=True, heartbeat_period=0.5)
        for _ in range(ns.workers)
    ]
    worker_threads = [
        threading.Thread(target=w.run, daemon=True) for w in workers
    ]
    for t in worker_threads:
        t.start()
    stats_server = disp.serve_stats(0)
    stats_port = stats_server.server_address[1]
    client = FaaSClient(gw.url)
    try:
        fid = client.register(no_op)
        # warm the stack end to end (pool children spawned inside
        # PushWorker.run; first results prove the wire) before timing
        for h in client.submit_many(fid, [((), {})] * 4):
            h.result(timeout=120.0)
        ipc0 = POOL_IPC.value
        frames0 = disp.m_task_frames.value
        dispatched0 = disp.n_dispatched
        results0 = disp.n_results
        scrape_ok: bool | None = None
        scrape_missing: list[str] = []
        scrape_error = ""
        t0 = time.perf_counter()
        chunk = 500
        submitted = 0
        while submitted < n_tasks:
            n = min(chunk, n_tasks - submitted)
            client.submit_many(fid, [((), {})] * n)
            submitted += n
        deadline = t0 + 600.0
        while (
            disp.n_results - results0 < n_tasks
            and time.perf_counter() < deadline
        ):
            if (
                scrape_ok is None
                and disp.n_results - results0 >= n_tasks // 2
            ):
                # mid-run scrape: the exposition must be valid and
                # complete WHILE the hot loop runs, not just at rest
                try:
                    with urllib.request.urlopen(
                        f"http://127.0.0.1:{stats_port}/metrics",
                        timeout=10,
                    ) as resp:
                        families = parse_exposition(
                            resp.read().decode("utf-8")
                        )
                    scrape_missing = require_series(
                        families, required_series
                    )
                    scrape_ok = not scrape_missing
                except Exception as exc:
                    scrape_ok = False
                    scrape_error = f"{type(exc).__name__}: {exc}"
            time.sleep(0.01)
        elapsed = time.perf_counter() - t0
        completed = disp.n_results - results0
        n_dispatched = disp.n_dispatched - dispatched0
        frames = disp.m_task_frames.value - frames0
        ipc = POOL_IPC.value - ipc0
        # solo latency probe on the now-idle stack: sequential
        # single-task round trips through the express lane. A short
        # settle first — the burst's tail (trace-book close-out, span
        # flushes, gateway observe backlog) otherwise bleeds one
        # multi-second outlier into a small-sample p99
        time.sleep(0.5)
        solo_ms: list[float] = []
        for _ in range(ns.solo):
            s0 = time.perf_counter()
            h = client.submit(fid)
            h.result(timeout=60.0)
            solo_ms.append((time.perf_counter() - s0) * 1e3)
        solo_ms.sort()  # percentile() is nearest-rank over SORTED data
        # quiesce the serve loop BEFORE reading its profile: cProfile
        # stats are only consistent after the profiled thread exits
        # (stop()/disp.stop() are idempotent flag-sets; the finally
        # block's repeats are harmless)
        for w in workers:
            w.stop()
        for t in worker_threads:
            t.join(timeout=30)
        disp.stop()
        disp_thread.join(timeout=10)
        row = {
            "batch_max": ns.batch_max,
            "batch_window_ms": ns.batch_window_ms,
            "columnar": bool(ns.columnar),
            "completed": completed,
            "tasks_per_s": round(completed / max(elapsed, 1e-9), 1),
            "frames_per_task": round(frames / max(n_dispatched, 1), 4),
            "pool_ipc_per_task": round(ipc / max(completed, 1), 4),
            "solo_p50_ms": round(percentile(solo_ms, 0.5), 3),
            "solo_p99_ms": round(percentile(solo_ms, 0.99), 3),
            "metrics_scrape_ok": bool(scrape_ok),
            "metrics_missing": scrape_missing,
            "metrics_scrape_error": scrape_error,
            # stall diagnostics: recompiles and purges mid-burst mean the
            # leg measured a compile/reclaim cascade, not the data plane
            "jit_signatures": disp.profiler.n_signatures,
            "workers_purged": disp.n_purged,
            "tasks_reclaimed": int(disp.m_reclaimed.value),
            "tick_p99_ms": round(
                disp.tracer.summary()
                .get("device_tick", {})
                .get("p99", 0.0) * 1e3,
                2,
            ),
            # top-10 cumulative serve-loop functions (cProfile over the
            # dispatcher thread, warm-up through solo probe)
            "host_profile": _top_profile(serve_profile),
        }
        print(json.dumps(row), flush=True)
    finally:
        for w in workers:
            w.stop()
        for t in worker_threads:
            t.join(timeout=30)
        disp.stop()
        disp_thread.join(timeout=10)
        disp.socket.close(linger=0)
        disp.close()
        gw.stop()
        handle.stop()


if __name__ == "__main__":
    sys.exit(main())
