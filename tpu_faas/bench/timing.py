"""Device timing helpers shared by bench.py and the BASELINE configs.

The pipeline-slope method: dispatch ``n`` in-order executions with fresh
inputs and force one readback of the last output (a single device stream
executes in order, so the readback implies all ``n`` completed), at two
depths ``n1 < n2``; the slope ``(t(n2) - t(n1)) / (n2 - n1)`` isolates
per-execution device time from the constant per-round-trip transport
latency. This matters because dev environments may reach the TPU through an
RPC tunnel with a ~70 ms round-trip floor that has nothing to do with the
kernel (a production dispatcher holds the device locally and syncs in
microseconds); naive per-call timing there misreports in BOTH directions —
async dispatch under-reports, sync round trips over-report.
"""

from __future__ import annotations

import time

import numpy as np


def pipeline_slope_ms(run, problems, n1: int, n2: int, points: int = 5) -> float:
    """Per-execution device time in ms. ``run(problem)`` must return a
    structure whose first leaf is a device array; ``problems`` are cycled to
    give each execution fresh inputs (defeats value-memoizing transports).

    Rather than a two-point difference — where ONE jittery timing window
    (host load, tunnel hiccup) corrupts the slope in either direction, even
    to negative values — this times ``points`` depths between n1 and n2 and
    takes the Theil-Sen estimate (median of all pairwise slopes), which
    tolerates up to ~29% corrupted windows."""
    import jax

    def pipelined(n: int) -> float:
        seq = [problems[i % len(problems)] for i in range(n)]
        t0 = time.perf_counter()
        outs = [run(p) for p in seq]
        np.asarray(jax.tree_util.tree_leaves(outs[-1])[0])
        return time.perf_counter() - t0

    depths = sorted({int(round(d)) for d in np.linspace(n1, n2, max(points, 2))})
    if len(depths) < 2:
        raise ValueError(f"need two distinct depths, got n1={n1}, n2={n2}")
    times = [(n, pipelined(n)) for n in depths]
    slopes = [
        (tb - ta) / (nb - na)
        for i, (na, ta) in enumerate(times)
        for nb, tb in times[i + 1 :]
    ]
    return float(np.median(slopes) * 1e3)


def transport_floor_ms(reps: int = 5) -> float:
    """Median round-trip cost of a trivial synchronous device call."""
    import jax
    import jax.numpy as jnp

    trivial = jax.jit(lambda x, i: (x + i).sum())
    float(trivial(jnp.zeros(16), 0.0))
    floors = []
    for i in range(reps):
        t0 = time.perf_counter()
        float(trivial(jnp.zeros(16), float(i + 1)))
        floors.append(time.perf_counter() - t0)
    return float(np.median(floors) * 1e3)
