"""Benchmark harness: end-to-end service measurement + the BASELINE configs.

The service harness reproduces the reference's client_performance.py metrics
(throughput over the poll window, mean per-task latency, time-to-register,
medians over simulations with a store flush between runs — BASELINE.md) with
the unit bug fixed (the reference printed milliseconds labeled "ns",
client_performance.py:301-302).
"""

from tpu_faas.bench.harness import BenchResult, measure_service

__all__ = ["BenchResult", "measure_service"]
