"""The five BASELINE benchmark configs (BASELINE.md / BASELINE.json
configs[]) plus one framework-extra:

1. PushDispatcher greedy load-balance, 8 PushWorkers, sleep-N tasks
2. PullDispatcher REP/REQ, 8 PullWorkers, mixed-duration tasks
3. Simulated 1k workers x 10k tasks, uniform cost, auction assignment
4. Heterogeneous workers + task-size estimates, Sinkhorn placement
5. Heartbeat churn: 4k workers, 5% fail/rejoin per tick, on-device
   task redistribution
6. (extra, no BASELINE analog) time-to-register: batch /execute_batch +
   pipelined store writes vs one POST per task
9. (extra) host dispatch throughput: intake -> device -> act against the
   in-process RESP store server — the host data plane end to end, with the
   store-round-trips-per-tick counter proving the batched (pipelined) forms
10. (extra) overload robustness: offered load >= 3x fleet capacity against
   the full stack with the admission controller engaged — goodput holds,
   rejects are clean 429s with Retry-After, no admitted task is lost
11. (extra) payload plane: repeated-fn store bytes/task + host dispatch
   throughput, inline vs content-addressed shipping (blob namespace,
   dispatcher blob cache, digest-shipped TASKs)
12. (extra) latency distribution: closed-loop submit→observe against the
   full stack with distributed tracing on — p50/p95/p99 submit→result
   plus the per-stage p99 breakdown from assembled cross-process traces
   (which stage owns the latency floor)
14. (extra) fleet throughput: the federated control plane — N store-shard
   subprocesses x N dispatcher subprocesses behind a stateless gateway
   tier vs the 1x1x1 single stack on the same box, publishing tasks/s per
   topology + the scaling ratio, plus a one-shard-primary-SIGKILL chaos
   leg under the race monitor (zero admitted-task loss)
15. (extra) tick-latency trajectory: the fused-Pallas resident tick vs
   the XLA op-graph tick over a shape ladder (median per-tick wall time,
   one-dispatch-per-tick pinned live), plus a capacity DRYRUN at the
   ROADMAP 500k x 32k shape (O(T+S) memory — no [T, S] materialization)
   and an optional sharded permute-winner-resolve leg

Configs 1-2, 6, 9-12 run the real socket stack; 3-5 run the device kernels
at scales the socket stack can't reach on one box (the reference had no
analog — its harness topped out at localhost subprocesses, SURVEY §4).
Each config returns a dict and is printed as one JSON line by the CLI.
"""

from __future__ import annotations

import time

import numpy as np

from tpu_faas.bench.timing import pipeline_slope_ms as _pipeline_slope_ms
from tpu_faas.bench.timing import transport_floor_ms


def config_1_push_sleep() -> dict:
    from tpu_faas.bench.harness import measure_service

    res = measure_service(
        mode="push",
        n_workers=8,
        n_procs=4,
        tasks_per_worker=10,
        workload="sleep",
        size=100,  # sleep 0.1 s
        n_sims=3,
    )
    return {"config": "push-8w-sleep", **res.to_dict()}


def config_2_pull_mixed() -> dict:
    from tpu_faas.bench.harness import measure_service

    res = measure_service(
        mode="pull",
        n_workers=8,
        n_procs=4,
        tasks_per_worker=10,
        workload="arithmetic",
        size=50_000,
        n_sims=3,
    )
    return {"config": "pull-8w-mixed", **res.to_dict()}


def config_3_auction_1k_10k() -> dict:
    """10k tasks x 1k workers (4k slots), uniform cost: auction assignment
    vs the rank-matching kernel on the identical problem.

    With separable cost (size/speed) the matrix satisfies the Monge
    property, so sorted pairing is provably optimal — rank-match is the
    production path and carries this config; the auction is the on-device
    exact solver for GENERAL costs. Its live cost is the WARM-started one:
    a dispatcher solves a sequence of similar problems, feeding each tick's
    equilibrium prices into the next (auction_placement init_price), so the
    cold number below is paid once at startup, not per tick. Both are
    measured. Inputs are perturbed per rep so execution-memoizing device
    tunnels can't fake the timing.
    """
    from tpu_faas.sched.auction import auction_placement
    from tpu_faas.sched.greedy import host_greedy_reference, rank_match_placement
    from tpu_faas.sched.problem import PlacementProblem

    import dataclasses

    import jax.numpy as jnp

    n_tasks, n_workers, max_slots = 10_000, 1_000, 4
    speeds = np.ones(n_workers, dtype=np.float32)
    free = np.full(n_workers, max_slots, dtype=np.int32)
    live = np.ones(n_workers, dtype=bool)
    # One padded template, then DISTINCT size vectors per execution — a
    # deep pipeline over a small cycled set would let execution-memoizing
    # dev tunnels replay repeated (executable, args) pairs for free and
    # fake the slope. 512 covers the deepest rank window below; only the
    # 40 KB size vector varies, the fleet arrays are shared.
    template = PlacementProblem.build(
        np.full(n_tasks, 1.0, dtype=np.float32), speeds, free, live,
        T=10_240, W=1_024,
    )
    base = np.asarray(template.task_size)
    problems = [
        dataclasses.replace(
            template,
            task_size=jnp.asarray(base + np.float32((i + 1) * 1e-6)),
        )
        for i in range(512)
    ]

    def run_auction(p):
        return auction_placement(
            p.task_size, p.task_valid, p.worker_speed, p.worker_free,
            p.worker_live, max_slots=max_slots, eps=1e-3,
        )

    # Steady-state warm tick: init_price = the converged equilibrium from
    # the cold solve. A live dispatcher chains each tick's prices into the
    # next; the measurement uses a FIXED pre-staged price buffer instead
    # because chaining device outputs into the next call's inputs defeats
    # pipelining over tunneled dev transports (measured: +66 ms/call of
    # pure round-trip, none of it device time — a production-local chip
    # chains for free). Same rounds executed either way.
    warm_price = [None]  # seeded after the cold compile below

    def run_auction_warm(p):
        return auction_placement(
            p.task_size, p.task_valid, p.worker_speed, p.worker_free,
            p.worker_live, max_slots=max_slots, eps=1e-3,
            init_price=warm_price[0],
        )

    def run_rank(p):
        return rank_match_placement(
            p.task_size, p.task_valid, p.worker_speed, p.worker_free,
            p.worker_live, max_slots=max_slots,
        )

    out = run_auction(problems[0])  # compile
    a = np.asarray(out.assignment)[:n_tasks]
    warm_price[0] = out.prices  # the equilibrium a live dispatcher carries
    out_w = run_auction_warm(problems[1])  # compile the warm trace
    warm_rounds = int(out_w.n_rounds)
    aw = np.asarray(out_w.assignment)[:n_tasks]
    r = np.asarray(run_rank(problems[0]))[:n_tasks]
    # depth >=10: at ~10 ms/exec the tunnel's per-round-trip jitter swamps
    # a shallow pipeline, making the slope estimate noisy by >10x. Cold
    # and warm are each the MEDIAN of 3 independent slope estimates: the
    # r4 capture read warm (12.3 ms) above cold (11.4 ms) purely on
    # single-estimate jitter — the deterministic round counts below are
    # the ground truth the medians must agree with
    def _median_of_valid(reps: list[float]):
        """Non-positive slopes are physically impossible (anti-correlated
        tunnel jitter across depths) and are EXCLUDED, not clamped — a
        clamped 0.0 median would fabricate a perfect number (the r2
        artifact's clamped \"0.0\" quantified nothing). None when no rep
        survives."""
        valid = [r for r in reps if r > 0.0]
        return (float(np.median(valid)) if valid else None), reps

    auction_ms, cold_reps = _median_of_valid(
        [_pipeline_slope_ms(run_auction, problems, 2, 10) for _ in range(3)]
    )
    auction_warm_ms, warm_reps = _median_of_valid(
        [
            _pipeline_slope_ms(run_auction_warm, problems, 2, 10)
            for _ in range(3)
        ]
    )
    # the rank kernel is ~0.1 ms: a DEEP pipeline (hundreds of execs) so
    # the signal clears tunnel jitter, and a median over 5 independent
    # slope estimates for real resolution (the r2 artifact's clamped
    # "0.0" quantified nothing)
    rank_reps = [
        max(0.0, _pipeline_slope_ms(run_rank, problems, 50, 450))
        for _ in range(5)
    ]
    rank_ms = float(np.median(rank_reps))

    # Heterogeneous leg: lognormal task costs over a mixed-speed fleet —
    # the regime where the classic cold eps-ladder measured 18.7 k rounds
    # (~18 s) on this chip. The analytic rank-dual seed + bounded rounds +
    # rank spill (sched/auction.py) solve it complete in warm_rounds.
    rng_h = np.random.default_rng(33)
    speeds_h = rng_h.uniform(0.5, 4.0, n_workers).astype(np.float32)
    base_h = rng_h.lognormal(0.0, 1.0, n_tasks).astype(np.float32)
    hetero_template = PlacementProblem.build(
        base_h, speeds_h, free, live, T=10_240, W=1_024
    )
    hetero = [
        dataclasses.replace(
            hetero_template,
            task_size=jnp.asarray(
                np.pad(base_h * (1 + i * 1e-4), (0, 10_240 - n_tasks))
            ),
        )
        for i in range(12)
    ]
    out_h = run_auction(hetero[0])  # same trace as the uniform leg
    ah = np.asarray(out_h.assignment)[:n_tasks]
    hetero_ms = _pipeline_slope_ms(run_auction, hetero, 2, 10)

    # Warm HETERO leg: THIS is where the price carry earns its keep. At
    # the uniform shape the analytic rank-dual cold seed is already
    # near-equilibrium (9 cold rounds), so warm has nothing to win there
    # (r4's warm>cold capture was jitter on a no-op); heterogeneous
    # lognormal costs are the regime where cold runs to the 64-round
    # budget with rank spill — carrying the previous instance's
    # equilibrium must beat that.
    def run_hetero_warm(p, _price=out_h.prices):
        return auction_placement(
            p.task_size, p.task_valid, p.worker_speed, p.worker_free,
            p.worker_live, max_slots=max_slots, eps=1e-3,
            init_price=_price,
        )

    out_hw = run_hetero_warm(hetero[1])  # compile warm-hetero trace
    ahw = np.asarray(out_hw.assignment)[:n_tasks]
    hetero_warm_ms = _pipeline_slope_ms(run_hetero_warm, hetero, 2, 10)

    # Quality pin for the heterogeneous leg (round-5, VERDICT r4 #5): the
    # auction is the one solver for non-separable costs, so its spilled
    # assignment must carry a makespan number exactly as config 4 pins
    # sinkhorn's — makespan on the placed subset vs the LP lower bound on
    # that same subset.
    from tpu_faas.sched.greedy import makespan
    from tpu_faas.sched.oracle import makespan_lower_bound

    def hetero_quality(assign):
        placed = assign >= 0
        ms = makespan(assign, base_h, speeds_h, max_slots)
        lb = makespan_lower_bound(
            base_h[placed], speeds_h, free, live, max_slots
        )
        return ms / lb

    hetero_makespan_vs_lp = hetero_quality(ah)
    hetero_warm_makespan_vs_lp = hetero_quality(ahw)

    cap = int(free.sum())
    sizes0 = np.full(n_tasks, 1.0, dtype=np.float32)
    return {
        "config": "auction-1k-workers-10k-tasks",
        "auction_cold_ms": (
            None if auction_ms is None else round(auction_ms, 3)
        ),
        "auction_cold_reps_ms": [round(x, 3) for x in cold_reps],
        "auction_cold_rounds": int(out.n_rounds),
        "auction_warm_ms": (
            None if auction_warm_ms is None else round(auction_warm_ms, 3)
        ),
        "auction_warm_reps_ms": [round(x, 3) for x in warm_reps],
        "auction_warm_rounds": warm_rounds,
        "warm_rounds_le_cold": bool(warm_rounds <= int(out.n_rounds)),
        "auction_hetero_makespan_vs_lp": round(hetero_makespan_vs_lp, 4),
        "auction_hetero_warm_ms": round(hetero_warm_ms, 3),
        "auction_hetero_warm_rounds": int(out_hw.n_rounds),
        "auction_hetero_warm_makespan_vs_lp": round(
            hetero_warm_makespan_vs_lp, 4
        ),
        "placed_auction_hetero_warm": int((ahw >= 0).sum()),
        "rank_match_ms": round(rank_ms, 4),
        "rank_match_reps_ms": [round(x, 4) for x in rank_reps],
        "auction_hetero_ms": round(hetero_ms, 3),
        "auction_hetero_rounds": int(out_h.n_rounds),
        "placed_auction_hetero": int((ah >= 0).sum()),
        "placed_auction": int((a >= 0).sum()),
        "placed_auction_warm": int((aw >= 0).sum()),
        "placed_rank_match": int((r >= 0).sum()),
        "expected_placed": min(n_tasks, cap),
        "greedy_host_ms": round(
            _time_host(
                lambda: host_greedy_reference(sizes0, speeds, free, live)
            )
            * 1e3,
            3,
        ),
    }


def config_4_sinkhorn_hetero() -> dict:
    """Sinkhorn placement at the HEADLINE shape (50k tasks x 4k workers,
    BASELINE's north-star scale): heterogeneous fleet, sized tasks; quality
    vs the offline bound and the host greedy. Uses the bucketed kernel —
    the dense one would need several ~800 MB [T, W] buffers; the bucketed
    one compresses the task axis via the rank-one cost structure and
    matches dense placement cost to <0.01% (tests/test_sched_sinkhorn.py)."""
    from tpu_faas.sched.greedy import host_greedy_reference, makespan
    from tpu_faas.sched.oracle import makespan_lower_bound
    from tpu_faas.sched.problem import PlacementProblem
    from tpu_faas.sched.sinkhorn import sinkhorn_placement_bucketed

    rng = np.random.default_rng(4)
    n_tasks, n_workers, max_slots = 50_000, 4_000, 8
    sizes = rng.lognormal(0.0, 1.0, n_tasks).astype(np.float32)
    speeds = rng.uniform(0.5, 4.0, n_workers).astype(np.float32)
    free = rng.integers(1, max_slots + 1, n_workers).astype(np.int32)
    live = np.ones(n_workers, dtype=bool)
    problems = [
        PlacementProblem.build(
            sizes * (1.0 + i * 1e-6), speeds, free, live, T=51_200, W=4_096
        )
        for i in range(3)
    ]
    p = problems[0]

    def run(prob):
        return sinkhorn_placement_bucketed(
            prob.task_size, prob.task_valid, prob.worker_speed,
            prob.worker_free, prob.worker_live,
            tau=0.05, n_iters=60, max_slots=max_slots,
        )

    out = run(p)  # compile
    # deep pipeline like bench.py's headline: shallow depths let tunnel
    # round-trip jitter (~tens of ms) swamp the slope for ~ms kernels
    placement_ms = max(0.0, _pipeline_slope_ms(run, problems, 10, 60))
    a = np.asarray(out.assignment)[:n_tasks]
    greedy = np.asarray(
        host_greedy_reference(sizes, speeds, np.minimum(free, max_slots), live)
    )
    # demand exceeds one-wave capacity: each placement handles a different
    # subset, so compare each makespan against the bound on ITS OWN subset
    def ratio(assign):
        placed = assign >= 0
        ms = makespan(assign, sizes, speeds, max_slots)
        lb = makespan_lower_bound(sizes[placed], speeds, free, live, max_slots)
        return ms / lb

    return {
        "config": "sinkhorn-heterogeneous",
        "placement_ms": round(placement_ms, 3),
        "placed": int((a >= 0).sum()),
        "makespan_vs_lp_bound": round(ratio(a), 4),
        "greedy_makespan_vs_lp_bound": round(ratio(greedy), 4),
        "marginal_err": float(out.marginal_err),
    }


def config_5_churn_4k() -> dict:
    """4k workers, 5% fail/rejoin per tick, device-computed redistribution."""
    from tpu_faas.sim import SimFleet

    # transport round-trip floor (~70 ms in tunneled dev environments)
    # dominates the per-tick sync wall time; production holds the device
    # locally.
    floor_ms = transport_floor_ms()

    rng = np.random.default_rng(5)
    fleet = SimFleet(
        n_workers=4_096,
        max_pending=8_192,
        rng=rng,
        hetero=True,
        time_to_expire=2.0,
    )
    sizes = rng.uniform(0.5, 4.0, 20_000).astype(np.float32)
    res = fleet.run(sizes, dt=1.0, churn=0.05, max_ticks=2_000)
    # Device-tick estimate by the SAME pipeline-slope method as every other
    # headline number (a clamped median-minus-floor subtraction reads 0.0
    # the moment the sync median sits under the floor — it quantifies the
    # tunnel, not the kernel). Measured on the post-churn fleet state the
    # sim just produced — recycled rows, mixed liveness — with a distinct
    # perturbed batch per execution so memoizing transports can't replay.
    a = fleet.arrays
    base = rng.uniform(0.5, 4.0, a.max_pending).astype(np.float32)
    tick_batches = [base * (1.0 + i * 1e-5) for i in range(64)]
    tick_reps = [
        max(0.0, _pipeline_slope_ms(a.tick, tick_batches, 10, 60))
        for _ in range(5)
    ]
    device_tick_ms = float(np.median(tick_reps))
    return {
        "config": "churn-4k-workers",
        "completed": res.completed,
        "lost": res.lost,
        "ticks": res.ticks,
        "median_tick_sync_ms": round(res.median_tick_ms, 3),
        "transport_floor_ms": round(floor_ms, 3),
        "device_tick_ms": round(device_tick_ms, 3),
        "device_tick_reps_ms": [round(x, 3) for x in tick_reps],
        "sim_makespan": round(res.makespan, 1),
    }


def config_6_batch_register() -> dict:
    """Time-to-register, batch vs single (beyond the five BASELINE configs):
    the reference's registration cost is one POST per task; /execute_batch +
    store pipelining registers a whole batch in one HTTP call and one store
    round trip. Full real stack: native/python store server over TCP,
    gateway, SDK."""
    from tpu_faas.client import FaaSClient
    from tpu_faas.core.executor import pack_params
    from tpu_faas.gateway import start_gateway_thread
    from tpu_faas.store.launch import make_store, start_store_thread

    n_tasks, n_sims = 100, 3
    store_handle = start_store_thread()
    gw = start_gateway_thread(make_store(store_handle.url))
    client = FaaSClient(gw.url)
    try:
        fid = client.register_payload("noop", "unused")
        payloads = [((i,), {}) for i in range(n_tasks)]
        single_s, batch_s = [], []
        for _ in range(n_sims):
            # symmetric timing: both windows include parameter packing
            t0 = time.perf_counter()
            for args, kwargs in payloads:
                client.execute_payload(fid, pack_params(*args, **kwargs))
            single_s.append(time.perf_counter() - t0)
            t0 = time.perf_counter()
            client.submit_many(fid, payloads)
            batch_s.append(time.perf_counter() - t0)
        single_ms = float(np.median(single_s) * 1e3)
        batch_ms = float(np.median(batch_s) * 1e3)
        return {
            "config": "batch-register-100",
            "n_tasks": n_tasks,
            "single_posts_ms": round(single_ms, 2),
            "batch_post_ms": round(batch_ms, 2),
            "speedup": round(single_ms / batch_ms, 1),
        }
    finally:
        gw.stop()
        store_handle.stop()


def _time_host(fn) -> float:
    t0 = time.perf_counter()
    fn()
    return time.perf_counter() - t0


def config_7_bid_headline() -> dict:
    """The auction's hot op at the HEADLINE bid shape (50k tasks x 32k
    slots, an implicit 6.7 GB [T, S] matrix): both backends on the real
    chip, BOTH under jit (production calls the XLA path only inside the
    jitted solver — an eager comparison would charge XLA several
    un-fused [T, S] materializations and fake an OOM). Measured v5e
    result: speed parity within run-to-run noise (~10-17 ms/round both);
    the streaming kernel's win is WORKING SET — O(T+S) vs the multi-GB
    [T, S] intermediates the fused XLA path still materializes — which is
    why 'auto' (sched/pallas_kernels.py resolve_backend) prefers it past
    XLA_CELL_BUDGET. NOTE the caveat in that module's docstring: full
    auction CONVERGENCE at this demand/supply imbalance needs thousands
    of rounds; the tick-latency kernels at headline scale are
    rank/sinkhorn — this config measures the per-round building block.

    14 distinct input batches: execution-memoizing dev tunnels replay
    repeated (executable, args) pairs for free, so a small cycled set
    fakes arbitrarily fast slopes.
    """
    import jax
    import jax.numpy as jnp

    from tpu_faas.sched.pallas_kernels import (
        bid_top2_pallas,
        bid_top2_xla,
        resolve_backend,
    )

    T, S = 51_200, 32_768
    rng = np.random.default_rng(7)
    sizes = [
        jnp.asarray(rng.lognormal(0.0, 1.0, T).astype(np.float32))
        for _ in range(14)
    ]
    inv_speed = jnp.asarray(rng.uniform(0.25, 2.0, S).astype(np.float32))
    valid = jnp.ones(S, dtype=jnp.float32)
    price = jnp.asarray(rng.uniform(0.0, 1.0, S).astype(np.float32))
    js = jnp.float32(1e-4)

    out: dict = {
        "config": "bid-top2-headline-50k-x-32k",
        "auto_resolves_to": resolve_backend(T, S),
    }
    backends = {
        "xla": jax.jit(bid_top2_xla),  # jitted like the production solver
        "pallas": bid_top2_pallas,  # jitted at definition
    }
    for backend, fn in backends.items():
        def run(s, _fn=fn):
            return _fn(s, inv_speed, valid, price, js)

        try:
            np.asarray(run(sizes[0])[0])  # compile + first
            out[f"{backend}_ms_per_round"] = round(
                _pipeline_slope_ms(run, sizes[1:], 2, 12), 3
            )
        except Exception as exc:
            out[f"{backend}_ms_per_round"] = None
            out[f"{backend}_error"] = f"{type(exc).__name__}: {str(exc)[:80]}"
    return out


def config_8_estimation() -> dict:
    """Placement quality with NO client hints: unhinted (all-1.0) vs
    operator-hinted (true sizes/speeds) vs LEARNED (the estimation loop,
    sched/estimator.py) on one mixed fleet + mixed workload. The learned
    column is the round-4 capability: the reference is size-blind
    (task_dispatcher.py:297-322) and rounds 1-3 only matched hints."""
    from tpu_faas.sched.estimator import RuntimeEstimator, fn_digest
    from tpu_faas.sched.greedy import makespan, rank_match_placement

    rng = np.random.default_rng(8)
    n_workers, n_fns, max_slots = 256, 32, 4
    n_tasks = n_workers * max_slots  # one full wave: makespans comparable
    true_speeds = rng.uniform(0.5, 4.0, n_workers).astype(np.float32)
    fn_sizes = rng.lognormal(0.0, 1.0, n_fns).astype(np.float32)

    # learning phase: the observations a live dispatcher would collect
    # (worker-measured elapsed = size/speed, with runtime jitter)
    est = RuntimeEstimator()
    wids = [f"w{i}".encode() for i in range(n_workers)]
    digests = [fn_digest(f"fn{i}") for i in range(n_fns)]
    n_obs = 4096
    for _ in range(n_obs):
        f = int(rng.integers(n_fns))
        w = int(rng.integers(n_workers))
        est.observe(
            digests[f],
            float(fn_sizes[f] / true_speeds[w] * rng.uniform(0.95, 1.05)),
            wids[w],
        )

    task_fn = rng.integers(0, n_fns, n_tasks)
    true_sizes = fn_sizes[task_fn].astype(np.float32)
    valid = np.ones(n_tasks, dtype=bool)
    free = np.full(n_workers, max_slots, dtype=np.int32)
    live = np.ones(n_workers, dtype=bool)
    learned_sizes = np.array(
        [est.size_for(digests[int(f)]) or est.default_size()
         for f in task_fn],
        dtype=np.float32,
    )
    learned_speeds = np.array(
        [est.speed_for(w) for w in wids], dtype=np.float32
    )

    def place(sizes, speeds):
        a = np.asarray(
            rank_match_placement(
                np.asarray(sizes, dtype=np.float32), valid,
                np.asarray(speeds, dtype=np.float32), free, live,
                max_slots=max_slots,
            )
        )
        return makespan(a, true_sizes, true_speeds, max_slots=max_slots)

    ms_blind = place(np.ones(n_tasks), np.ones(n_workers))
    ms_hinted = place(true_sizes, true_speeds)
    ms_learned = place(learned_sizes, learned_speeds)

    # -- mixed-param leg (round 5): ONE function whose runtime varies 64x
    # by parameter (the reference corpus shape — sleep_n/arithmetic(n),
    # client_performance.py:19-92). The fn-level EWMA collapses every
    # variant to the historical mean; the exact-param level recovers the
    # per-variant runtime, and the makespans quantify the difference.
    est_p = RuntimeEstimator()
    d_mixed = fn_digest("mixed-fn")
    variant_sizes = [0.125, 1.0, 8.0]
    pdig = [fn_digest(f"variant{i}") for i in range(len(variant_sizes))]
    for _ in range(n_obs // 2):
        v = int(rng.integers(len(variant_sizes)))
        w = int(rng.integers(n_workers))
        est_p.observe(
            d_mixed,
            float(variant_sizes[v] / true_speeds[w] * rng.uniform(0.97, 1.03)),
            wids[w],
            pdig[v],
            64,
        )
    task_v = rng.integers(0, len(variant_sizes), n_tasks)
    true_sizes_p = np.array(
        [variant_sizes[int(v)] for v in task_v], np.float32
    )
    param_aware = np.array(
        [est_p.size_for(d_mixed, pdig[int(v)], 64) for v in task_v],
        np.float32,
    )
    fn_collapsed = np.array(
        [est_p.size_for(d_mixed) for _ in task_v], np.float32
    )
    speeds_p = np.array([est_p.speed_for(w) for w in wids], np.float32)

    def place_p(sizes):
        a = np.asarray(
            rank_match_placement(
                np.asarray(sizes, dtype=np.float32), valid, speeds_p,
                np.full(n_workers, max_slots, np.int32), live,
                max_slots=max_slots,
            )
        )
        return makespan(a, true_sizes_p, true_speeds, max_slots=max_slots)

    ms_param_aware = place_p(param_aware)
    ms_fn_collapsed = place_p(fn_collapsed)

    return {
        "config": "estimation-unhinted-vs-hinted-vs-learned",
        "n_workers": n_workers,
        "n_tasks": n_tasks,
        "n_observations": n_obs,
        "makespan_unhinted": round(ms_blind, 3),
        "makespan_hinted": round(ms_hinted, 3),
        "makespan_learned": round(ms_learned, 3),
        "learned_vs_unhinted": round(ms_blind / ms_learned, 2),
        "learned_vs_hinted": round(ms_learned / ms_hinted, 3),
        "mixed_param_makespan_param_aware": round(ms_param_aware, 3),
        "mixed_param_makespan_fn_collapsed": round(ms_fn_collapsed, 3),
        "param_aware_vs_fn_collapsed": round(
            ms_fn_collapsed / ms_param_aware, 2
        ),
    }


def config_9_host_dispatch() -> dict:
    """Host data-plane throughput: intake -> device tick -> act, end to end
    against the in-process RESP store server (real TCP round trips, real
    RESP parsing) — the path the device-tick configs never see because they
    synthesize tasks in memory. Workers are registered directly on the
    ROUTER mirror (no subprocesses): dispatch sends to peers that never
    connected are dropped by ZMQ, so the measurement isolates the HOST cost
    of acting on a device decision — announce drain, one pipelined record
    fetch, the device step, the send loop, and the coalesced RUNNING flush.

    Runs the SAME measurement as two legs against fresh stacks: leg
    "dict" (classic PendingTask intake over a plain RESP connection), then
    leg "columnar" (``--columnar`` arena intake over a binbatch-negotiated
    connection). Each leg makes TWO passes over n_tasks fresh tasks:
    pass 1 uninstrumented — the ``tasks_per_s`` figure, comparable with
    pre-columnar revisions of this config, which never profiled — and
    pass 2 under cProfile, publishing its top-10 cumulative functions
    (``host_profile`` / ``host_profile_dict``) so the BENCH record
    attributes WHERE the cycles went — codec vs bookkeeping vs device —
    not just that the ratio moved. The mid-run /metrics scrape happens
    during pass 1. Announces are pre-buffered into the dispatcher's
    backlog before each pass's clock starts: pub/sub delivery rides the
    store server thread, and its GIL race with the tick loop used to
    dominate run-to-run variance — the timed loop measures the host
    dispatch path alone.

    ``host_dispatch_tasks_per_s`` — the key CI asserts on — is the
    columnar leg's headline; the control leg publishes
    ``host_dispatch_tasks_per_s_dict``. Shape via
    TPU_FAAS_BENCH_HOST_SHAPE="tasks,workers,procs" (fleet capacity must
    cover the task count: no results flow back to free slots); the CI
    smoke lane runs "200,64,4".
    """
    import cProfile
    import os
    import pstats
    import urllib.request

    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.obs.expofmt import parse_exposition, require_series
    from tpu_faas.store.launch import make_store, start_store_thread
    from tpu_faas.worker import messages as m

    #: series the dispatcher scrape must always carry (eagerly registered,
    #: so absence means a regression in the obs wiring, not "no traffic")
    required_series = [
        "tpu_faas_dispatcher_pending_tasks",
        "tpu_faas_dispatcher_inflight_tasks",
        "tpu_faas_dispatcher_workers_registered",
        "tpu_faas_dispatcher_tasks_dispatched_total",
        "tpu_faas_dispatcher_results_total",
        "tpu_faas_task_stage_seconds",
        "tpu_faas_span_seconds",
        "tpu_faas_jit_recompiles_total",
        "tpu_faas_tick_shape",
        "tpu_faas_store_round_trips_total",
    ]

    shape = os.environ.get("TPU_FAAS_BENCH_HOST_SHAPE", "20000,4096,8")
    n_tasks, n_workers, n_procs = (int(x) for x in shape.split(","))

    def top_profile(prof: cProfile.Profile, limit: int = 10) -> list[dict]:
        """Top ``limit`` functions by cumulative time, as JSON-able rows."""
        st = pstats.Stats(prof)
        st.sort_stats("cumulative")
        out: list[dict] = []
        for func in st.fcn_list or []:
            _cc, nc, tt, ct, _callers = st.stats[func]
            fname, line, name = func
            out.append(
                {
                    "func": f"{os.path.basename(fname)}:{line}({name})",
                    "cum_s": round(ct, 4),
                    "tot_s": round(tt, 4),
                    "calls": int(nc),
                }
            )
            if len(out) >= limit:
                break
        return out

    def run_leg(columnar: bool) -> dict:
        # a fresh store server + dispatcher per leg: the second leg must
        # not inherit the first's announce backlog, record state, or TCP
        # connections, or the comparison measures teardown residue
        handle = start_store_thread()
        store = make_store(handle.url, binbatch=columnar)
        feeder = make_store(handle.url)
        disp = TpuPushDispatcher(
            ip="127.0.0.1",
            port=0,
            store=store,
            max_workers=n_workers,
            max_pending=min(8192, max(n_tasks, 64)),
            # two measurement passes, no results ever freeing entries:
            # the table must hold 2 x n_tasks plus headroom
            max_inflight=2 * n_tasks + 1024,
            max_slots=n_procs,
            recover_queued=False,
            columnar=columnar,
            # the bench workers are ROUTER mirrors that never heartbeat:
            # letting the 10s default purge them mid-run would swap the
            # measurement for a reclaim cascade (profiled legs run longer
            # than the TTL at the full shape)
            time_to_expire=1e9,
        )
        try:
            for i in range(n_workers):
                disp._handle(
                    f"bench-w{i}".encode(),
                    m.REGISTER,
                    {"num_processes": n_procs},
                )
            # compile the device step OUTSIDE the timed window, before any
            # task exists (shapes are padded/static, so the empty tick
            # compiles the same trace the loaded ticks replay)
            disp.tick()
            stats_server = disp.serve_stats(0)
            stats_port = stats_server.server_address[1]
            warm = disp.n_dispatched  # 0 unless the empty tick found strays
            need = required_series + (
                [
                    "tpu_faas_columnar_intake_total",
                    "tpu_faas_columnar_arena_occupancy",
                ]
                if columnar
                else []
            )

            def feed(prefix: str) -> None:
                # one pipelined batch create per chunk: feeding must not
                # become the bottleneck being measured
                chunk = 2_000
                for lo in range(0, n_tasks, chunk):
                    feeder.create_tasks(
                        [
                            (f"{prefix}{i}", "F", "P")
                            for i in range(lo, min(lo + chunk, n_tasks))
                        ]
                    )

            def prebuffer() -> None:
                # pre-buffer every announce BEFORE the timed window:
                # announce delivery rides the store server thread, and at
                # full shape its pub/sub push races the busy tick loop
                # for the GIL — run-to-run that race is worth +-30% of
                # wall clock. Parking the whole stream in the
                # dispatcher's announce backlog first makes the timed
                # loop measure the host dispatch path itself (record
                # fetch, decode, device step, send loop), identically
                # for both legs.
                buffered: list[str] = []
                buffer_deadline = time.perf_counter() + 120.0
                while (
                    len(buffered) < n_tasks
                    and time.perf_counter() < buffer_deadline
                ):
                    got = disp.drain_announces(n_tasks - len(buffered))
                    if not got:
                        time.sleep(0.005)
                    buffered.extend(got)
                disp._announce_backlog.extend(buffered)

            # PASS 1 — unprofiled: the throughput figure. cProfile costs
            # the serve loop more than half its throughput at this shape,
            # so the headline number must come from an uninstrumented run
            # to stay comparable with the pre-columnar revisions of this
            # config (which never profiled).
            feed("bench-t")
            prebuffer()
            rounds: list[int] = []
            scrape_ok: bool | None = None
            scrape_missing: list[str] = []
            scrape_error = ""
            pass1_goal = warm + n_tasks
            t0 = time.perf_counter()
            deadline = t0 + 600.0
            while (
                disp.n_dispatched < pass1_goal
                and time.perf_counter() < deadline
            ):
                rt0 = store.n_round_trips
                disp.tick()
                rounds.append(store.n_round_trips - rt0)
                if (
                    scrape_ok is None
                    and disp.n_dispatched >= warm + n_tasks // 2
                ):
                    # mid-run scrape: the exposition must be valid and
                    # complete WHILE the hot loop runs, not just at rest
                    try:
                        with urllib.request.urlopen(
                            f"http://127.0.0.1:{stats_port}/metrics",
                            timeout=10,
                        ) as resp:
                            families = parse_exposition(
                                resp.read().decode("utf-8")
                            )
                        scrape_missing = require_series(families, need)
                        scrape_ok = not scrape_missing
                    except Exception as exc:  # malformed exposition incl.
                        scrape_ok = False
                        scrape_error = f"{type(exc).__name__}: {exc}"
            elapsed = time.perf_counter() - t0
            dispatched = disp.n_dispatched - warm

            # PASS 2 — profiled: identical work on fresh task ids, for
            # the host_profile ATTRIBUTION only (where the cycles go:
            # codec vs bookkeeping vs device). Its wall clock is
            # deliberately not reported. Pass 1 consumed one fleet slot
            # per task and no results flow back in this harness, so the
            # free-slot lanes are restored first — otherwise pass 2
            # starves on leftover capacity instead of measuring.
            disp.arrays.worker_free[:] = n_procs
            feed("bench2-t")
            prebuffer()
            pass2_goal = disp.n_dispatched + n_tasks
            pass2_deadline = time.perf_counter() + 600.0
            prof = cProfile.Profile()
            prof.enable()
            while (
                disp.n_dispatched < pass2_goal
                and time.perf_counter() < pass2_deadline
            ):
                disp.tick()
            prof.disable()
            spans = disp.tracer.summary()
            arena = fallback = 0
            if columnar:
                # counters span both passes (2 x n_tasks through intake)
                arena = int(
                    disp.m_columnar_intake.labels(lane="arena").value
                )
                fallback = int(
                    disp.m_columnar_intake.labels(lane="fallback").value
                )
            return {
                "dispatched": dispatched,
                "tasks_per_s": round(dispatched / max(elapsed, 1e-9), 1),
                "ticks": len(rounds) + 1,
                "store_round_trips_per_tick_max": max(rounds, default=0),
                "store_round_trips_per_tick": rounds[:32],
                "intake_p50_ms": round(
                    spans.get("intake", {}).get("p50", 0.0) * 1e3, 3
                ),
                "act_p50_ms": round(
                    spans.get("act", {}).get("p50", 0.0) * 1e3, 3
                ),
                "device_tick_p50_ms": round(
                    spans.get("device_tick", {}).get("p50", 0.0) * 1e3, 3
                ),
                "jit_recompiles": disp.profiler.n_signatures,
                "metrics_scrape_ok": bool(scrape_ok),
                "metrics_missing": scrape_missing,
                "metrics_scrape_error": scrape_error,
                "columnar_intake_arena": arena,
                "columnar_intake_fallback": fallback,
                "host_profile": top_profile(prof),
            }
        finally:
            disp.socket.close(linger=0)
            disp.close()
            feeder.close()
            handle.stop()

    # control leg FIRST (conservative ordering: any warm-process advantage
    # — allocator pools, imported modules, branch caches — accrues to the
    # leg we are arguing AGAINST)
    dict_leg = run_leg(columnar=False)
    col_leg = run_leg(columnar=True)
    return {
        "config": "host-dispatch-throughput",
        "shape": {"tasks": n_tasks, "workers": n_workers, "procs": n_procs},
        "dispatched": col_leg["dispatched"],
        "dispatched_dict": dict_leg["dispatched"],
        "host_dispatch_tasks_per_s": col_leg["tasks_per_s"],
        "host_dispatch_tasks_per_s_dict": dict_leg["tasks_per_s"],
        "columnar_speedup": round(
            col_leg["tasks_per_s"]
            / max(dict_leg["tasks_per_s"], 1e-9),
            2,
        ),
        "ticks": col_leg["ticks"],
        "store_round_trips_per_tick_max": col_leg[
            "store_round_trips_per_tick_max"
        ],
        "store_round_trips_per_tick": col_leg["store_round_trips_per_tick"],
        "intake_p50_ms": col_leg["intake_p50_ms"],
        "act_p50_ms": col_leg["act_p50_ms"],
        "device_tick_p50_ms": col_leg["device_tick_p50_ms"],
        "intake_p50_ms_dict": dict_leg["intake_p50_ms"],
        "act_p50_ms_dict": dict_leg["act_p50_ms"],
        "jit_recompiles": col_leg["jit_recompiles"],
        # every task through the arena, none spilled to the dict fallback,
        # or the leg did not measure the columnar plane
        "columnar_intake_arena": col_leg["columnar_intake_arena"],
        "columnar_intake_fallback": col_leg["columnar_intake_fallback"],
        # the mid-run /metrics scrape verdicts (False on malformed
        # exposition or a scrape that never happened; the missing list
        # names absent required series)
        "metrics_scrape_ok": col_leg["metrics_scrape_ok"],
        "metrics_missing": col_leg["metrics_missing"],
        "metrics_scrape_error": col_leg["metrics_scrape_error"],
        "metrics_scrape_ok_dict": dict_leg["metrics_scrape_ok"],
        "metrics_missing_dict": dict_leg["metrics_missing"],
        # top-10 cumulative serve-loop functions per leg (cProfile)
        "host_profile": col_leg["host_profile"],
        "host_profile_dict": dict_leg["host_profile"],
    }


def config_10_overload() -> dict:
    """Overload robustness (config 10): offered load >= 3x fleet capacity
    against the full real stack — store server, gateway WITH the admission
    controller engaged, tpu-push dispatcher publishing the saturation
    signal, real push-worker subprocesses running sleep tasks.

    Phase 1 measures the unloaded throughput (submissions paced under the
    brownout threshold). Phase 2 offers ~3x the fleet's drain rate for a
    fixed window with NO client-side retries, records every admitted task
    id and every reject (asserting the Retry-After header is present),
    then drains: the row proves (a) nonzero rejects — admission actually
    engaged, (b) zero admitted tasks lost — every admitted id reached a
    terminal state, (c) goodput under overload vs the unloaded
    throughput (the graceful-degradation ratio; the acceptance bar is
    >= 0.85), (d) all rejects carried Retry-After. A slice of the burst
    carries a short queue ``deadline``, exercising EXPIRED shedding end
    to end (count reported; timing-dependent, not asserted).

    Shape via TPU_FAAS_BENCH_OVERLOAD_SHAPE="workers,procs,task_ms,
    window_s" (default "4,2,100,10"); the CI smoke lane runs "2,2,60,6".
    """
    import os

    import requests as _requests

    from tpu_faas.admission import AdmissionController
    from tpu_faas.admission.controller import AdmissionConfig
    from tpu_faas.bench.harness import _spawn_worker
    from tpu_faas.client import FaaSClient
    from tpu_faas.core.executor import pack_params
    from tpu_faas.core.serialize import serialize
    from tpu_faas.core.task import TaskStatus
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.gateway import start_gateway_thread
    from tpu_faas.store.launch import make_store, start_store_thread
    from tpu_faas.workloads import sleep_task

    import threading as _threading

    shape = os.environ.get("TPU_FAAS_BENCH_OVERLOAD_SHAPE", "4,2,100,10")
    n_workers, n_procs, task_ms, window_s = (
        float(x) for x in shape.split(",")
    )
    n_workers, n_procs = int(n_workers), int(n_procs)
    slots = n_workers * n_procs
    task_s = task_ms / 1e3
    capacity_rate = slots / task_s  # tasks/s the fleet can drain
    bound = 4 * slots  # admission bound: ~4 queued waves of work

    handle = start_store_thread()
    admission = AdmissionController(
        AdmissionConfig(max_system_inflight=bound)
    )
    gw = start_gateway_thread(make_store(handle.url), admission=admission)
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=make_store(handle.url),
        max_workers=max(64, n_workers),
        max_pending=max(256, 2 * bound),
        max_inflight=4096,
        max_slots=n_procs,
        tick_period=0.005,
        time_to_expire=5.0,
        rescan_period=2.0,
    )
    disp_thread = _threading.Thread(target=disp.start, daemon=True)
    disp_thread.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker(
            "push_worker", n_procs, url, "--hb", "--hb-period", "0.5"
        )
        for _ in range(n_workers)
    ]
    client = FaaSClient(gw.url)  # phase-1 client (retries on)
    raw = _requests.Session()  # phase-2: raw posts, NO retries
    try:
        time.sleep(1.5)  # workers register
        fid = client.register_payload("sleep", serialize(sleep_task))
        payload = pack_params(task_s)

        # -- phase 1: unloaded throughput (stay under brownout) -----------
        n0_wave = max(1, bound // 2)
        # untimed warmup wave: worker pool spawn + first dill decode would
        # otherwise be billed to the unloaded number and fake a flattering
        # goodput ratio
        for h in client.submit_many(fid, [((task_s,), {})] * n0_wave):
            h.result(timeout=120.0)
        n0 = 0
        t0 = time.perf_counter()
        for _ in range(3):
            handles = client.submit_many(
                fid, [((task_s,), {})] * n0_wave
            )
            for h in handles:
                h.result(timeout=120.0)
            n0 += n0_wave
        unloaded_tps = n0 / (time.perf_counter() - t0)

        # -- phase 2: 3x offered load, no retries -------------------------
        offered_rate = 3.0 * capacity_rate
        burst = max(1, int(round(offered_rate / 8)))  # 8 bursts/s
        deadline_s = max(0.2, bound / (3.0 * capacity_rate))
        admitted: list[str] = []
        deadline_ids: list[str] = []
        offered = rejected = with_retry_after = 0
        t_burst0 = time.perf_counter()
        i_burst = 0
        while time.perf_counter() - t_burst0 < window_s:
            body = {
                "function_id": fid,
                "payloads": [payload] * burst,
            }
            if i_burst % 4 == 3:
                # the deadline slice: short submit-TTL under a saturated
                # queue — EXPIRED shedding end to end
                body["deadlines"] = [deadline_s] * burst
            r = raw.post(f"{gw.url}/execute_batch", json=body, timeout=30)
            offered += burst
            if r.status_code == 200:
                ids = r.json()["task_ids"]
                admitted.extend(ids)
                if "deadlines" in body:
                    deadline_ids.extend(ids)
            elif r.status_code in (429, 503):
                rejected += burst
                if r.headers.get("Retry-After"):
                    with_retry_after += burst
            else:
                r.raise_for_status()
            i_burst += 1
            # pace the OFFERED load (not the admitted load)
            sleep_until = t_burst0 + i_burst * burst / offered_rate
            pause = sleep_until - time.perf_counter()
            if pause > 0:
                time.sleep(pause)

        # -- drain: every admitted task must reach a terminal state -------
        store = make_store(handle.url)
        deadline_wall = time.monotonic() + max(60.0, 20 * window_s)
        statuses: dict[str, str] = {}
        pending_ids = list(admitted)
        while pending_ids and time.monotonic() < deadline_wall:
            got = store.hget_many(pending_ids, "status")
            still = []
            for tid, status in zip(pending_ids, got):
                if status is not None and TaskStatus.terminal_str(status):
                    statuses[tid] = status
                else:
                    still.append(tid)
            pending_ids = still
            if pending_ids:
                time.sleep(0.25)
        t_done = time.perf_counter()
        store.close()

        completed = sum(
            1 for s in statuses.values() if s == str(TaskStatus.COMPLETED)
        )
        expired = sum(
            1 for s in statuses.values() if s == str(TaskStatus.EXPIRED)
        )
        goodput = completed / max(t_done - t_burst0, 1e-9)
        return {
            "config": "overload-3x-admission",
            "shape": {
                "workers": n_workers,
                "procs": n_procs,
                "task_ms": task_ms,
                "window_s": window_s,
                "bound": bound,
            },
            "capacity_tasks_per_s": round(capacity_rate, 1),
            "offered_tasks_per_s": round(offered_rate, 1),
            "unloaded_tasks_per_s": round(unloaded_tps, 1),
            "offered": offered,
            "admitted": len(admitted),
            "rejected": rejected,
            "rejects_with_retry_after": with_retry_after,
            "admitted_lost": len(pending_ids),
            "completed": completed,
            "expired": expired,
            "deadline_slice": len(deadline_ids),
            "overload_goodput_tasks_per_s": round(goodput, 1),
            "goodput_ratio": round(goodput / max(unloaded_tps, 1e-9), 3),
            "gateway_stats_admission": _requests.get(
                f"{gw.url}/stats", timeout=10
            ).json()["admission"],
        }
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        disp.stop()
        disp_thread.join(timeout=10)
        gw.stop()
        handle.stop()


def config_11_payload_plane() -> dict:
    """Payload plane (config 11): repeated-fn host throughput and store
    bytes/task, inline vs content-addressed — the full real submit path
    (store server over TCP, gateway, HTTP batch submits) into a tpu-push
    dispatcher with mirror workers on the ROUTER (config-9 style: sends to
    never-connected peers are dropped, isolating the host cost).

    One function of ``payload_bytes`` serialized size repeats across every
    task — the shape the payload plane exists for (a 50k burst of one
    function). Two legs, identical except the gateway's ``payload_plane``
    flag: the row reports store wire bytes per dispatched task for each
    (the blob leg writes the body once, records carry a 64-char digest),
    end-to-end host dispatch throughput, the dispatcher blob-cache hit
    rate (mirror workers alternate legacy/blob-capable, so the legacy
    half exercises inline materialization from the cache), and the
    worker-wire payload bytes per task (the capable half ships digests).

    Shape via TPU_FAAS_BENCH_PAYLOAD_SHAPE="tasks,workers,procs,
    payload_bytes" — fleet capacity (workers x procs) must cover the task
    count, exactly as in config 9: mirror workers never return results,
    so no slot is ever refilled. Default "20000,4096,8,8192"; the CI
    smoke lane runs "1000,256,4,4096" (the PR-3 comparison shape) and
    asserts a nonzero blob-cache hit rate plus store bytes/task below
    the inline leg.
    """
    import os

    import requests as _requests

    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.gateway import start_gateway_thread
    from tpu_faas.store.launch import make_store, start_store_thread
    from tpu_faas.worker import messages as m

    shape = os.environ.get(
        "TPU_FAAS_BENCH_PAYLOAD_SHAPE", "20000,4096,8,8192"
    )
    n_tasks, n_workers, n_procs, payload_bytes = (
        int(x) for x in shape.split(",")
    )
    fn_payload = "A" * payload_bytes  # opaque to every hop measured here
    param = "P" * 64

    def run_leg(plane: bool) -> dict:
        handle = start_store_thread()
        gw_store = make_store(handle.url)
        disp_store = make_store(handle.url)
        gw = start_gateway_thread(gw_store, payload_plane=plane)
        disp = TpuPushDispatcher(
            ip="127.0.0.1",
            port=0,
            store=disp_store,
            max_workers=n_workers,
            max_pending=min(8192, max(n_tasks, 64)),
            max_inflight=max(2 * n_tasks, 1024),
            max_slots=n_procs,
            recover_queued=False,
        )
        http = _requests.Session()
        try:
            # mirror fleet: majority blob-capable (the steady state the
            # plane is built for) with a 1-in-8 LEGACY minority, which
            # forces inline materialization through the dispatcher blob
            # cache — both resolution paths stay measured, and the cache
            # hit rate the CI lane asserts on comes from real traffic
            for i in range(n_workers):
                reg = {"num_processes": n_procs}
                if i % 8:
                    reg["caps"] = list(m.WORKER_CAPS)
                disp._handle(f"bench-w{i}".encode(), m.REGISTER, reg)
            disp.tick()  # compile the device step outside the timed window
            r = http.post(
                f"{gw.url}/register_function",
                json={"name": "blobfn", "payload": fn_payload},
            )
            r.raise_for_status()
            fid = r.json()["function_id"]
            bytes0 = gw_store.n_bytes_sent + disp_store.n_bytes_sent
            wire0 = disp.m_payload_bytes.value
            t0 = time.perf_counter()
            submitted = 0
            chunk = 2_000
            while submitted < n_tasks:
                n = min(chunk, n_tasks - submitted)
                # raw posts, no idempotency keys: both legs ride the
                # single-pipeline create_tasks path symmetrically
                r = http.post(
                    f"{gw.url}/execute_batch",
                    json={"function_id": fid, "payloads": [param] * n},
                    timeout=120,
                )
                r.raise_for_status()
                submitted += n
            submit_s = time.perf_counter() - t0
            # dispatch window timed SEPARATELY so tasks_per_s is the same
            # quantity config 9 reports (intake -> device -> act), directly
            # comparable with its headline
            t1 = time.perf_counter()
            deadline = t1 + 600.0
            while (
                disp.n_dispatched < n_tasks
                and time.perf_counter() < deadline
            ):
                disp.tick()
            elapsed = time.perf_counter() - t1
            store_bytes = (
                gw_store.n_bytes_sent + disp_store.n_bytes_sent - bytes0
            )
            cache = disp.blob_cache
            return {
                "dispatched": disp.n_dispatched,
                "submit_s": round(submit_s, 3),
                "tasks_per_s": round(disp.n_dispatched / max(elapsed, 1e-9), 1),
                "store_bytes_per_task": round(store_bytes / max(n_tasks, 1), 1),
                "worker_wire_payload_bytes_per_task": round(
                    (disp.m_payload_bytes.value - wire0) / max(n_tasks, 1), 1
                ),
                "blob_cache_hits": cache.hits,
                "blob_cache_hit_rate": round(
                    cache.hits / max(cache.hits + cache.misses, 1), 4
                ),
            }
        finally:
            disp.socket.close(linger=0)
            disp.close()
            gw.stop()
            handle.stop()

    inline = run_leg(False)
    blob = run_leg(True)
    return {
        "config": "payload-plane-repeated-fn",
        "shape": {
            "tasks": n_tasks,
            "workers": n_workers,
            "procs": n_procs,
            "payload_bytes": payload_bytes,
        },
        "inline": inline,
        "blob": blob,
        # the acceptance headline: store wire bytes per dispatched task,
        # content-addressed vs inline (>= 5x expected on this shape)
        "store_bytes_per_task_reduction_x": round(
            inline["store_bytes_per_task"]
            / max(blob["store_bytes_per_task"], 1e-9),
            2,
        ),
        "host_dispatch_tasks_per_s": blob["tasks_per_s"],
    }


def _latency_leg(
    n_workers: int,
    n_procs: int,
    n_tasks: int,
    concurrency: int,
    express: bool,
    tick_period: float = 0.005,
) -> dict:
    """One closed-loop latency leg against a FRESH full real stack (store
    server over TCP, --trace gateway, tpu-push dispatcher, real
    push-worker subprocesses running a no-op function).

    ``express=False`` is the POLLING leg — the reference's client
    behavior ROADMAP item 2 calls the polling floor: each submitter
    polls ``GET /result?wait=0`` on a 10 ms pacing sleep until terminal.
    ``express=True`` is the EXPRESS leg — dispatcher ``--express``
    (inline result announces + event-driven intake) and the SDK's
    pacing-free long-poll, so a result's delivery path is
    worker → dispatcher write+announce → gateway inline forward → parked
    reply, with no poll cadence anywhere."""
    import threading as _threading

    import requests as _requests

    from tpu_faas.client import FaaSClient
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.gateway import start_gateway_thread
    from tpu_faas.obs.expofmt import parse_exposition, require_series
    from tpu_faas.store.launch import make_store, start_store_thread
    from tpu_faas.bench.harness import _spawn_worker
    from tpu_faas.workloads import no_op
    from tpu_faas.core.task import TaskStatus

    #: families the scrape must carry now that the latency-SLO plane is
    #: wired (absence = obs-wiring regression, not "no traffic")
    required_series = [
        "tpu_faas_task_e2e_seconds",
        "tpu_faas_slo_burn_rate",
        "tpu_faas_slo_good_ratio",
        "tpu_faas_slo_target_ratio",
        "tpu_faas_slo_threshold_seconds",
        "tpu_faas_slo_source_present",
        "tpu_faas_trace_duplicate_events_total",
        "tpu_faas_trace_spans_dropped_total",
        "tpu_faas_gateway_requests_total",
        "tpu_faas_gateway_result_served_total",
    ]

    handle = start_store_thread()
    gw = start_gateway_thread(make_store(handle.url), trace=True)
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=make_store(handle.url),
        max_workers=max(64, n_workers),
        max_pending=max(256, 2 * n_tasks),
        max_inflight=4096,
        max_slots=n_procs,
        tick_period=tick_period,
        express=express,
    )
    disp_thread = _threading.Thread(target=disp.start, daemon=True)
    disp_thread.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker(
            "push_worker", n_procs, url, "--hb", "--hb-period", "0.5"
        )
        for _ in range(n_workers)
    ]
    setup = FaaSClient(gw.url)
    try:
        time.sleep(1.5)  # workers register
        from tpu_faas.core.serialize import serialize

        fid = setup.register_payload("no_op", serialize(no_op))
        # warmup OUTSIDE the measured window: pool spawn + first dill
        # decode + announce-path warm; result() long-polls at the gateway
        for h in setup.submit_many(fid, [((), {})] * (2 * concurrency)):
            h.result(timeout=120.0)

        def _served_counts() -> dict[str, float]:
            got = {"inline": 0.0, "store": 0.0}
            try:
                fam = parse_exposition(
                    _requests.get(f"{gw.url}/metrics", timeout=10).text
                ).get("tpu_faas_gateway_result_served_total")
                for sample in fam.samples if fam is not None else []:
                    src = sample.labels.get("source")
                    if src in got:
                        got[src] = sample.value
            except Exception:
                pass
            return got

        # the warmup's deliveries must not dilute the measured window's
        # delivery-source attribution: baseline now, report the delta
        served_base = _served_counts()

        latencies: list[float] = []
        task_ids: list[str] = []
        lat_lock = _threading.Lock()

        def _await_polling(client: FaaSClient, task_id: str) -> None:
            # the reference-era wait loop: immediate-reply polls paced by
            # a 10 ms sleep — the client-side floor the express leg kills
            deadline = time.monotonic() + 120.0
            while True:
                status, _payload = client.raw_result(task_id, wait=0.0)
                if TaskStatus(status).is_terminal():
                    return
                if time.monotonic() > deadline:
                    raise TimeoutError(task_id)
                time.sleep(0.01)

        def closed_loop(count: int) -> None:
            # one client (= one connection pool) per submitter thread
            client = FaaSClient(gw.url, trace=True)
            for _ in range(count):
                t0 = time.perf_counter()
                h = client.submit(fid)
                if express:
                    h.result(timeout=120.0)
                else:
                    _await_polling(client, h.task_id)
                dt = time.perf_counter() - t0
                with lat_lock:
                    latencies.append(dt)
                    task_ids.append(h.task_id)

        # the remainder is spread over the first threads so the lane runs
        # EXACTLY shape.tasks tasks for any shape (CI asserts equality)
        threads = [
            _threading.Thread(
                target=closed_loop,
                args=(
                    n_tasks // concurrency
                    + (1 if i < n_tasks % concurrency else 0),
                ),
            )
            for i in range(concurrency)
        ]
        t_run0 = time.perf_counter()
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        run_s = time.perf_counter() - t_run0

        # -- strict-grammar scrape + SLO snapshot (post-run, traffic in) --
        scrape_missing: list[str] = []
        scrape_error = ""
        families: dict = {}
        try:
            r = _requests.get(f"{gw.url}/metrics", timeout=10)
            families = parse_exposition(r.text)
            scrape_missing = require_series(families, required_series)
            scrape_ok = not scrape_missing
        except Exception as exc:
            scrape_ok = False
            scrape_error = f"{type(exc).__name__}: {exc}"
        # degrade like the scrape above: a stalled/reset /slo fetch must
        # not crash the leg after every task already completed
        try:
            slo_snapshot = _requests.get(f"{gw.url}/slo", timeout=10).json()
        except Exception as exc:
            slo_snapshot = {"error": f"{type(exc).__name__}: {exc}"}

        # -- per-stage breakdown from the assembled cross-process traces --
        # sample a bounded slice; spans flush on ~0.25 s cadences, so give
        # the tail a moment and re-fetch until assembly stops growing
        sample = task_ids[-min(len(task_ids), 200):]
        stage_durs: dict[str, list[float]] = {}
        stages_seen: list[int] = []
        processes_max: list[str] = []
        uncovered: list[float] = []
        deadline = time.monotonic() + 10.0
        timelines: dict[str, dict] = {}
        while time.monotonic() < deadline:
            for tid in sample:
                # a fully-assembled timeline never shrinks — stop
                # re-fetching it (at 200 sampled ids the poll would
                # otherwise hammer the very gateway it just measured with
                # hundreds of redundant GETs per 0.5 s round)
                old = timelines.get(tid)
                if old is not None and old["n_stages"] >= 9:
                    continue
                r = _requests.get(f"{gw.url}/trace/{tid}", timeout=10)
                if r.status_code != 200:
                    continue
                tl = r.json()
                if old is None or tl["n_stages"] > old["n_stages"]:
                    timelines[tid] = tl
            full = [t for t in timelines.values() if t["n_stages"] >= 9]
            if len(full) >= max(1, len(sample) // 2):
                break
            time.sleep(0.5)
        for tl in timelines.values():
            stages_seen.append(tl["n_stages"])
            if len(tl["processes"]) > len(processes_max):
                processes_max = tl["processes"]
            if "uncovered_s" in tl:
                uncovered.append(tl["uncovered_s"])
            for s in tl["spans"]:
                stage_durs.setdefault(
                    f"{s['process']}:{s['stage']}", []
                ).append(s["duration_s"])

        def p(vals: list[float], q: float) -> float:
            return float(np.percentile(vals, q)) if vals else 0.0

        stage_p99_ms = {
            stage: round(p(durs, 99) * 1e3, 3)
            for stage, durs in sorted(stage_durs.items())
        }
        floor_stage = (
            max(stage_p99_ms, key=stage_p99_ms.get) if stage_p99_ms else None
        )

        # express attribution: how many terminal deliveries the gateway
        # served from the inline forward vs a store read (the counter is
        # the proof the express lane actually carried the leg), plus the
        # event-driven-intake pin — the dispatcher's announce_wait span
        # (gateway submit stamp -> announce drained) must sit BELOW the
        # tick period when intake is event-driven, ON it when tick-cadence
        served_now = _served_counts()
        served = {
            src: max(0.0, served_now[src] - served_base[src])
            for src in served_now
        }
        n_served = served["inline"] + served["store"]
        return {
            "leg": "express" if express else "polling",
            "express": express,
            "tick_period_ms": round(tick_period * 1e3, 3),
            "completed": len(latencies),
            "run_s": round(run_s, 2),
            "closed_loop_tasks_per_s": round(
                len(latencies) / max(run_s, 1e-9), 1
            ),
            "submit_to_result_p50_ms": round(p(latencies, 50) * 1e3, 2),
            "submit_to_result_p95_ms": round(p(latencies, 95) * 1e3, 2),
            "submit_to_result_p99_ms": round(p(latencies, 99) * 1e3, 2),
            "submit_to_result_mean_ms": round(
                float(np.mean(latencies)) * 1e3, 2
            ) if latencies else 0.0,
            # which stage owns the floor: per-(process:stage) p99 over the
            # assembled cross-process traces, plus the uncovered wall time
            # between spans (announce-bus + poll gaps)
            "stage_p99_ms": stage_p99_ms,
            "floor_stage": floor_stage,
            # the event-driven-intake pin: submit stamp -> announce drained
            "announce_wait_p99_ms": stage_p99_ms.get(
                "dispatcher:announce_wait"
            ),
            "uncovered_p99_ms": round(p(uncovered, 99) * 1e3, 3),
            "traces_assembled": len(timelines),
            "trace_stages_max": max(stages_seen, default=0),
            "trace_stages_min": min(stages_seen, default=0),
            "trace_processes": processes_max,
            # delivery-source attribution (gateway counter): the express
            # leg must serve ~all its results from the inline forward
            "result_served_inline": int(served["inline"]),
            "result_served_store": int(served["store"]),
            "inline_served_fraction": round(
                served["inline"] / n_served, 4
            ) if n_served else 0.0,
            "slo": slo_snapshot,
            "metrics_scrape_ok": bool(scrape_ok),
            "metrics_missing": scrape_missing,
            "metrics_scrape_error": scrape_error,
        }
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        disp.stop()
        disp_thread.join(timeout=10)
        gw.stop()
        handle.stop()


def config_12_latency() -> dict:
    """Latency-distribution lane (config 12): closed-loop submit→observe
    against the full real stack, TWO legs on the same box —

    - **polling leg**: the transport floor the reference's clients live
      under (immediate-reply /result polls on a 10 ms pacing sleep,
      tick-cadence dispatcher intake, store re-read per delivery);
    - **express leg**: the whole push lane — dispatcher ``--express``
      (inline result announces + event-driven intake + express sub-tick),
      gateway inline-forward serving, SDK pacing-free long-poll.

    The throughput lanes (configs 9-11) measure tasks/s with results that
    never flow back; this lane measures what a CLIENT waits, and the
    express/polling p99 ratio is the number ROADMAP item 2 ("kill the
    polling floor", p99 < 10 ms for sub-ms functions) is judged against.

    Per leg: p50/p95/p99/mean submit→result (client-measured), the
    per-stage p99 breakdown from the assembled cross-process traces
    (incl. ``dispatcher:announce_wait`` — the event-driven-intake pin —
    and the uncovered poll/bus gap), the delivery-source counters
    (inline vs store), the gateway /slo snapshot, and a strict-grammar
    /metrics verdict. Top level: the p99 ratio plus both legs whole.

    Shape via TPU_FAAS_BENCH_LATENCY_SHAPE="workers,procs,tasks,
    concurrency" (default "4,2,400,8"); legs via
    TPU_FAAS_BENCH_LATENCY_LEGS (default "polling,express"); the CI
    latency-smoke lane runs "2,2,80,4"."""
    import os

    shape = os.environ.get("TPU_FAAS_BENCH_LATENCY_SHAPE", "4,2,400,8")
    n_workers, n_procs, n_tasks, concurrency = (
        int(x) for x in shape.split(",")
    )
    legs_env = os.environ.get(
        "TPU_FAAS_BENCH_LATENCY_LEGS", "polling,express"
    )
    legs = [leg.strip() for leg in legs_env.split(",") if leg.strip()]
    # both legs share one tick period (TPU_FAAS_BENCH_LATENCY_TICK,
    # seconds) so the comparison isolates the DELIVERY path: the express
    # leg's claim is precisely that its latency stops being a function of
    # this knob (event-driven intake + push delivery), which a larger
    # tick makes visible instead of hiding under device-step noise
    tick_period = float(
        os.environ.get("TPU_FAAS_BENCH_LATENCY_TICK", "0.005")
    )
    row: dict = {
        "config": "latency-closed-loop",
        "shape": {
            "workers": n_workers,
            "procs": n_procs,
            "tasks": n_tasks,
            "concurrency": concurrency,
        },
    }
    for leg in legs:
        row[leg] = _latency_leg(
            n_workers, n_procs, n_tasks, concurrency,
            express=(leg == "express"), tick_period=tick_period,
        )
    if "polling" in row and "express" in row:
        express_p99 = row["express"]["submit_to_result_p99_ms"]
        row["p99_ratio_polling_over_express"] = round(
            row["polling"]["submit_to_result_p99_ms"] / express_p99, 2
        ) if express_p99 else None
    # back-compat headline fields (BENCH_r06 comparisons, CI asserts):
    # mirror the express leg when it ran, else the single leg that did
    head = row.get("express") or row.get(legs[-1]) if legs else None
    if head:
        for key in (
            "completed",
            "submit_to_result_p50_ms",
            "submit_to_result_p99_ms",
            "stage_p99_ms",
            "floor_stage",
            "trace_stages_max",
            "trace_processes",
            "metrics_scrape_ok",
            "metrics_missing",
            "metrics_scrape_error",
        ):
            row[key] = head[key]
    return row


def config_13_graph_pipeline() -> dict:
    """Task-graph lane (config 13): a fan-out/fan-in diamond workload
    (1 root -> width middles -> 1 sink, repeated ``rounds`` times as
    independent graphs) through the full real stack — store server over
    TCP, gateway with POST /execute_graph, tpu-push dispatcher with the
    device frontier, real push-worker subprocesses. Two legs:

    - **graph leg**: each diamond submitted as a DAG; the middles exist as
      WAITING records until the root completes (promotion plane + in-tick
      frontier mask), the sink until the middles do. Reported: graph
      makespan (submit -> sink terminal, the dependency-aware number) and
      the frontier-size trajectory sampled from the dispatcher while the
      leg runs.
    - **flat leg**: the SAME node multiset submitted dependency-free via
      /execute_batch — the baseline that shows what the dependency
      bookkeeping costs on wall time when no ordering is required (it
      also runs the sink/middles concurrently, so flat completing faster
      is expected; the row is a sanity floor, not a race).

    Invariants the smoke lane asserts: every graph node reaches COMPLETED,
    zero WAITING records survive the run, and the frontier trajectory was
    actually sampled (peak >= width+1). Shape via TPU_FAAS_BENCH_GRAPH_SHAPE=
    "width,rounds,workers,procs" (default "8,6,4,2"); the CI graph-smoke
    lane runs "4,3,2,2"."""
    import os
    import threading as _threading

    from tpu_faas.client import FaaSClient
    from tpu_faas.core.serialize import serialize
    from tpu_faas.core.task import TaskStatus
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.gateway import start_gateway_thread
    from tpu_faas.store.launch import make_store, start_store_thread
    from tpu_faas.bench.harness import _spawn_worker
    from tpu_faas.workloads import no_op

    shape = os.environ.get("TPU_FAAS_BENCH_GRAPH_SHAPE", "8,6,4,2")
    width, rounds, n_workers, n_procs = (int(x) for x in shape.split(","))
    nodes_per_graph = width + 2

    handle = start_store_thread()
    gw = start_gateway_thread(make_store(handle.url))
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=make_store(handle.url),
        max_workers=max(64, n_workers),
        max_pending=max(256, 4 * nodes_per_graph * rounds),
        max_inflight=4096,
        max_slots=n_procs,
        tick_period=0.005,
    )
    disp_thread = _threading.Thread(target=disp.start, daemon=True)
    disp_thread.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker(
            "push_worker", n_procs, url, "--hb", "--hb-period", "0.5"
        )
        for _ in range(n_workers)
    ]
    client = FaaSClient(gw.url)
    try:
        time.sleep(1.5)  # workers register
        fid = client.register_payload("no_op", serialize(no_op))
        # warmup outside the measured window (pool spawn + dill decode)
        for h in client.submit_many(fid, [((), {})] * (2 * n_procs)):
            h.result(timeout=120.0)

        # -- graph leg: sample the frontier gauge while diamonds run ------
        frontier_traj: list[int] = []
        sampling = _threading.Event()

        def sample_frontier() -> None:
            while not sampling.is_set():
                g = disp.graph
                frontier_traj.append(0 if g is None else len(g))
                sampling.wait(0.05)

        sampler = _threading.Thread(target=sample_frontier, daemon=True)
        sampler.start()
        graph_makespans: list[float] = []
        all_ids: list[str] = []
        t0 = time.perf_counter()
        for _ in range(rounds):
            g = client.graph()
            root = g.call(fid)
            mids = [g.call(fid, after=[root]) for _ in range(width)]
            sink = g.call(fid, after=mids)
            g.submit()
            all_ids.extend(h.task_id for h in [root, *mids, sink])
            t_g = time.perf_counter()
            sink.result(timeout=300.0)
            graph_makespans.append(time.perf_counter() - t_g)
        graph_s = time.perf_counter() - t0
        sampling.set()
        sampler.join(timeout=5)

        # -- flat leg: same node multiset, no dependencies ----------------
        t1 = time.perf_counter()
        flat_makespans: list[float] = []
        for _ in range(rounds):
            t_f = time.perf_counter()
            handles = client.submit_many(fid, [((), {})] * nodes_per_graph)
            for h in handles:
                h.result(timeout=300.0)
            flat_makespans.append(time.perf_counter() - t_f)
        flat_s = time.perf_counter() - t1

        # -- invariants ---------------------------------------------------
        store = make_store(handle.url)
        try:
            statuses = store.hget_many(all_ids, "status")
            completed = sum(
                1 for s in statuses if s == str(TaskStatus.COMPLETED)
            )
            waiting_left = sum(
                1 for s in statuses if s == str(TaskStatus.WAITING)
            )
        finally:
            store.close()
        stats = disp.stats()
        return {
            "config": "graph-pipeline",
            "shape": {
                "width": width,
                "rounds": rounds,
                "workers": n_workers,
                "procs": n_procs,
                "nodes": nodes_per_graph * rounds,
            },
            "graph_completed": completed,
            "waiting_left": waiting_left,
            "graph_leg_s": round(graph_s, 3),
            "flat_leg_s": round(flat_s, 3),
            "graph_makespan_p50_s": round(
                float(np.percentile(graph_makespans, 50)), 4
            ),
            "graph_makespan_max_s": round(max(graph_makespans), 4),
            "flat_makespan_p50_s": round(
                float(np.percentile(flat_makespans, 50)), 4
            ),
            # the dependency-bookkeeping trajectory: frontier occupancy
            # sampled at 20 Hz across the graph leg (peak ~= width+1 per
            # in-flight diamond; must return to 0)
            "frontier_size_trajectory": frontier_traj[:256],
            "frontier_size_peak": max(frontier_traj, default=0),
            "frontier_dispatches": stats["graph"]["frontier_dispatches"],
            # EXPECTED dependent-node count (computed from the shape, not
            # measured) — the measured promotion counter lives on the
            # dispatcher scrape (tpu_faas_graph_nodes_total{outcome})
            "dependent_nodes_expected": rounds * (width + 1),
            "dispatched": disp.n_dispatched,
        }
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        disp.stop()
        disp_thread.join(timeout=10)
        gw.stop()
        handle.stop()


def _free_port() -> int:
    import socket as _socket

    with _socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _spawn_store_server(port: int):
    """A store shard as a real subprocess (SIGKILL-able, own core)."""
    import socket as _socket
    import subprocess
    import sys as _sys

    proc = subprocess.Popen(
        [
            _sys.executable, "-m", "tpu_faas.store.server",
            "--host", "127.0.0.1", "--port", str(port),
        ],
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )
    deadline = time.monotonic() + 20
    while time.monotonic() < deadline:
        try:
            with _socket.create_connection(("127.0.0.1", port), timeout=0.5):
                return proc
        except OSError:
            if proc.poll() is not None:
                raise RuntimeError("store shard subprocess died at launch")
            time.sleep(0.05)
    proc.kill()
    raise RuntimeError("store shard subprocess never bound")


def _http_json(url: str, timeout: float = 10.0):
    import json as _json
    import urllib.request

    with urllib.request.urlopen(url, timeout=timeout) as r:
        return _json.loads(r.read().decode("utf-8"))


def config_14_fleet() -> dict:
    """Federated control plane (config 14): N store shards x N tpu-push
    dispatchers behind a stateless gateway tier vs the single 1x1x1 stack
    on the same box — ROADMAP item 1's scaling claim, measured.

    Every store shard and every dispatcher is a REAL subprocess (threads
    would let the GIL serialize exactly the serve loops being compared);
    the gateway tier runs over the full ShardedStore ring and is scraped
    mid-run. Dispatch throughput is isolated config-9 style: mirror
    workers registered on each child's ROUTER (no result path — the task
    feed IS the bottleneck probe), fed by pipelined batch creates through
    the sharded client, i.e. the gateway's own write path minus HTTP
    framing. Each leg reports tasks/s, the per-shard dispatch split, and
    a strict-grammar /metrics verdict for every process (gateway +
    dispatchers); the headline is ``scaling_ratio`` = fleet tasks/s over
    control tasks/s. ``host_cores`` rides along: process-level scaling
    cannot exceed the cores actually present, so a 2-core CI box bounds
    the ratio long before the architecture does.

    A chaos leg always runs at a small fixed shape: 2 shards where shard
    0 is a primary+replica pair, real subprocess workers, race monitor on
    every store client — shard 0's primary is SIGKILLed mid-burst, its
    replica promoted, and the leg asserts zero admitted-task loss and
    zero monitor errors (per-shard failover composing with the PR-6 HA
    plane). TPU_FAAS_BENCH_FLEET_CHAOS=0 skips it.

    Shape via TPU_FAAS_BENCH_FLEET_SHAPE="tasks,workers,procs,shards".
    ``workers`` is the mirror fleet EACH dispatcher child registers
    (workers*procs must cover tasks: mirror workers never free a slot,
    and a shard can draw several % over tasks/shards from the ring);
    the CI smoke lane runs "2000,256,8,2".
    """
    import os
    import signal as _signal
    import subprocess
    import sys as _sys
    import urllib.request

    from tpu_faas.obs.expofmt import parse_exposition, require_series
    from tpu_faas.store.launch import make_store

    shape = os.environ.get("TPU_FAAS_BENCH_FLEET_SHAPE", "20000,4096,8,4")
    n_tasks, n_workers, n_procs, n_shards = (
        int(x) for x in shape.split(",")
    )

    def run_leg(leg_shards: int) -> dict:
        from tpu_faas.gateway.app import start_gateway_thread

        stores = []
        ports = []
        children: list[subprocess.Popen] = []
        gw = None
        feeder = None
        try:
            for _ in range(leg_shards):
                port = _free_port()
                stores.append(_spawn_store_server(port))
                ports.append(port)
            hostports = [f"127.0.0.1:{p}" for p in ports]
            url = "resp://" + (
                ";".join(hostports) if leg_shards > 1 else hostports[0]
            )
            gw = start_gateway_thread(make_store(url))
            # EVERY child registers the full mirror fleet: per-shard
            # splits would have to cover the ring's worst-case imbalance
            # (a shard can draw several % over tasks/N, and mirror
            # workers never free a slot — an undersized shard stalls the
            # leg at its slot cap), and identical pads mean one XLA
            # compile shared by both legs' children via the persistent
            # cache. Capacity is not speed: the serve loops being
            # compared are unchanged.
            per_workers = n_workers
            stats_ports = []
            for i in range(leg_shards):
                sp = _free_port()
                stats_ports.append(sp)
                children.append(
                    subprocess.Popen(
                        [
                            _sys.executable, "-m",
                            "tpu_faas.bench.fleet_child",
                            "--store", url,
                            "--shard", str(i if leg_shards > 1 else -1),
                            "--workers", str(per_workers),
                            "--procs", str(n_procs),
                            "--stats-port", str(sp),
                            "--max-pending",
                            str(min(8192, max(64, n_tasks))),
                            "--max-inflight",
                            str(max(2 * n_tasks, 1024)),
                        ],
                        stdout=subprocess.DEVNULL,
                        stderr=subprocess.DEVNULL,
                    )
                )

            def child_stats(i: int) -> dict | None:
                try:
                    return _http_json(
                        f"http://127.0.0.1:{stats_ports[i]}/stats",
                        timeout=5,
                    )
                except Exception:
                    return None

            # readiness: every child registered its mirror fleet and
            # compiled its device step (excluded from the timed window;
            # the XLA cold compile can take minutes on a loaded box)
            deadline = time.monotonic() + 900
            ready = [False] * leg_shards
            while not all(ready) and time.monotonic() < deadline:
                for i in range(leg_shards):
                    if ready[i]:
                        continue
                    if children[i].poll() is not None:
                        raise RuntimeError(
                            f"fleet child {i} died before readiness"
                        )
                    got = child_stats(i)
                    if (
                        got is not None
                        and got.get("workers_registered", 0) >= per_workers
                    ):
                        ready[i] = True
                if not all(ready):
                    time.sleep(0.25)
            if not all(ready):
                raise RuntimeError(f"fleet children never ready: {ready}")

            feeder = make_store(url)
            scrape_ok = True
            scrape_missing: list[str] = []
            scrape_error = ""
            scraped = False
            t0 = time.perf_counter()
            chunk = 2_000
            for lo in range(0, n_tasks, chunk):
                feeder.create_tasks(
                    [
                        (f"fleet-t{i}", "F", "P")
                        for i in range(lo, min(lo + chunk, n_tasks))
                    ]
                )
            dispatched_per_child = [0] * leg_shards
            deadline = time.perf_counter() + 600
            last_progress = (0, time.perf_counter())
            while (
                sum(dispatched_per_child) < n_tasks
                and time.perf_counter() < deadline
            ):
                for i in range(leg_shards):
                    got = child_stats(i)
                    if got is not None:
                        dispatched_per_child[i] = got.get(
                            "n_dispatched", dispatched_per_child[i]
                        )
                total = sum(dispatched_per_child)
                if total > last_progress[0]:
                    last_progress = (total, time.perf_counter())
                elif time.perf_counter() - last_progress[1] > 60:
                    # stalled (dead child, exhausted capacity): stop the
                    # clock instead of billing the wait to tasks/s
                    break
                if not scraped and sum(dispatched_per_child) >= n_tasks // 2:
                    # mid-run scrape of EVERY process against the strict
                    # exposition grammar: gateway + each dispatcher child
                    scraped = True
                    targets = [
                        (f"{gw.url}/metrics", ["tpu_faas_gateway_requests_total"]),
                    ] + [
                        (
                            f"http://127.0.0.1:{sp}/metrics",
                            [
                                "tpu_faas_dispatcher_tasks_dispatched_total",
                                "tpu_faas_store_round_trips_total",
                            ],
                        )
                        for sp in stats_ports
                    ]
                    for target, required in targets:
                        try:
                            with urllib.request.urlopen(
                                target, timeout=10
                            ) as r:
                                families = parse_exposition(
                                    r.read().decode("utf-8")
                                )
                            missing = require_series(families, required)
                            scrape_missing.extend(missing)
                            scrape_ok = scrape_ok and not missing
                        except Exception as exc:
                            scrape_ok = False
                            scrape_error = f"{type(exc).__name__}: {exc}"
                time.sleep(0.05)
            dispatched = sum(dispatched_per_child)
            # the clock stops at the LAST OBSERVED PROGRESS: a stall
            # break (or the final poll sleep) must not dilute tasks/s
            elapsed = (
                last_progress[1] - t0 if dispatched else
                time.perf_counter() - t0
            )
            return {
                "shards": leg_shards,
                "dispatched": dispatched,
                "tasks_per_s": round(dispatched / max(elapsed, 1e-9), 1),
                "elapsed_s": round(elapsed, 2),
                "dispatched_per_shard": dispatched_per_child,
                "store_round_trips_feeder": feeder.n_round_trips,
                "metrics_scrape_ok": bool(scrape_ok and scraped),
                "metrics_missing": scrape_missing,
                "metrics_scrape_error": scrape_error,
            }
        finally:
            for child in children:
                if child.poll() is None:
                    child.send_signal(_signal.SIGTERM)
            for child in children:
                try:
                    child.wait(timeout=20)
                except subprocess.TimeoutExpired:
                    child.kill()
                    child.wait()
            if feeder is not None:
                feeder.close()
            if gw is not None:
                gw.stop()
            for proc in stores:
                if proc.poll() is None:
                    proc.terminate()
                    try:
                        proc.wait(timeout=10)
                    except subprocess.TimeoutExpired:
                        proc.kill()
                        proc.wait()

    control = run_leg(1)
    fleet = run_leg(n_shards)
    ratio = (
        fleet["tasks_per_s"] / control["tasks_per_s"]
        if control["tasks_per_s"] > 0
        else 0.0
    )
    out = {
        "config": "fleet-throughput",
        "shape": {
            "tasks": n_tasks,
            "workers": n_workers,
            "procs": n_procs,
            "shards": n_shards,
        },
        # the physical bound on process-level scaling for THIS record: a
        # ratio near min(shards, cores) is the box saturating, not the
        # architecture
        "host_cores": os.cpu_count(),
        "control": control,
        "fleet": fleet,
        "scaling_ratio": round(ratio, 2),
    }
    if os.environ.get("TPU_FAAS_BENCH_FLEET_CHAOS", "1") != "0":
        out["chaos"] = _fleet_chaos_leg()
    return out


def _fleet_chaos_leg() -> dict:
    """One-shard-primary-SIGKILL under the race monitor: 2 shards (shard
    0 = subprocess primary + in-thread replica), a gateway over the full
    ring, one tpu-push dispatcher owning each shard, real subprocess
    workers. Shard 0's primary dies mid-burst, its replica is promoted,
    and every admitted task must still COMPLETE with zero monitor errors
    — per-shard failover composing with the PR-6 HA plane."""
    import signal as _signal
    import threading as _threading

    from tpu_faas.bench.harness import _spawn_worker
    from tpu_faas.client import FaaSClient
    from tpu_faas.core.task import TaskStatus
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.gateway import start_gateway_thread
    from tpu_faas.store.client import RespStore
    from tpu_faas.store.launch import make_store, start_store_thread
    from tpu_faas.store.racecheck import RaceCheckStore, RaceMonitor
    from tpu_faas.workloads import sleep_task

    task_s = 0.05
    n_submits = 60
    kill_at = n_submits // 2

    p0_port = _free_port()
    p0 = _spawn_store_server(p0_port)
    r0 = start_store_thread(replica_of=("127.0.0.1", p0_port))
    s1 = start_store_thread()
    url = (
        f"resp://127.0.0.1:{p0_port},127.0.0.1:{r0.port}"
        f";127.0.0.1:{s1.port}"
    )
    monitor = RaceMonitor()
    gw = start_gateway_thread(
        RaceCheckStore(make_store(url), monitor, actor="gateway")
    )
    disps = [
        TpuPushDispatcher(
            ip="127.0.0.1",
            port=0,
            store=RaceCheckStore(
                make_store(url, owned_shards=[i]),
                monitor,
                actor=f"dispatcher-{i}",
            ),
            max_workers=64,
            max_pending=256,
            max_inflight=512,
            tick_period=0.01,
            time_to_expire=2.0,
            rescan_period=0.5,
        )
        for i in range(2)
    ]
    threads = [
        _threading.Thread(target=d.start, daemon=True) for d in disps
    ]
    for t in threads:
        t.start()
    workers = [
        _spawn_worker(
            "push_worker", 2, f"tcp://127.0.0.1:{d.port}",
            "--hb", "--hb-period", "0.3",
        )
        for d in disps
    ]
    client = FaaSClient(gw.url)
    rc = RespStore(port=r0.port)
    admitted: list = []
    submit_errors: list[str] = []
    try:
        deadline = time.monotonic() + 30
        while rc.info().get("repl_link_up") != "1":
            if time.monotonic() > deadline:
                raise RuntimeError("shard-0 replica never synced")
            time.sleep(0.1)
        fid = client.register(sleep_task)
        for i in range(n_submits):
            if i == kill_at:
                # -- the event: shard 0's primary dies hard --------------
                p0.send_signal(_signal.SIGKILL)
                p0.wait()
                rc.promote()  # the operator runbook's failover action
            try:
                admitted.append(client.submit(fid, task_s))
            except Exception as exc:  # rejected after SDK retries: not
                submit_errors.append(f"{type(exc).__name__}: {exc}")
                # admitted, so not part of the zero-loss population
        results = [h.result(timeout=180.0) for h in admitted]
        completed = sum(1 for r in results if r == task_s)
        # settle: let in-flight timelines close before judging the trace
        deadline = time.monotonic() + 30
        while monitor.unfinished() and time.monotonic() < deadline:
            time.sleep(0.25)
        return {
            "submits": n_submits,
            "admitted": len(admitted),
            "completed": completed,
            "lost": len(admitted) - completed,
            "submit_errors": len(submit_errors),
            "shard0_failover_rearms": disps[0].n_failover_rearms,
            "monitor_errors": [str(v) for v in monitor.errors],
            "monitor_warnings": len(monitor.warnings),
            "zero_loss": completed == len(admitted)
            and not monitor.errors,
        }
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        for d in disps:
            d.stop()
        for t in threads:
            t.join(timeout=10)
        gw.stop()
        rc.close()
        for h in (r0, s1):
            h.stop()
        if p0.poll() is None:
            p0.kill()
            p0.wait()


def _resident_fleet(rs, n_workers: int, procs: int) -> None:
    """Register a full mirror fleet by direct array fill (a Python
    register() loop at 32k workers costs more than the ticks being
    measured; the host mirrors are the registration surface)."""
    now = rs.clock()
    rs.worker_speed[:n_workers] = 1.0
    rs.worker_active[:n_workers] = True
    rs.worker_procs[:n_workers] = procs
    rs.worker_free[:n_workers] = procs
    rs.last_heartbeat[:n_workers] = now
    for i in range(n_workers):
        wid = b"bench-w%d" % i
        rs.worker_ids[wid] = i
        rs.row_ids[i] = wid


def _tick_leg(
    backend: str, T: int, W: int, n_ticks: int, seed: int,
    placement: str = "rank",
) -> dict:
    """Median integrated-tick time for one backend at one shape: bulk-load
    a full pending buffer, then measure tick_resident + full resolve
    (arrivals trickling in each tick) — the steady-state product cycle.
    ``seed`` fixes the task-size instance so the two backends of one
    shape solve the IDENTICAL problem (the headlined ratio must compare
    kernels, not random instances)."""
    import itertools

    from tpu_faas.sched.resident import ResidentScheduler

    rng = np.random.default_rng(seed)
    procs = 8
    rs = ResidentScheduler(
        max_workers=W,
        max_pending=T,
        max_inflight=min(4 * W * procs, 1 << 17),
        max_slots=procs,
        placement=placement,
        tick_backend=backend,
    )
    _resident_fleet(rs, W, procs)
    # load leaves KA-per-tick arrival headroom at real shapes; small
    # (smoke) shapes clamp to half the buffer — surplus arrivals bounce
    # and re-queue, which the resident contract handles by design
    n_load = min(T, max(T - rs.KA * (n_ticks + 1), (T + 1) // 2))
    rs.pending_bulk_load(
        [f"t{i}" for i in range(n_load)],
        rng.uniform(0.1, 5.0, n_load).astype(np.float32),
    )
    # warmup: compile + first placement wave outside the timed window
    rs.tick_resident()
    while rs.resolve_next() is not None:
        pass
    times = []
    dispatches_max = 0
    arrival_seq = itertools.count(n_load)
    for _ in range(n_ticks):
        for _k in range(rs.KA // 2):
            rs.pending_add(f"t{next(arrival_seq)}", 1.0)
        t0 = time.perf_counter()
        rs.tick_resident()
        while rs.resolve_next() is not None:  # forces the readback
            pass
        times.append((time.perf_counter() - t0) * 1e3)
        # the one-dispatch pin covers EVERY measured tick: a single
        # overflow flush on any of them is a contract violation, not
        # only one on the last
        dispatches_max = max(dispatches_max, rs.device_dispatches_last_tick)
    times.sort()
    return {
        "median_ms": round(times[len(times) // 2], 3),
        "q25_ms": round(times[len(times) // 4], 3),
        "max_ms": round(times[-1], 3),
        "n_ticks": n_ticks,
        "dispatches_last_tick": dispatches_max,
    }


def config_15_tick_trajectory() -> dict:
    """Tick-latency trajectory (config 15): the fused Pallas resident tick
    vs the XLA op-graph tick, integrated (delta pack -> kernel -> resolved
    readback), over a shape ladder — the ROADMAP item-3 capacity story.

    MEDIAN per-tick wall time headlines each shape (ADVICE r5 estimator
    rule: the median is the compliance number, quartiles are context).
    The fused leg also pins the one-dispatch-per-tick contract live
    (``dispatches_last_tick`` must be 1) and feeds a TickProfiler whose
    rendered exposition is strict-parsed — the bench's /metrics verdict.

    The capacity DRYRUN runs ONE tick per leg at the 500k x 32k ROADMAP
    shape: completion is the assertion (the rank path is sort-based and
    the fused auction bid streams O(T+S), so no [T, S] buffer exists to
    OOM — materializing one would be 500k x 256k x 4 B = 512 GB).

    On CPU the fused leg runs under the Pallas interpreter (the parity
    contract's form — latency numbers there compare interpreter overhead,
    not kernels; the TPU capture is the headline artifact). Shapes via
    TPU_FAAS_BENCH_TICK_SHAPES="T,W;T,W", rank dryrun via
    TPU_FAAS_BENCH_TICK_DRYRUN="T,W", fused-AUCTION dryrun via
    TPU_FAAS_BENCH_TICK_AUCTION_DRYRUN="T,W" (empty string disables
    either), reps via TPU_FAAS_BENCH_TICK_REPS, sharded winner-resolve
    leg via TPU_FAAS_BENCH_TICK_MULTICHIP=1 (needs >= 2 devices)."""
    import os

    import jax

    from tpu_faas.obs.expofmt import parse_exposition
    from tpu_faas.obs.metrics import MetricsRegistry, render
    from tpu_faas.obs.profile import TickProfiler

    fused = "fused" if jax.default_backend() == "tpu" else "fused_interpret"
    shapes = [
        tuple(int(x) for x in part.split(","))
        for part in os.environ.get(
            "TPU_FAAS_BENCH_TICK_SHAPES", "50000,4096;200000,16384"
        ).split(";")
        if part
    ]
    dry_env = os.environ.get("TPU_FAAS_BENCH_TICK_DRYRUN", "500000,32768")
    n_ticks = int(os.environ.get("TPU_FAAS_BENCH_TICK_REPS", "5"))

    registry = MetricsRegistry()
    profiler = TickProfiler(registry)
    rows = []
    for T, W in shapes:
        # one seed per shape, SHARED by both legs: identical instance
        xla = _tick_leg("xla", T, W, n_ticks, seed=15 + T)
        fus = _tick_leg(fused, T, W, n_ticks, seed=15 + T)
        profiler.observe_shape(
            tasks=T, workers=W, slots=8,
            signature=("bench15", T, W, fused),
        )
        profiler.note_device_dispatches(fus["dispatches_last_tick"])
        rows.append(
            {
                "tasks": T,
                "workers": W,
                "xla": xla,
                "fused": fus,
                "fused_vs_xla": round(
                    xla["median_ms"] / max(fus["median_ms"], 1e-9), 3
                ),
                "one_dispatch_per_tick": fus["dispatches_last_tick"] == 1,
            }
        )

    dryrun = None
    if dry_env:
        dT, dW = (int(x) for x in dry_env.split(","))
        t0 = time.perf_counter()
        leg = _tick_leg(fused, dT, dW, 1, seed=15 + dT)
        dryrun = {
            "tasks": dT,
            "workers": dW,
            "backend": fused,
            "tick_ms": leg["median_ms"],
            "total_s": round(time.perf_counter() - t0, 2),
            "one_dispatch_per_tick": leg["dispatches_last_tick"] == 1,
            "ok": True,
        }

    # auction capacity leg: the O(T+S) claim is about the BID matrix,
    # which the rank dryrun above never builds in the first place — this
    # leg drives one fused AUCTION tick (the streamed in-kernel bid), at
    # a shape whose per-round [T, S] block would be multi-GB if anything
    # regressed into materializing it. Smaller than the rank dryrun
    # because each streamed round still EVALUATES T x S cells.
    auction_dry = None
    adry_env = os.environ.get(
        "TPU_FAAS_BENCH_TICK_AUCTION_DRYRUN", "50000,4096"
    )
    if adry_env:
        aT, aW = (int(x) for x in adry_env.split(","))
        t0 = time.perf_counter()
        leg = _tick_leg(
            fused, aT, aW, 1, seed=16 + aT, placement="auction"
        )
        auction_dry = {
            "tasks": aT,
            "workers": aW,
            "backend": fused,
            "bid_matrix_gb_never_built": round(
                aT * aW * 8 * 4 / 2**30, 1
            ),
            "tick_ms": leg["median_ms"],
            "total_s": round(time.perf_counter() - t0, 2),
            "one_dispatch_per_tick": leg["dispatches_last_tick"] == 1,
            "ok": True,
        }

    multichip = None
    if os.environ.get("TPU_FAAS_BENCH_TICK_MULTICHIP", "0") == "1":
        multichip = _tick_multichip_leg()

    scrape_missing: list[str] = []
    scrape_error = ""
    try:
        families = parse_exposition(render([registry]))
        for fam in (
            "tpu_faas_tick_device_dispatches_last",
            "tpu_faas_tick_device_dispatches_total",
            "tpu_faas_jit_recompiles_total",
            "tpu_faas_device_ticks_total",
            "tpu_faas_tick_shape",
        ):
            if fam not in families:
                scrape_missing.append(fam)
        scrape_ok = not scrape_missing
    except Exception as exc:  # malformed exposition included
        scrape_ok = False
        scrape_error = f"{type(exc).__name__}: {exc}"

    return {
        "config": "tick-latency-trajectory",
        "backend_fused": fused,
        "jax_backend": jax.default_backend(),
        "shapes": rows,
        "dryrun_500k": dryrun,
        "auction_dryrun": auction_dry,
        "multichip": multichip,
        "metrics_scrape_ok": scrape_ok,
        "metrics_missing": scrape_missing,
        "metrics_scrape_error": scrape_error,
    }


def _tick_multichip_leg() -> dict:
    """Sharded winner-resolve dryrun: the explicit ring-permute auction
    vs the GSPMD lexsort form on the same sharded problem — exact
    assignment parity asserted, median solve time for both (MULTICHIP
    artifact material; on the virtual CPU mesh the timing compares
    lowering overhead, the parity is the point)."""
    import jax
    import jax.numpy as jnp

    if len(jax.devices()) < 2:
        return {"skipped": True, "reason": "needs >= 2 devices"}
    from tpu_faas.parallel.mesh import (
        make_mesh,
        replicate,
        shard_task_arrays,
        sharded_auction_placement,
    )
    from tpu_faas.sched.auction import auction_placement

    n_dev = len(jax.devices())
    rng = np.random.default_rng(16)
    T, W, K = 4096, 512, 4
    ts = rng.uniform(0.1, 5.0, T).astype(np.float32)
    tv = np.ones(T, bool)
    ws = rng.uniform(0.5, 4.0, W).astype(np.float32)
    wf = rng.integers(1, K + 1, W).astype(np.int32)
    wl = np.ones(W, bool)
    mesh = make_mesh(n_dev)
    ts_d, tv_d = shard_task_arrays(mesh, jnp.asarray(ts), jnp.asarray(tv))
    ws_d, wf_d, wl_d = replicate(
        mesh, jnp.asarray(ws), jnp.asarray(wf), jnp.asarray(wl)
    )

    def run_permute():
        return sharded_auction_placement(
            mesh, ts_d, tv_d, ws_d, wf_d, wl_d, max_slots=K
        )

    def run_gspmd():
        return auction_placement(
            ts_d, tv_d, ws_d, wf_d, wl_d, max_slots=K
        )

    res_p = run_permute()  # compile + parity reference
    res_g = run_gspmd()
    exact = bool(
        np.array_equal(
            np.asarray(res_p.assignment), np.asarray(res_g.assignment)
        )
    )
    if not exact:
        # the MULTICHIP artifact exists to PROVE bit-identical winner
        # resolution — regenerating it with a silent parity break would
        # commit a record that no longer proves anything
        raise RuntimeError(
            "permute winner-resolve diverged from the GSPMD form at the "
            "multichip dryrun shape — parity regression"
        )

    def med_ms(fn) -> float:
        times = []
        for _ in range(3):
            t0 = time.perf_counter()
            jax.block_until_ready(fn().assignment)
            times.append((time.perf_counter() - t0) * 1e3)
        return round(sorted(times)[1], 2)

    return {
        "n_devices": n_dev,
        "tasks": T,
        "workers": W,
        "rounds": int(res_p.n_rounds),
        "assignment_exact_parity": exact,
        "permute_solve_ms_median": med_ms(run_permute),
        "gspmd_solve_ms_median": med_ms(run_gspmd),
    }


def _tenant_stack(
    n_workers: int,
    n_procs: int,
    tick_period: float,
    tenant_shares: str | None,
    tenant_caps: str | None = None,
):
    """A fresh full real stack for one tenant-fairness leg: store server
    over TCP, gateway, tpu-push dispatcher (tenancy plane per
    ``tenant_shares``; None = plane OFF, the FCFS control), real
    push-worker subprocesses. Returns (gw, disp, disp_thread, workers,
    store_handle) — callers tear all five down."""
    import threading as _threading

    from tpu_faas.bench.harness import _spawn_worker
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.gateway import start_gateway_thread
    from tpu_faas.store.launch import make_store, start_store_thread

    handle = start_store_thread()
    # admission OFF: this lane measures in-TICK fairness among ADMITTED
    # tasks. With the default edge admission on, the heavy backlog trips
    # the derived in-system bound and the light tenant's submits measure
    # 429/Retry-After backoff instead of placement (config 10 owns that
    # surface) — the 20-second "p99" that shows up is the SDK sleeping,
    # not the tick queueing.
    gw = start_gateway_thread(make_store(handle.url), admission=False)
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=make_store(handle.url),
        max_workers=max(64, n_workers),
        max_pending=8192,
        max_inflight=4096,
        max_slots=n_procs,
        tick_period=tick_period,
        tenant_shares=tenant_shares,
        tenant_caps=tenant_caps,
    )
    disp_thread = _threading.Thread(target=disp.start, daemon=True)
    disp_thread.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _spawn_worker(
            "push_worker", n_procs, url, "--hb", "--hb-period", "0.5"
        )
        for _ in range(n_workers)
    ]
    return gw, disp, disp_thread, workers, handle


def _teardown_tenant_stack(gw, disp, disp_thread, workers, handle) -> None:
    for w in workers:
        if w.poll() is None:
            w.kill()
            w.wait()
    disp.stop()
    disp_thread.join(timeout=10)
    gw.stop()
    handle.stop()


def _light_latency_leg(
    n_workers: int,
    n_procs: int,
    n_light: int,
    heavy_backlog: int,
    task_s: float,
    tenant_shares: str | None,
    tenant_caps: str | None = None,
    tick_period: float = 0.005,
) -> dict:
    """One light-tenant latency measurement: optionally flood the fleet
    with ``heavy_backlog`` sleep tasks from the HEAVY tenant first (one
    batched submit), then run the LIGHT tenant's closed loop of
    ``n_light`` sleep tasks and report its latency distribution plus the
    heavy tenant's saturation evidence. ``heavy_backlog=0`` is the light
    tenant's SOLO baseline."""
    from tpu_faas.client import FaaSClient
    from tpu_faas.core.serialize import serialize
    from tpu_faas.workloads import sleep_task

    gw, disp, disp_thread, workers, handle = _tenant_stack(
        n_workers, n_procs, tick_period, tenant_shares, tenant_caps
    )
    try:
        time.sleep(1.5)  # workers register
        light = FaaSClient(gw.url, tenant="light")
        heavy = FaaSClient(gw.url, tenant="heavy")
        fid = light.register_payload("sleep_task", serialize(sleep_task))
        # warmup outside the window: pool spawn + first dill decode
        for h in light.submit_many(fid, [(((0.001,), {}))] * 4):
            h.result(timeout=60.0)
        dispatched0 = disp.n_dispatched
        if heavy_backlog:
            heavy.submit_many(
                fid, [(((task_s,), {}))] * heavy_backlog
            )
        lat: list[float] = []
        t0 = time.perf_counter()
        for _ in range(n_light):
            s = time.perf_counter()
            light.submit(fid, task_s).result(timeout=300.0)
            lat.append(time.perf_counter() - s)
        run_s = time.perf_counter() - t0
        arr = np.asarray(lat)
        tenancy = disp.stats().get("tenancy")
        # strict-grammar /metrics scrape carrying the tenant families
        # (tenancy legs only — the FCFS control has no tenant series)
        scrape_ok = True
        scrape_missing: list[str] = []
        scrape_error = ""
        if tenant_shares is not None:
            import requests as _requests

            from tpu_faas.obs.expofmt import parse_exposition, require_series

            try:
                srv = disp.serve_stats(0)
                port = srv.server_address[1]
                families = parse_exposition(
                    _requests.get(
                        f"http://127.0.0.1:{port}/metrics", timeout=10
                    ).text
                )
                scrape_missing = require_series(
                    families,
                    [
                        "tpu_faas_tasks_dispatched_total",
                        "tpu_faas_tenant_queue_depth",
                        "tpu_faas_tenant_inflight_tasks",
                    ],
                )
                scrape_ok = not scrape_missing
            except Exception as exc:
                scrape_ok = False
                scrape_error = f"{type(exc).__name__}: {exc}"
        return {
            "leg": (
                "solo" if not heavy_backlog
                else ("overload" if tenant_shares is not None
                      else "overload-control")
            ),
            "light_tasks": n_light,
            "heavy_backlog": heavy_backlog,
            "run_s": round(run_s, 2),
            "light_p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 2),
            "light_p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 2),
            "light_mean_ms": round(float(arr.mean()) * 1e3, 2),
            # saturation evidence: the heavy tenant kept the fleet busy
            # through the light run (dispatches well past the light count)
            "dispatched_during": disp.n_dispatched - dispatched0,
            "tenancy": tenancy,
            "metrics_scrape_ok": scrape_ok,
            "metrics_missing": scrape_missing,
            "metrics_scrape_error": scrape_error,
        }
    finally:
        _teardown_tenant_stack(gw, disp, disp_thread, workers, handle)


def _weighted_share_leg(
    n_workers: int,
    n_procs: int,
    backlog_per_tenant: int,
    task_s: float,
    shares: dict[str, float],
    tick_period: float = 0.005,
) -> dict:
    """Three saturating tenants under a configured share vector: submit
    equal backlogs, let the fleet run until roughly half the work is
    dispatched (every tenant still backlogged), and report each tenant's
    dispatched fraction against its configured share fraction."""
    from tpu_faas.client import FaaSClient
    from tpu_faas.core.serialize import serialize
    from tpu_faas.workloads import sleep_task

    spec = ",".join(f"{k}={v:g}" for k, v in shares.items())
    gw, disp, disp_thread, workers, handle = _tenant_stack(
        n_workers, n_procs, tick_period, spec
    )
    try:
        time.sleep(1.5)
        clients = {k: FaaSClient(gw.url, tenant=k) for k in shares}
        first = next(iter(clients.values()))
        fid = first.register_payload("sleep_task", serialize(sleep_task))
        for h in first.submit_many(fid, [(((0.001,), {}))] * 4):
            h.result(timeout=60.0)
        base = {
            k: int(disp.tenancy.dispatched[disp.tenancy.row_for(k)])
            for k in shares
        }
        for k, c in clients.items():
            c.submit_many(fid, [(((task_s,), {}))] * backlog_per_tenant)
        total = backlog_per_tenant * len(shares)
        # sample while EVERY tenant is still backlogged: at half the
        # total dispatched, the largest share (<= 4/7 of the work) has
        # not yet exhausted its equal backlog
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            done = sum(
                int(disp.tenancy.dispatched[disp.tenancy.row_for(k)])
                - base[k]
                for k in shares
            )
            if done >= total // 2:
                break
            time.sleep(0.05)
        counts = {
            k: int(disp.tenancy.dispatched[disp.tenancy.row_for(k)])
            - base[k]
            for k in shares
        }
        got_total = max(sum(counts.values()), 1)
        share_total = sum(shares.values())
        return {
            "shares": dict(shares),
            "backlog_per_tenant": backlog_per_tenant,
            "dispatched": counts,
            "dispatched_fraction": {
                k: round(v / got_total, 4) for k, v in counts.items()
            },
            "configured_fraction": {
                k: round(v / share_total, 4) for k, v in shares.items()
            },
            "max_abs_fraction_error": round(
                max(
                    abs(counts[k] / got_total - shares[k] / share_total)
                    for k in shares
                ),
                4,
            ),
        }
    finally:
        _teardown_tenant_stack(gw, disp, disp_thread, workers, handle)


def config_16_tenant_fairness() -> dict:
    """Tenant-fairness lane (config 16): the tenancy plane's two promises
    measured on the full real stack (store server, gateway, tpu-push with
    ``--tenant-shares``, real push-worker subprocesses) —

    - **isolation**: a LIGHT tenant's closed-loop p99 with a HEAVY
      tenant's backlog saturating the fleet, against its own SOLO
      baseline (the bar: <= 1.2x while the heavy tenant saturates);
      plus an optional FCFS CONTROL leg (tenancy off) where the same
      light task sits behind the whole heavy backlog — the number the
      plane exists to fix;
    - **weighted shares**: three saturating tenants under a 4:2:1 share
      vector; dispatched fractions must track configured fractions
      (CI bar: within 10%).

    Shape via TPU_FAAS_BENCH_TENANT_SHAPE="workers,procs,light_tasks,
    heavy_backlog,task_ms" (default "2,4,20,160,300" — task_ms well
    above the box's fixed scheduling jitter, so the ratio reflects
    isolation, not host noise); TPU_FAAS_BENCH_TENANT_CONTROL=0 skips
    the slow FCFS control leg;
    TPU_FAAS_BENCH_TENANT_SHARE_SHAPE="backlog,task_ms" sizes the share
    leg (default "150,20")."""
    import os

    shape = os.environ.get(
        "TPU_FAAS_BENCH_TENANT_SHAPE", "2,4,20,160,300"
    )
    n_workers, n_procs, n_light, heavy_backlog, task_ms = (
        int(x) for x in shape.split(",")
    )
    task_s = task_ms / 1e3
    # the isolation config under test, both mechanisms the plane ships:
    # the SHARE vector makes the light tenant's head-of-queue virtual
    # position (1/share) beat the backlogged tenant's head on the first
    # free slot (weight 8 ~ "latency-sensitive"), and the heavy tenant's
    # inflight CAP of slots-1 keeps one slot of standing headroom — a
    # saturating tenant may never occupy the LAST slot, so the light
    # tenant's task starts immediately instead of waiting out a
    # slot-free interval. This is the documented latency-isolation
    # recipe (OPERATIONS.md "Multi-tenancy"); the weighted-share leg
    # below measures the share vector without caps.
    shares = "light=8,heavy=1"
    caps = f"heavy={n_workers * n_procs - 1}"
    row: dict = {
        "config": "tenant-fairness",
        "shape": {
            "workers": n_workers,
            "procs": n_procs,
            "light_tasks": n_light,
            "heavy_backlog": heavy_backlog,
            "task_ms": task_ms,
        },
        "tenant_shares": shares,
        "tenant_caps": caps,
        "solo": _light_latency_leg(
            n_workers, n_procs, n_light, 0, task_s, shares, caps
        ),
        "overload": _light_latency_leg(
            n_workers, n_procs, n_light, heavy_backlog, task_s, shares,
            caps,
        ),
    }
    solo_p99 = row["solo"]["light_p99_ms"]
    row["light_p99_ratio_overload_over_solo"] = (
        round(row["overload"]["light_p99_ms"] / solo_p99, 3)
        if solo_p99
        else None
    )
    # the heavy tenant saturated: it consumed (nearly) every dispatch the
    # light tenant didn't
    row["heavy_saturated"] = (
        row["overload"]["dispatched_during"] >= n_light + heavy_backlog // 2
    )
    if os.environ.get("TPU_FAAS_BENCH_TENANT_CONTROL", "1") != "0":
        # FCFS control: tenancy OFF, fewer light tasks (each can wait out
        # the whole heavy backlog — that is the point)
        row["control"] = _light_latency_leg(
            n_workers, n_procs, max(3, n_light // 5), heavy_backlog,
            task_s, None,
        )
        if solo_p99:
            row["light_p99_ratio_control_over_solo"] = round(
                row["control"]["light_p99_ms"] / solo_p99, 3
            )
    share_shape = os.environ.get(
        "TPU_FAAS_BENCH_TENANT_SHARE_SHAPE", "150,20"
    )
    share_backlog, share_task_ms = (int(x) for x in share_shape.split(","))
    row["weighted_share"] = _weighted_share_leg(
        n_workers, n_procs, share_backlog, share_task_ms / 1e3,
        {"gold": 4.0, "silver": 2.0, "bronze": 1.0},
    )
    row["share_ratios_within_10pct"] = (
        row["weighted_share"]["max_abs_fraction_error"] <= 0.10
    )
    return row


def config_17_batched_plane() -> dict:
    """Batched worker data plane (config 17): e2e dispatch throughput for
    no-op functions against the FULL real stack — store server over TCP,
    gateway, an express tpu-push dispatcher, and real PushWorkers (run
    in-process so their pool counters are readable; execution still
    happens in forkserver child processes) — in a ``batched`` leg
    (--batch-max K, --batch-window-ms W: TASK_BATCH frames out,
    RESULT_BATCH frames back, K-task pool bundles) vs an ``unbatched``
    control (batch off: the per-task wire, byte-identical to the
    pre-batch build) on the same box and topology.

    Each leg also runs a SOLO latency probe — sequential single-task
    submit→result round trips on the idle stack — pinning that the
    batching window never re-introduces a latency floor for a lone
    express task (acceptance: batched solo p99 <= 1.1x unbatched). The
    frames-per-task and pool-IPC-per-task counters prove the
    O(1)-per-bundle claim (both ~1.0 on the control, << 1 batched), and
    each leg's dispatcher /metrics is scraped mid-run against the strict
    exposition grammar with the new batch families required.

    Both full-stack legs run with ``--columnar`` (arena intake + binbatch
    store wire — the shipped host plane; held constant so the ratio
    isolates the worker wire), pin the gateway announce-loss safety poll
    to 0.25s (a dropped announce otherwise floors the solo p99 at the
    default 2s poll), and carry a ``host_profile`` block — the top-10
    cumulative serve-loop functions from cProfile — attributing where
    each leg's host cycles went.

    Shape via TPU_FAAS_BENCH_BATCH_SHAPE="tasks,workers,procs,batch_max"
    (default "2000,2,4,16"); the CI smoke lane runs "300,2,2,8" and
    asserts completion on both legs, a finite nonzero ratio, bundling
    engaged (frames/task < 1 on the batched leg), and clean scrapes.
    """
    import json
    import os
    import threading

    from tpu_faas.worker.push_worker import PushWorker
    from tpu_faas.workloads import no_op

    shape = os.environ.get("TPU_FAAS_BENCH_BATCH_SHAPE", "2000,2,4,16")
    n_tasks, n_workers, n_procs, batch_max = (
        int(x) for x in shape.split(",")
    )
    n_solo = int(os.environ.get("TPU_FAAS_BENCH_BATCH_SOLO", "30"))

    def run_leg(leg_batch_max: int, window_ms: float) -> dict:
        """One full-stack leg in a FRESH child process
        (tpu_faas/bench/batch_leg_child.py): run as threads of this
        process, the second leg inherits the first's teardown tail
        (dying forkserver children, allocator/GC state) and identical
        reps were observed 6x apart purely by order — the config-14
        lesson, applied to legs instead of fleet members."""
        import subprocess
        import sys as _sys

        proc = subprocess.run(
            [
                _sys.executable, "-m", "tpu_faas.bench.batch_leg_child",
                "--batch-max", str(leg_batch_max),
                "--batch-window-ms", str(window_ms),
                "--tasks", str(n_tasks),
                "--workers", str(n_workers),
                "--procs", str(n_procs),
                "--solo", str(n_solo),
                # both legs ride the columnar host plane + binbatch store
                # wire (the shipped configuration); the batched-vs-unbatched
                # comparison is about the WORKER wire, so the host plane is
                # held constant across legs
                "--columnar",
                # pin the gateway's announce-loss safety poll low: a lone
                # dropped announce otherwise floors the solo probe's p99 at
                # the default 2s poll, measuring the recovery path instead
                # of the express wire
                "--safety-poll-s", "0.25",
            ],
            capture_output=True,
            text=True,
            timeout=900,
        )
        for line in reversed(proc.stdout.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                return json.loads(line)
        raise RuntimeError(
            f"batch leg child produced no row (rc={proc.returncode}): "
            f"{proc.stderr[-2000:]}"
        )

    def run_wire_leg(frame_size: int) -> dict:
        """The worker data plane in isolation: a synthetic ROUTER feeds a
        real PushWorker (real decode, real pool, real no-op execution in
        forkserver children, real result frames back) open-loop, in
        per-task TASK framing (frame_size 1 — the pre-batch wire) or
        TASK_BATCH frames of ``frame_size``. This is the per-process
        segment the batching optimizes, free of the store/gateway/
        device-tick costs the full-stack legs share on a small box."""
        import zmq

        from tpu_faas.core.executor import pack_params
        from tpu_faas.core.serialize import serialize
        from tpu_faas.worker import messages as wm
        from tpu_faas.worker.pool import POOL_IPC

        n = max(4 * n_tasks, 2000)
        ctx = zmq.Context.instance()
        router = ctx.socket(zmq.ROUTER)
        port = router.bind_to_random_port("tcp://127.0.0.1")
        worker = PushWorker(
            n_procs, f"tcp://127.0.0.1:{port}", poll_timeout_ms=10
        )
        t = threading.Thread(target=worker.run, daemon=True)
        t.start()
        try:
            wid, _ = router.recv_multipart()
            fn = serialize(no_op)
            params = pack_params()
            tasks = [
                {"task_id": f"t{i}", "fn_payload": fn,
                 "param_payload": params}
                for i in range(n)
            ]
            ipc0 = POOL_IPC.value
            frames = 0
            t0 = time.perf_counter()
            if frame_size > 1:
                for lo in range(0, n, frame_size):
                    router.send_multipart(
                        [wid, wm.encode(
                            wm.TASK_BATCH, tasks=tasks[lo:lo + frame_size]
                        )]
                    )
                    frames += 1
            else:
                for task in tasks:
                    router.send_multipart(
                        [wid, wm.encode(wm.TASK, **task)]
                    )
                    frames += 1
            got = 0
            deadline = t0 + 300.0
            while got < n and time.perf_counter() < deadline:
                if not router.poll(1000):
                    continue
                _, raw = router.recv_multipart()
                typ, data = wm.decode(raw)
                if typ == wm.RESULT:
                    got += 1
                elif typ == wm.RESULT_BATCH:
                    got += len(data["results"])
            elapsed = time.perf_counter() - t0
            return {
                "frame_size": frame_size,
                "completed": got,
                "tasks_per_s": round(got / max(elapsed, 1e-9), 1),
                "frames_per_task": round(frames / max(n, 1), 4),
                "pool_ipc_per_task": round(
                    (POOL_IPC.value - ipc0) / max(got, 1), 4
                ),
            }
        finally:
            worker.stop()
            t.join(timeout=30)
            router.close(linger=0)

    def best_of(fn, reps: int = 2) -> dict:
        """Best-of-N on a shared/noisy box (config-15 precedent: medians
        over reps): a leg that starts into the previous leg's teardown
        tail (dying pool children, forkserver churn) can lose 5x+ for
        environmental reasons, so each leg settles first and the healthy
        rep carries the row; every rep's throughput is recorded."""
        import gc

        rows = []
        for _ in range(reps):
            gc.collect()
            time.sleep(1.5)  # let the previous leg's teardown tail drain
            rows.append(fn())
        best = max(rows, key=lambda r: r["tasks_per_s"])
        best["reps_tasks_per_s"] = [r["tasks_per_s"] for r in rows]
        return best

    # control leg FIRST: the process accumulates state (forkserver
    # residue, registries) across legs, so any ordering bias loads the
    # BATCHED leg and the reported ratio is conservative
    unbatched = best_of(lambda: run_leg(0, 0.0))
    batched = best_of(lambda: run_leg(batch_max, 2.0))
    wire_per_task = best_of(lambda: run_wire_leg(1))
    wire_batched = best_of(lambda: run_wire_leg(batch_max))
    return {
        "config": "batched-data-plane",
        "shape": {
            "tasks": n_tasks,
            "workers": n_workers,
            "procs": n_procs,
            "batch_max": batch_max,
        },
        "host_cores": os.cpu_count(),
        "batched": batched,
        "unbatched": unbatched,
        # acceptance headlines: the full-stack ratio shares one box with
        # the (untouched) store server, gateway, and device tick — on a
        # core-starved host those bound it well below the data plane's
        # own win, so the isolated worker-wire ratio is recorded beside
        # it (config-14 precedent: host_cores is the binding constraint
        # before architecture is); the solo guard (<= 1.1x) pins that
        # batching never trades idle latency away
        "throughput_ratio": round(
            batched["tasks_per_s"] / max(unbatched["tasks_per_s"], 1e-9), 3
        ),
        "solo_p99_ratio": round(
            batched["solo_p99_ms"] / max(unbatched["solo_p99_ms"], 1e-9), 3
        ),
        "wire_batched": wire_batched,
        "wire_per_task": wire_per_task,
        "wire_ratio": round(
            wire_batched["tasks_per_s"]
            / max(wire_per_task["tasks_per_s"], 1e-9),
            3,
        ),
    }


def _tail_spawn_worker(n_procs: int, url: str, delay_s: float | None):
    """One push-worker subprocess; ``delay_s`` injects the deterministic
    sick-worker behavior (workloads.straggler_sleep reads the env in the
    worker's pool children)."""
    import subprocess
    import sys as _sys

    from tpu_faas.bench.harness import REPO, cpu_worker_env

    env = cpu_worker_env()
    if delay_s:
        env["TPU_FAAS_EXEC_DELAY_S"] = str(delay_s)
    return subprocess.Popen(
        [_sys.executable, "-m", "tpu_faas.worker.push_worker",
         str(n_procs), url, "--hb", "--hb-period", "0.3"],
        env=env, cwd=REPO, start_new_session=True,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def _tail_stack(
    n_workers: int,
    n_procs: int,
    slow_s: float,
    speculate: bool,
    monitor=None,
    time_to_expire: float = 3.0,
):
    """Full real stack for one tail leg: store server, gateway, tpu-push
    (speculation per flag), N real push-worker subprocesses with worker 0
    carrying ``slow_s`` of injected per-execution delay. ``monitor``
    wraps every store handle under the race monitor (chaos leg)."""
    import threading as _threading

    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.gateway import start_gateway_thread
    from tpu_faas.store.launch import make_store, start_store_thread

    def wrap(actor):
        s = make_store(handle.url)
        if monitor is None:
            return s
        from tpu_faas.store.racecheck import RaceCheckStore

        return RaceCheckStore(s, monitor, actor=actor)

    handle = start_store_thread()
    gw = start_gateway_thread(wrap("gateway"), admission=False)
    kw: dict = {}
    if speculate:
        kw = dict(
            speculate_mult=3.0,
            speculate_max_frac=0.3,
            speculate_min_s=0.02,
        )
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=wrap("dispatcher"),
        # modest padded shapes: the spec scan re-runs the device tick at
        # hedge granularity while work is in flight, and this lane's
        # boxes are small — an oversized padded tick would bill the
        # measurement for compute the shape never uses
        max_workers=max(16, n_workers),
        max_pending=512,
        max_inflight=1024,
        max_slots=n_procs,
        tick_period=0.005,
        time_to_expire=time_to_expire,
        # the estimator would LEARN the sick worker's speed and re-derive
        # the prediction; the lane pins the prediction to the client cost
        # hint so the injected delay is the one variable measured
        estimate_runtimes=False,
        **kw,
    )
    disp_thread = _threading.Thread(target=disp.start, daemon=True)
    disp_thread.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _tail_spawn_worker(n_procs, url, slow_s if i == 0 else None)
        for i in range(n_workers)
    ]
    return gw, disp, disp_thread, workers, handle


def _tail_teardown(gw, disp, disp_thread, workers, handle) -> None:
    import os as _os
    import signal as _signal

    for w in workers:
        if w.poll() is None:
            try:
                _os.killpg(w.pid, _signal.SIGKILL)  # pool children too
            except (ProcessLookupError, PermissionError):
                w.kill()
            w.wait()
    disp.stop()
    disp_thread.join(timeout=10)
    gw.stop()
    handle.stop()


def _tail_scrapes(gw, disp) -> dict:
    """Strict-grammar /metrics scrapes from every serving process (the
    speculation families required on hedged dispatchers)."""
    import requests as _requests

    from tpu_faas.obs.expofmt import parse_exposition, require_series

    out: dict = {"scrape_ok": True, "missing": [], "error": ""}
    try:
        srv = disp.serve_stats(0)
        port = srv.server_address[1]
        fams = parse_exposition(
            _requests.get(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ).text
        )
        need = ["tpu_faas_dispatcher_tasks_dispatched_total"]
        if disp.spec is not None:
            need += [
                "tpu_faas_dispatcher_hedges_total",
                "tpu_faas_dispatcher_hedge_loser_exec_seconds_total",
            ]
        out["missing"] = require_series(fams, need)
        gfams = parse_exposition(
            _requests.get(f"{gw.url}/metrics", timeout=10).text
        )
        out["missing"] += require_series(
            gfams, ["tpu_faas_gateway_safety_poll_served_total"]
        )
        out["scrape_ok"] = not out["missing"]
    except Exception as exc:
        out["scrape_ok"] = False
        out["error"] = f"{type(exc).__name__}: {exc}"
    return out


def _tail_leg(
    n_tasks: int,
    n_workers: int,
    n_procs: int,
    task_s: float,
    slow_s: float,
    speculate: bool,
) -> dict:
    """One tail-latency measurement: open-loop batch of speculative tasks
    with cost hints against the injected-straggler fleet; per-task
    latency = batch submit -> that task's terminal delivery (one waiter
    thread per handle, so serial polling can't skew the tail)."""
    import threading as _threading

    from tpu_faas.client import FaaSClient
    from tpu_faas.core.serialize import serialize
    from tpu_faas.workloads import straggler_sleep

    gw, disp, disp_thread, workers, handle = _tail_stack(
        n_workers, n_procs, slow_s, speculate
    )
    try:
        time.sleep(1.5)  # workers register
        c = FaaSClient(gw.url)
        fid = c.register_payload(
            "straggler_sleep", serialize(straggler_sleep)
        )
        # warmup outside the window: pool spawn + first dill decode on
        # every worker (incl. the slow one — its delay is paid here once)
        warm = c.submit_many(fid, [(((0.001,), {}))] * (n_workers * n_procs))
        for h in warm:
            h.result(timeout=120.0)
        handles = c.submit_many(
            fid,
            [(((task_s,), {}))] * n_tasks,
            costs=[task_s] * n_tasks,
            speculative=True,
        )
        t0 = time.perf_counter()
        # inf sentinel: a lost/errored task must push the tail to
        # infinity, never contribute a flattering 0.0 to the percentiles
        lat = [float("inf")] * n_tasks
        errs: list[str] = []

        def waiter(i, h):
            try:
                h.result(timeout=300.0)
                lat[i] = time.perf_counter() - t0
            except Exception as exc:  # loss shows as an error, not a hang
                errs.append(f"{h.task_id}: {type(exc).__name__}")

        threads = [
            _threading.Thread(target=waiter, args=(i, h), daemon=True)
            for i, h in enumerate(handles)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=310.0)
        arr = np.asarray(lat)
        spec = disp.stats()["speculation"]
        row = {
            "leg": "hedged" if speculate else "unhedged",
            "tasks": n_tasks,
            "completed": n_tasks - len(errs),
            "errors": errs,
            "run_s": round(float(arr.max()), 3) if len(arr) else None,
            "p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 1),
            "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 1),
            "p999_ms": round(float(np.percentile(arr, 99.9)) * 1e3, 1),
            "mean_ms": round(float(arr.mean()) * 1e3, 1),
            "speculation": spec,
        }
        if spec is not None:
            row["wasted_work_frac"] = round(
                spec["launched"] / max(n_tasks, 1), 4
            )
            row["loser_exec_s"] = spec["wasted_exec_s"]
        row.update(_tail_scrapes(gw, disp))
        return row
    finally:
        _tail_teardown(gw, disp, disp_thread, workers, handle)


def _tail_chaos_leg(
    n_tasks: int, n_workers: int, n_procs: int, task_s: float
) -> dict:
    """SIGKILL the worker running the ORIGINALS mid-hedge, under the race
    monitor: every admitted task must complete (replica first-wins, or
    promotion on the purge) with zero monitor errors."""
    from tpu_faas.client import FaaSClient
    from tpu_faas.core.serialize import serialize
    from tpu_faas.store.racecheck import RaceMonitor
    from tpu_faas.workloads import straggler_sleep

    monitor = RaceMonitor()
    gw, disp, disp_thread, workers, handle = _tail_stack(
        n_workers, n_procs, 30.0, True, monitor=monitor,
        time_to_expire=2.0,
    )
    try:
        time.sleep(1.5)
        c = FaaSClient(gw.url)
        fid = c.register_payload(
            "straggler_sleep", serialize(straggler_sleep)
        )
        # warm only the HEALTHY workers (tiny batch; the sick one's 30 s
        # delay must not gate the leg — its victims are the point)
        for h in c.submit_many(fid, [(((0.001,), {}))] * 2):
            h.result(timeout=120.0)
        handles = c.submit_many(
            fid,
            [(((task_s,), {}))] * n_tasks,
            costs=[task_s] * n_tasks,
            speculative=True,
        )
        deadline = time.monotonic() + 60.0
        while (
            time.monotonic() < deadline
            and disp.spec is not None
            and disp.spec.n_launched == 0
        ):
            time.sleep(0.02)
        hedges_at_kill = disp.spec.n_launched
        import os as _os
        import signal as _signal

        try:
            _os.killpg(workers[0].pid, _signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            workers[0].kill()
        workers[0].wait()
        completed = 0
        errs: list[str] = []
        for h in handles:
            try:
                h.result(timeout=300.0)
                completed += 1
            except Exception as exc:
                errs.append(f"{h.task_id}: {type(exc).__name__}")
        row = {
            "leg": "chaos-kill-original",
            "tasks": n_tasks,
            "completed": completed,
            "errors": errs,
            "hedges_at_kill": hedges_at_kill,
            "speculation": disp.stats()["speculation"],
            "monitor_errors": [str(v) for v in monitor.errors],
            "monitor_warnings": len(monitor.warnings),
            "zero_loss": completed == n_tasks,
            "race_clean": not monitor.errors,
        }
        row.update(_tail_scrapes(gw, disp))
        return row
    finally:
        _tail_teardown(gw, disp, disp_thread, workers, handle)


def config_18_tail_hedging() -> dict:
    """Tail-hedging lane (config 18, tpu_faas/spec): the speculation
    plane's promise measured on the full real stack — store server,
    gateway, tpu-push with --speculate-mult, real push-worker
    subprocesses with ONE deterministically sick worker (every execution
    there pays an injected delay; workloads.straggler_sleep).

    - **hedged vs unhedged**: an open-loop batch of speculative tasks
      with cost hints; the sick worker's victims own p99/p999 unhedged,
      and the hedged leg's replicas must beat them >= 1.5x at a
      wasted-work fraction (hedges launched / tasks) <= 0.3;
    - **chaos**: SIGKILL the worker running the ORIGINALS mid-hedge under
      the race monitor — 100% of admitted tasks complete, zero monitor
      errors.

    Shape via TPU_FAAS_BENCH_TAIL_SHAPE="tasks,workers,procs,task_ms,
    slow_ms" (default "48,4,2,40,1500");
    TPU_FAAS_BENCH_TAIL_CHAOS=0 skips the chaos leg."""
    import os

    shape = os.environ.get("TPU_FAAS_BENCH_TAIL_SHAPE", "48,4,2,40,1500")
    n_tasks, n_workers, n_procs, task_ms, slow_ms = (
        int(x) for x in shape.split(",")
    )
    task_s, slow_s = task_ms / 1e3, slow_ms / 1e3
    row: dict = {
        "config": "tail-hedging",
        "shape": {
            "tasks": n_tasks,
            "workers": n_workers,
            "procs": n_procs,
            "task_ms": task_ms,
            "slow_ms": slow_ms,
        },
        "host_cores": os.cpu_count(),
        "unhedged": _tail_leg(
            n_tasks, n_workers, n_procs, task_s, slow_s, False
        ),
        "hedged": _tail_leg(
            n_tasks, n_workers, n_procs, task_s, slow_s, True
        ),
    }
    hp99 = row["hedged"]["p99_ms"]
    row["p99_ratio_unhedged_over_hedged"] = (
        round(row["unhedged"]["p99_ms"] / hp99, 3) if hp99 else None
    )
    hp999 = row["hedged"]["p999_ms"]
    row["p999_ratio_unhedged_over_hedged"] = (
        round(row["unhedged"]["p999_ms"] / hp999, 3) if hp999 else None
    )
    if os.environ.get("TPU_FAAS_BENCH_TAIL_CHAOS", "1") != "0":
        row["chaos"] = _tail_chaos_leg(
            max(8, n_tasks // 4), n_workers, n_procs, task_s
        )
    return row


# -- config 19: composed tail-SLO product bench ------------------------------

#: default per-class objectives for the composed lane (overridable via
#: TPU_FAAS_BENCH_COMPOSED_SLO). The int_p999 threshold is the lane's
#: STATED interactive p999 bar — the row's verdict checks the measured
#: client-side p999 against it.
_COMPOSED_SLO_SPEC = (
    "int_p99=total@interactive:0.5:0.99,"
    "int_p999=total@interactive:2.0:0.999,"
    "batch_p99=total@batch:30:0.99,"
    "gw_int_p99=submit_to_finish@interactive:0.5:0.99"
)


def _composed_stack(n_workers: int, n_procs: int, slow_s: float):
    """Full real stack with EVERY opt-in plane on at once: store server,
    tracing gateway, tpu-push with express + micro-batching + weighted
    tenancy (bulk capped to half the fleet) + speculation + columnar
    intake, N real push-worker subprocesses with worker 0 deterministically
    sick (``slow_s`` injected per execution). Callers must already hold
    the composed env gates (class label, hi-res buckets, SLO spec) — both
    serving processes read them at construction."""
    import threading as _threading

    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.gateway import start_gateway_thread
    from tpu_faas.store.launch import make_store, start_store_thread

    handle = start_store_thread()
    # admission OFF for the same reason as configs 16/18: the lane
    # measures in-tick composition among admitted tasks; edge 429s are
    # config 10's surface
    gw = start_gateway_thread(
        make_store(handle.url), admission=False, trace=True
    )
    cap_bulk = max(1, (n_workers * n_procs) // 2)
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=make_store(handle.url),
        max_workers=max(16, n_workers),
        max_pending=2048,
        max_inflight=2048,
        max_slots=n_procs,
        tick_period=0.005,
        time_to_expire=60.0,
        # pin predictions to the client cost hints (config 18's rule):
        # the sick worker's injected delay is the variable under test
        estimate_runtimes=False,
        express=True,
        batch_max=4,
        batch_window_ms=1.0,
        tenant_shares="fast=3,bulk=1",
        tenant_caps=f"bulk={cap_bulk}",
        speculate_mult=3.0,
        speculate_max_frac=0.3,
        speculate_min_s=0.02,
        columnar=True,
    )
    disp_thread = _threading.Thread(target=disp.start, daemon=True)
    disp_thread.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        _tail_spawn_worker(n_procs, url, slow_s if i == 0 else None)
        for i in range(n_workers)
    ]
    return gw, disp, disp_thread, workers, handle


def _attrib_totals(fams) -> dict:
    """{plane: {outcome: {class: value}}} from one parsed exposition, or
    {} when the family is absent (gate off — a lane bug here)."""
    fam = fams.get("tpu_faas_task_attrib_total")
    if fam is None:
        return {}
    out: dict = {}
    for s in fam.samples:
        plane, outcome, cls = (
            s.labels["plane"], s.labels["outcome"], s.labels["class"]
        )
        out.setdefault(plane, {}).setdefault(outcome, {})[cls] = int(s.value)
    return out


def _plane_sum(attrib: dict, plane: str, *outcomes: str) -> int:
    total = 0
    for outcome in outcomes or tuple(attrib.get(plane, ())):
        total += sum(attrib.get(plane, {}).get(outcome, {}).values())
    return total


def _composed_scrapes(gw, disp) -> dict:
    """Strict-grammar /metrics from both serving processes plus their
    /slo and /flightrec bodies — the composed lane's required families
    include the class-labeled histograms, the attribution counters, the
    per-objective burn gauges and the worker-health family."""
    import requests as _requests

    from tpu_faas.obs.expofmt import parse_exposition, require_series

    out: dict = {"scrape_ok": True, "missing": [], "error": ""}
    try:
        srv = disp.serve_stats(0)
        port = srv.server_address[1]
        base = f"http://127.0.0.1:{port}"
        dfams = parse_exposition(
            _requests.get(f"{base}/metrics", timeout=10).text
        )
        out["missing"] = require_series(
            dfams,
            [
                "tpu_faas_task_attrib_total",
                "tpu_faas_task_stage_seconds",
                "tpu_faas_slo_burn_rate",
                "tpu_faas_worker_health",
                "tpu_faas_tenant_queue_depth",
                "tpu_faas_dispatcher_hedges_total",
            ],
        )
        gfams = parse_exposition(
            _requests.get(f"{gw.url}/metrics", timeout=10).text
        )
        out["missing"] += require_series(
            gfams,
            [
                "tpu_faas_task_attrib_total",
                "tpu_faas_task_e2e_seconds",
                "tpu_faas_slo_burn_rate",
            ],
        )
        # the class label actually rides the latency histograms
        stage_fam = dfams["tpu_faas_task_stage_seconds"]
        out["class_label_live"] = any(
            s.labels.get("class") == "interactive" for s in stage_fam.samples
        )
        # hi-res ladder: the e2e histogram carries ~30 le= bounds + +Inf
        e2e = gfams["tpu_faas_task_e2e_seconds"]
        les = {
            s.labels["le"]
            for s in e2e.samples
            if s.name.endswith("_bucket")
        }
        out["hires_bucket_count"] = len(les)
        # per-plane attribution, summed across both processes
        d_at, g_at = _attrib_totals(dfams), _attrib_totals(gfams)
        out["attribution"] = {"dispatcher": d_at, "gateway": g_at}
        out["planes_live"] = {
            "express": _plane_sum(g_at, "express", "inline") > 0,
            "batch": _plane_sum(d_at, "batch", "bundle_rode") > 0,
            "speculation": _plane_sum(d_at, "speculation") > 0,
            "tenancy": _plane_sum(d_at, "tenancy") > 0,
            "columnar": _plane_sum(d_at, "columnar", "arena") > 0,
        }
        out["slo"] = {
            "dispatcher": _http_json(f"{base}/slo"),
            "gateway": _http_json(f"{gw.url}/slo"),
        }
        frec_d = _http_json(f"{base}/flightrec")
        frec_g = _http_json(f"{gw.url}/flightrec")
        kinds: dict[str, int] = {}
        for body in (frec_d, frec_g):
            for ev in body.get("events", []):
                kinds[ev["kind"]] = kinds.get(ev["kind"], 0) + 1
        out["flightrec"] = {
            "dispatcher_events": len(frec_d.get("events", [])),
            "gateway_events": len(frec_g.get("events", [])),
            "kinds": kinds,
        }
        out["scrape_ok"] = not out["missing"]
    except Exception as exc:
        out["scrape_ok"] = False
        out["error"] = f"{type(exc).__name__}: {exc}"
    return out


def config_19_composed_slo() -> dict:
    """Composed tail-SLO lane (config 19): ALL four opt-in planes live at
    once — express result delivery, micro-batching, weighted tenancy with
    an inflight cap, device-scored speculation — plus columnar intake, on
    the full real stack under mixed insult traffic: closed-loop SHORT
    interactive tasks racing a saturating BULK tenant's long batch
    backlog across a fleet with one deterministically sick worker.

    The composed observability plane is on (TPU_FAAS_OBS_CLASS +
    TPU_FAAS_OBS_HIRES_BUCKETS + per-class TPU_FAAS_SLO): the row reports
    client-side p50/p99/p999 PER CLASS, both processes' /slo burn rates
    (per-class objectives included), the per-plane attribution counter
    totals proving every plane actually touched tasks, the flight
    recorders' event mix, and strict-grammar /metrics verdicts from every
    serving process. The headline verdict: the stated interactive p999
    objective HELD while every plane was live.

    Shape via TPU_FAAS_BENCH_COMPOSED_SHAPE =
    "interactive,loops,batch_backlog,workers,procs,task_ms,batch_ms,
    slow_ms" (default "120,12,60,4,2,20,100,800" — loops deliberately
    exceeds the fleet's slot count so the health-aware scheduler cannot
    fully route around the sick worker and the speculation plane
    reliably has stragglers to hedge); objectives via
    TPU_FAAS_BENCH_COMPOSED_SLO."""
    import os
    import threading as _threading

    from tpu_faas.client import FaaSClient
    from tpu_faas.core.serialize import serialize
    from tpu_faas.obs.attribution import CLASS_ENV, HIRES_ENV
    from tpu_faas.obs.slo import SLO_ENV, parse_objectives
    from tpu_faas.workloads import straggler_sleep

    shape = os.environ.get(
        "TPU_FAAS_BENCH_COMPOSED_SHAPE", "120,12,60,4,2,20,100,800"
    )
    (
        n_int, n_loops, backlog, n_workers, n_procs, task_ms, batch_ms,
        slow_ms,
    ) = (int(x) for x in shape.split(","))
    task_s, batch_s, slow_s = task_ms / 1e3, batch_ms / 1e3, slow_ms / 1e3
    slo_spec = os.environ.get(
        "TPU_FAAS_BENCH_COMPOSED_SLO", _COMPOSED_SLO_SPEC
    )
    p999_objective_s = next(
        (
            o.threshold_s
            for o in parse_objectives(slo_spec)
            if o.name == "int_p999"
        ),
        None,
    )
    saved = {k: os.environ.get(k) for k in (CLASS_ENV, HIRES_ENV, SLO_ENV)}
    os.environ[CLASS_ENV] = "1"
    os.environ[HIRES_ENV] = "1"
    os.environ[SLO_ENV] = slo_spec
    stack = None
    try:
        stack = _composed_stack(n_workers, n_procs, slow_s)
        gw, disp, disp_thread, workers, handle = stack
        time.sleep(1.5)  # workers register
        fast = FaaSClient(gw.url, tenant="fast", trace=True)
        bulk = FaaSClient(gw.url, tenant="bulk", trace=True)
        fid = fast.register_payload(
            "straggler_sleep", serialize(straggler_sleep)
        )
        # warmup outside the window: pool spawn + first dill decode on
        # every worker (the sick one's delay is paid here once)
        for h in fast.submit_many(
            fid, [(((0.001,), {}))] * (n_workers * n_procs)
        ):
            h.result(timeout=120.0)
        # the insult: a saturating batch backlog from the capped tenant
        bulk_handles = bulk.submit_many(
            fid,
            [(((batch_s,), {}))] * backlog,
            costs=[batch_s] * backlog,
            slo_class="batch",
        )
        t0 = time.perf_counter()
        int_lat: list[list[float]] = [[] for _ in range(n_loops)]
        int_errs: list[str] = []
        per_loop = max(1, n_int // n_loops)

        def int_loop(i: int) -> None:
            # closed loop: each iteration is one interactive RTT — the
            # latency an interactive CALLER sees, not backlog drain
            for _ in range(per_loop):
                s = time.perf_counter()
                try:
                    fast.submit_with(
                        fid,
                        (task_s,),
                        cost=task_s,
                        speculative=True,
                        slo_class="interactive",
                    ).result(timeout=300.0)
                    int_lat[i].append(time.perf_counter() - s)
                except Exception as exc:
                    int_errs.append(type(exc).__name__)

        threads = [
            _threading.Thread(target=int_loop, args=(i,), daemon=True)
            for i in range(n_loops)
        ]
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=600.0)
        int_run_s = time.perf_counter() - t0
        # drain the batch class too (its percentiles + /slo need closes)
        batch_done, batch_errs = 0, []
        batch_lat: list[float] = []
        for h in bulk_handles:
            try:
                h.result(timeout=300.0)
                batch_lat.append(time.perf_counter() - t0)
                batch_done += 1
            except Exception as exc:
                batch_errs.append(type(exc).__name__)
        arr_i = np.asarray([v for lane in int_lat for v in lane])
        arr_b = np.asarray(batch_lat) if batch_lat else np.asarray([0.0])

        def _pcts(arr) -> dict:
            return {
                "p50_ms": round(float(np.percentile(arr, 50)) * 1e3, 1),
                "p99_ms": round(float(np.percentile(arr, 99)) * 1e3, 1),
                "p999_ms": round(float(np.percentile(arr, 99.9)) * 1e3, 1),
                "mean_ms": round(float(arr.mean()) * 1e3, 1),
            }

        stats = disp.stats()
        row = {
            "config": "composed-slo",
            "shape": {
                "interactive": len(arr_i),
                "loops": n_loops,
                "batch_backlog": backlog,
                "workers": n_workers,
                "procs": n_procs,
                "task_ms": task_ms,
                "batch_ms": batch_ms,
                "slow_ms": slow_ms,
            },
            "host_cores": os.cpu_count(),
            "slo_spec": slo_spec,
            "interactive": {
                "completed": int(len(arr_i)),
                "errors": int_errs,
                "run_s": round(int_run_s, 2),
                **_pcts(arr_i),
            },
            "batch": {
                "completed": batch_done,
                "errors": batch_errs,
                **_pcts(arr_b),
            },
            "speculation": stats.get("speculation"),
            "tenancy": stats.get("tenancy"),
            "worker_health": stats.get("worker_health"),
        }
        row.update(_composed_scrapes(gw, disp))
        planes = row.get("planes_live", {})
        row["all_planes_live"] = bool(planes) and all(planes.values())
        if p999_objective_s is not None and len(arr_i):
            row["interactive_p999_objective_ms"] = p999_objective_s * 1e3
            row["interactive_p999_held"] = (
                float(np.percentile(arr_i, 99.9)) <= p999_objective_s
            )
        return row
    finally:
        if stack is not None:
            _tail_teardown(*stack)
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# -- config 20: chaos scenario runner (fault plane + quarantine) -------------


def _chaos_spawn_worker(n_procs: int, url: str, chaos_spec: str | None):
    """One push-worker subprocess with an explicit per-process chaos spec.

    ``cpu_worker_env()`` inherits the bench process's environment — which
    config 20 arms with DISPATCHER-side chaos — so the worker's
    TPU_FAAS_CHAOS is always overridden here: cleared for healthy
    workers, set to the gray-failure spec for the victim."""
    import subprocess
    import sys as _sys

    from tpu_faas.bench.harness import REPO, cpu_worker_env
    from tpu_faas.chaos import ENV_VAR

    env = cpu_worker_env()
    env.pop(ENV_VAR, None)
    if chaos_spec:
        env[ENV_VAR] = chaos_spec
    return subprocess.Popen(
        [_sys.executable, "-m", "tpu_faas.worker.push_worker",
         str(n_procs), url, "--hb", "--hb-period", "0.3"],
        env=env, cwd=REPO, start_new_session=True,
        stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
    )


def _chaos_stack(
    n_workers: int,
    n_procs: int,
    gray_ms: int,
    gray_until_s: int,
    seed: int,
):
    """Full real stack for the chaos lane: store server, gateway,
    tpu-push with speculation AND quarantine on, N push-worker
    subprocesses with worker 0 gray-failing (chaos exec.slow stalls its
    intake for the first ``gray_until_s`` seconds of its life, then the
    window closes and the worker is healthy again).

    The caller must already hold TPU_FAAS_CHAOS armed with the
    dispatcher-side spec — the store clients and the dispatcher wire
    read it at construction."""
    import threading as _threading

    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.gateway import start_gateway_thread
    from tpu_faas.store.launch import make_store, start_store_thread

    handle = start_store_thread()
    gw = start_gateway_thread(make_store(handle.url), admission=False)
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=make_store(handle.url),
        max_workers=max(16, n_workers),
        max_pending=1024,
        max_inflight=1024,
        max_slots=n_procs,
        tick_period=0.005,
        # liveness must NOT be what catches the gray worker: with a 60 s
        # horizon the heartbeat path never fires inside the scenario, so
        # a quarantine transition is provably the health plane's doing
        time_to_expire=60.0,
        # pin predictions to the client cost hints (config 18's rule):
        # the injected stall is the one variable under test
        estimate_runtimes=False,
        speculate_mult=3.0,
        speculate_max_frac=0.5,
        speculate_min_s=0.02,
        quarantine=True,
        # bench-speed thresholds: two lost hedge races (0.8^2 = 0.64)
        # put the gray row under the enter bar — the gray worker's
        # stalled slots throttle how fast it can accumulate evidence, so
        # the bar must be reachable from a handful of races; release
        # needs the score back over 0.8
        quarantine_enter=0.7,
        quarantine_release=0.8,
        quarantine_canary_s=0.5,
    )
    # instance shadow of the class constant: the production 30 s recovery
    # tau would stretch this scenario past any CI budget; 3 s keeps the
    # release transition inside the run without touching the policy
    disp.arrays.HEALTH_RECOVERY_TAU = 3.0
    disp_thread = _threading.Thread(target=disp.start, daemon=True)
    disp_thread.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    gray_spec = (
        f"seed={seed};exec.slow:ms={gray_ms}:p=1:until={gray_until_s}"
    )
    workers = [
        _chaos_spawn_worker(n_procs, url, gray_spec if i == 0 else None)
        for i in range(n_workers)
    ]
    return gw, disp, disp_thread, workers, handle, gray_spec


def _chaos_scrapes(gw, disp) -> dict:
    """Strict-grammar /metrics from every serving process: the chaos lane
    requires the injection counter, the quarantine state family and the
    health family on the dispatcher surface."""
    import requests as _requests

    from tpu_faas.obs.expofmt import parse_exposition, require_series

    out: dict = {"scrape_ok": True, "missing": [], "error": ""}
    try:
        srv = disp.serve_stats(0)
        port = srv.server_address[1]
        dfams = parse_exposition(
            _requests.get(
                f"http://127.0.0.1:{port}/metrics", timeout=10
            ).text
        )
        out["missing"] = require_series(
            dfams,
            [
                "tpu_faas_chaos_injected_total",
                "tpu_faas_worker_quarantined",
                "tpu_faas_worker_health",
                "tpu_faas_dispatcher_hedges_total",
                "tpu_faas_dispatcher_tasks_dispatched_total",
            ],
        )
        fam = dfams.get("tpu_faas_chaos_injected_total")
        out["scraped_injections"] = (
            int(sum(s.value for s in fam.samples)) if fam else 0
        )
        qfam = dfams.get("tpu_faas_worker_quarantined")
        out["quarantine_series"] = (
            {s.labels["state"]: int(s.value) for s in qfam.samples}
            if qfam
            else {}
        )
        gfams = parse_exposition(
            _requests.get(f"{gw.url}/metrics", timeout=10).text
        )
        out["missing"] += require_series(
            gfams, ["tpu_faas_gateway_safety_poll_served_total"]
        )
        out["scrape_ok"] = not out["missing"]
    except Exception as exc:
        out["scrape_ok"] = False
        out["error"] = f"{type(exc).__name__}: {exc}"
    return out


def config_20_chaos_quarantine() -> dict:
    """Chaos scenario runner (config 20): the full real stack under a
    seeded fault schedule, proving the PR's two halves together.

    The schedule: worker 0 gray-fails — chaos ``exec.slow`` stalls its
    intake thread per task while its heartbeats keep flowing — for the
    first ``gray_until_s`` seconds of its life, then recovers. The
    dispatcher process itself runs under chaos too (store round-trip
    latency + held wire frames), so the control plane is exercised dirty,
    not clean. Speculation hedges the stalled tasks; every lost race
    decays the gray row's health score; the quarantine book trips, drains
    the row (placement ceiling 0), probes it with canary tasks
    (ceiling 1), and releases it once the window closes and the score
    recovers.

    Asserted: ZERO admitted-task loss (every submitted handle reaches a
    result — the reclaim/hedge machinery absorbs every injection),
    quarantine entered BEFORE any liveness purge (the gray worker's row
    still active and its process alive at the enter transition — health
    beat heartbeat lapse by design: the horizon is 60 s, the enter fires
    in single-digit seconds), bounded recovery (a release transition
    observed inside the run), and strict /metrics scrapes carrying the
    injection counter and the quarantine state family.

    Shape via TPU_FAAS_BENCH_CHAOS_SHAPE =
    "tasks,workers,procs,task_ms,gray_ms,gray_until_s,seed"
    (default "160,3,2,25,600,8,20")."""
    import os
    import threading as _threading

    from tpu_faas import chaos as chaos_mod
    from tpu_faas.client import FaaSClient
    from tpu_faas.core.serialize import serialize
    from tpu_faas.workloads import straggler_sleep

    shape = os.environ.get(
        "TPU_FAAS_BENCH_CHAOS_SHAPE", "160,3,2,25,600,8,20"
    )
    (
        n_tasks, n_workers, n_procs, task_ms, gray_ms, gray_until_s, seed,
    ) = (int(x) for x in shape.split(","))
    task_s = task_ms / 1e3
    disp_spec = (
        f"seed={seed};store.latency:ms=2:p=0.1,wire.delay:ms=5:p=0.05"
    )
    saved = os.environ.get(chaos_mod.ENV_VAR)
    os.environ[chaos_mod.ENV_VAR] = disp_spec
    chaos_mod._reset_for_tests()
    stack = None
    try:
        stack = _chaos_stack(
            n_workers, n_procs, gray_ms, gray_until_s, seed
        )
        gw, disp, disp_thread, workers, handle, gray_spec = stack[:6]
        plan = chaos_mod.from_env()  # the serving process's armed plan
        time.sleep(1.5)  # workers register (gray window is already open)
        c = FaaSClient(gw.url)
        fid = c.register_payload(
            "straggler_sleep", serialize(straggler_sleep)
        )
        # warmup outside the window: pool spawn + first dill decode on
        # every worker (the gray one's stall is paid per task here too)
        for h in c.submit_many(
            fid, [(((0.001,), {}))] * (n_workers * n_procs)
        ):
            h.result(timeout=120.0)
        t0 = time.perf_counter()
        handles = c.submit_many(
            fid,
            [(((task_s,), {}))] * n_tasks,
            costs=[task_s] * n_tasks,
            speculative=True,
        )
        # inf sentinel (config 18's rule): a lost task must poison the
        # percentiles, never contribute a flattering 0.0
        lat = [float("inf")] * n_tasks
        errs: list[str] = []

        def waiter(i, h):
            try:
                h.result(timeout=120.0)
                lat[i] = time.perf_counter() - t0
            except Exception as exc:
                errs.append(f"{h.task_id}: {type(exc).__name__}")

        threads = [
            _threading.Thread(target=waiter, args=(i, h), daemon=True)
            for i, h in enumerate(handles)
        ]
        for th in threads:
            th.start()
        # scenario observer: record the quarantine transitions as they
        # happen, and keep a steady probe trickle flowing for the whole
        # scenario — BEFORE the enter it is the placement pressure that
        # re-fills the gray worker's slots as hedges resolve (each
        # re-placement is another lost race, i.e. fresh health
        # evidence); AFTER the enter it is what canary ticks carry
        q = disp.quarantine
        first_enter = first_release = None
        active_at_enter: int | None = None
        gray_alive_at_enter: bool | None = None
        trickle: list = []
        last_trickle = 0.0
        deadline = t0 + gray_until_s + 25.0
        while time.perf_counter() < deadline:
            now = time.perf_counter()
            if first_enter is None and q.entered_total > 0:
                first_enter = now - t0
                # the proof the ISSUE asks for: at the enter transition
                # the gray worker is still a LIVE fleet member — no row
                # purged, its process up — so quarantine beat liveness
                active_at_enter = int(disp.arrays.worker_active.sum())
                gray_alive_at_enter = workers[0].poll() is None
            if first_enter is not None and q.released_total > 0:
                first_release = now - t0
                break
            if now - last_trickle > 0.1:
                trickle.append(
                    c.submit_with(
                        fid, (task_s,), cost=task_s, speculative=True
                    )
                )
                last_trickle = now
            time.sleep(0.05)
        for th in threads:
            th.join(timeout=130.0)
        trickle_errs: list[str] = []
        trickle_done = 0
        for h in trickle:
            try:
                h.result(timeout=60.0)
                trickle_done += 1
            except Exception as exc:
                trickle_errs.append(type(exc).__name__)
        finished = np.asarray([v for v in lat if v != float("inf")])
        if not len(finished):
            finished = np.asarray([0.0])
        qs = q.stats()
        inj = {
            f"{site}.{kind}": int(v)
            for (site, kind), v in sorted(
                (plan.counts if plan is not None else {}).items()
            )
        }
        stats = disp.stats()
        admitted = n_tasks + len(trickle)
        completed = (n_tasks - len(errs)) + trickle_done
        row = {
            "config": "chaos-quarantine",
            "shape": {
                "tasks": n_tasks,
                "workers": n_workers,
                "procs": n_procs,
                "task_ms": task_ms,
                "gray_ms": gray_ms,
                "gray_until_s": gray_until_s,
                "seed": seed,
            },
            "host_cores": os.cpu_count(),
            "chaos": {
                "dispatcher_spec": disp_spec,
                "gray_worker_spec": gray_spec,
                "dispatcher_injections": inj,
                "injected_total": int(sum(inj.values())),
            },
            "admitted": admitted,
            "completed": completed,
            "errors": errs + trickle_errs,
            "zero_admitted_loss": completed == admitted,
            "p50_ms": round(float(np.percentile(finished, 50)) * 1e3, 1),
            "p99_ms": round(float(np.percentile(finished, 99)) * 1e3, 1),
            "p999_ms": round(
                float(np.percentile(finished, 99.9)) * 1e3, 1
            ),
            "quarantine": qs,
            "quarantine_entered": qs["entered_total"] >= 1,
            "quarantine_released": qs["released_total"] >= 1,
            "time_to_quarantine_s": (
                None if first_enter is None else round(first_enter, 2)
            ),
            "time_to_release_s": (
                None if first_release is None else round(first_release, 2)
            ),
            "entered_before_liveness": bool(
                gray_alive_at_enter and active_at_enter == n_workers
            ),
            "worker_health": stats.get("worker_health"),
            "speculation": stats.get("speculation"),
        }
        row.update(_chaos_scrapes(gw, disp))
        row["verdict_pass"] = bool(
            row["zero_admitted_loss"]
            and row["quarantine_entered"]
            and row["quarantine_released"]
            and row["entered_before_liveness"]
            and row["chaos"]["injected_total"] > 0
            and row["scrape_ok"]
        )
        return row
    finally:
        if stack is not None:
            _tail_teardown(*stack[:5])
        if saved is None:
            os.environ.pop(chaos_mod.ENV_VAR, None)
        else:
            os.environ[chaos_mod.ENV_VAR] = saved
        chaos_mod._reset_for_tests()


def _graph_locality_leg(
    result_blobs: bool,
    width: int,
    rounds: int,
    n_workers: int,
    n_procs: int,
    n_kib: int,
) -> dict:
    """One graph-locality leg over the real stack: store server over TCP,
    gateway, tpu-push dispatcher, in-process PushWorker threads (their
    ``result_cache`` counters are the leg's cache-hit evidence — a
    subprocess fleet would hide them). ``result_blobs=False`` is the
    store-mediated CONTROL (--dep-results): parent bodies finish into
    the store and the dispatcher reads them back per child. True is the
    TREATMENT (--result-blobs): digest-only results, bodies riding
    worker caches edge-to-edge."""
    import threading as _threading

    from tpu_faas.client import FaaSClient
    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.gateway import start_gateway_thread
    from tpu_faas.store.launch import make_store, start_store_thread
    from tpu_faas.worker.push_worker import PushWorker
    from tpu_faas.workloads import big_result, merge_deps, no_op

    nodes_per_graph = width + 1
    handle = start_store_thread()
    gw = start_gateway_thread(make_store(handle.url))
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=make_store(handle.url),
        max_workers=max(64, n_workers),
        max_pending=max(256, 4 * nodes_per_graph * rounds),
        max_inflight=4096,
        max_slots=n_procs,
        tick_period=0.005,
        dep_results=not result_blobs,
        result_blobs=result_blobs,
    )
    disp_thread = _threading.Thread(target=disp.start, daemon=True)
    disp_thread.start()
    url = f"tcp://127.0.0.1:{disp.port}"
    workers = [
        PushWorker(n_procs, url, heartbeat=True, heartbeat_period=0.5)
        for _ in range(n_workers)
    ]
    worker_threads = [
        _threading.Thread(target=w.run, daemon=True) for w in workers
    ]
    for t in worker_threads:
        t.start()
    client = FaaSClient(gw.url)
    try:
        time.sleep(1.0)  # workers register
        # warmup outside the measured window (pool spawn + dill decode)
        wfid = client.register(no_op)
        for h in client.submit_many(
            wfid, [((), {})] * (2 * n_procs * n_workers)
        ):
            h.result(timeout=120.0)
        read0 = disp.m_result_store_bytes.labels(dir="read").value
        write0 = disp.m_result_store_bytes.labels(dir="write").value
        makespans: list[float] = []
        t0 = time.perf_counter()
        for r in range(rounds):
            g = client.graph()
            parents = [
                g.call(big_result, n_kib, seed=r * width + i)
                for i in range(width)
            ]
            sink = g.call(merge_deps, f"r{r}", after=parents)
            g.submit()
            t_g = time.perf_counter()
            merged = sink.result(timeout=300.0)
            makespans.append(time.perf_counter() - t_g)
            # correctness oracle: the sink saw every parent byte on BOTH
            # lanes (merge_deps reports parent count + total chars)
            assert merged == f"r{r}:{width}:{width * n_kib * 1024}", merged
        leg_s = time.perf_counter() - t0
        n_results = nodes_per_graph * rounds
        read_b = disp.m_result_store_bytes.labels(dir="read").value - read0
        write_b = (
            disp.m_result_store_bytes.labels(dir="write").value - write0
        )
        return {
            "completed": len(makespans),
            "leg_s": round(leg_s, 3),
            "makespan_p50_s": round(
                float(np.percentile(makespans, 50)), 4
            ),
            "makespan_max_s": round(max(makespans), 4),
            # the headline quantity: RESULT bytes that round-tripped the
            # store, per graph node (control pays a write per parent
            # body plus a read per delivered dep; the digest lane pays
            # only the sink's small final answer)
            "result_store_read_bytes": int(read_b),
            "result_store_write_bytes": int(write_b),
            "result_store_bytes_per_task": round(
                (read_b + write_b) / max(n_results, 1), 1
            ),
            "worker_rcache_hits": sum(
                w.result_cache.hits for w in workers
            ),
            "worker_rcache_misses": sum(
                w.result_cache.misses for w in workers
            ),
            "rblob_pulls_filled": disp.m_rblob_pulls.labels(
                outcome="filled"
            ).value,
            "frontier_dispatches": disp.n_frontier_dispatches,
        }
    finally:
        for w in workers:
            w.stop()
        for t in worker_threads:
            t.join(timeout=10)
        disp.stop()
        disp_thread.join(timeout=10)
        gw.stop()
        handle.stop()


def config_21_graph_locality() -> dict:
    """Graph data locality (config 21): the result data plane's headline
    row — a map-reduce graph (``width`` parents each producing an
    ``result_kib``-KiB body, one sink consuming them all, repeated
    ``rounds`` times) run twice over the full real stack:

    - **control leg** (--dep-results): parent results finish into the
      store; the dispatcher reads every body back and ships it inline on
      the sink's TASK frame. Every parent byte round-trips the store.
    - **blobs leg** (--result-blobs): workers hash-and-hold large
      results, records carry digests, and the sink's frame carries
      ``dep_digests`` served from worker result caches — parent bytes
      never touch the store.

    Reported per leg: makespan percentiles, result store bytes per
    graph node (read + write), worker result-cache hit counts, and the
    reduction ratio the acceptance bar asserts (>= 5x on the default
    shape). Shape via TPU_FAAS_BENCH_RBLOB_SHAPE=
    "width,rounds,workers,procs,result_kib" (default "8,6,4,2,16"); the
    CI graph-locality-smoke lane runs "4,3,2,2,8"."""
    import os

    shape = os.environ.get("TPU_FAAS_BENCH_RBLOB_SHAPE", "8,6,4,2,16")
    width, rounds, n_workers, n_procs, n_kib = (
        int(x) for x in shape.split(",")
    )
    control = _graph_locality_leg(
        False, width, rounds, n_workers, n_procs, n_kib
    )
    blobs = _graph_locality_leg(
        True, width, rounds, n_workers, n_procs, n_kib
    )
    return {
        "config": "graph-locality",
        "shape": {
            "width": width,
            "rounds": rounds,
            "workers": n_workers,
            "procs": n_procs,
            "result_kib": n_kib,
            "nodes": (width + 1) * rounds,
        },
        "control": control,
        "blobs": blobs,
        # acceptance headline: store result-bytes per graph node,
        # store-mediated vs digest lane
        "result_store_bytes_per_task_reduction_x": round(
            control["result_store_bytes_per_task"]
            / max(blobs["result_store_bytes_per_task"], 1e-9),
            2,
        ),
        "makespan_p50_speedup_x": round(
            control["makespan_p50_s"]
            / max(blobs["makespan_p50_s"], 1e-9),
            3,
        ),
    }


CONFIGS = {
    "1": config_1_push_sleep,
    "2": config_2_pull_mixed,
    "3": config_3_auction_1k_10k,
    "4": config_4_sinkhorn_hetero,
    "5": config_5_churn_4k,
    "6": config_6_batch_register,
    "7": config_7_bid_headline,
    "8": config_8_estimation,
    "9": config_9_host_dispatch,
    "10": config_10_overload,
    "11": config_11_payload_plane,
    "12": config_12_latency,
    "13": config_13_graph_pipeline,
    "14": config_14_fleet,
    "15": config_15_tick_trajectory,
    "16": config_16_tenant_fairness,
    "17": config_17_batched_plane,
    "18": config_18_tail_hedging,
    "19": config_19_composed_slo,
    "20": config_20_chaos_quarantine,
    "21": config_21_graph_locality,
}
