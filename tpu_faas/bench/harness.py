"""End-to-end service benchmark (reference client_performance.py analog).

Spawns the full stack — store server (native C++ if buildable, else the
Python fallback), REST gateway, a dispatcher in the chosen mode, N worker
subprocesses — then measures, from the client side:

- time_to_register_s: wall time to POST every execute_function call
  (reference client_performance.py:109-116);
- throughput_tps: n_tasks / wall time of the result-poll window
  (reference :119-139);
- avg_latency_s: mean(completion - submit) per task (reference :115,131,140);
- correctness: every result equals the locally recomputed value
  (reference test_client.py:121-126).

Medians over ``n_sims`` runs with a FLUSHDB between (reference :162,253).
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field

import numpy as np

from tpu_faas.client import FaaSClient
from tpu_faas.gateway import start_gateway_thread
from tpu_faas.store.launch import make_store, start_store_thread
from tpu_faas.utils.logging import get_logger
from tpu_faas.workloads import make_workload

log = get_logger("bench")

REPO = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)


@dataclass
class BenchResult:
    mode: str
    n_workers: int
    n_procs: int
    n_tasks: int
    throughput_tps: float
    avg_latency_s: float
    time_to_register_s: float
    correctness_rate: float
    sims: int = 1
    extras: dict = field(default_factory=dict)

    def to_dict(self) -> dict:
        return {
            "mode": self.mode,
            "workers": self.n_workers,
            "procs_per_worker": self.n_procs,
            "n_tasks": self.n_tasks,
            "throughput_tps": round(self.throughput_tps, 2),
            "avg_latency_s": round(self.avg_latency_s, 4),
            "time_to_register_s": round(self.time_to_register_s, 4),
            "correctness_rate": self.correctness_rate,
            "sims": self.sims,
            **self.extras,
        }


def cpu_worker_env() -> dict:
    """Environment for spawning a PURE-CPU worker subprocess: the repo on
    PYTHONPATH, minus sitecustomize dirs (e.g. ".axon_site") that import
    JAX into every interpreter on dev boxes — a worker + its forkserver +
    each pool child paying a ~2 s jax import stretches worker cold-start
    to ~10 s, flaking timing-sensitive e2e tests and inflating measured
    time_to_register. Shared by the bench harness and the test spawners."""
    existing = os.environ.get("PYTHONPATH", "")
    kept = [
        p
        for p in existing.split(":")
        if p and not os.path.basename(p.rstrip("/")).endswith("_site")
    ]
    # Pin the JAX backend of every spawned child: a tpu-push dispatcher
    # subprocess that initializes the default (tunneled-TPU) backend hangs
    # indefinitely when the tunnel is down, turning an unrelated outage into
    # a red suite (cost round 2 one e2e test). Default cpu, following the
    # suite-wide TPU_FAAS_TEST_PLATFORM override when set.
    platform = os.environ.get("TPU_FAAS_PLATFORM") or os.environ.get(
        "TPU_FAAS_TEST_PLATFORM", "cpu"
    )
    return dict(
        os.environ,
        PYTHONPATH=":".join([REPO, *kept]),
        TPU_FAAS_PLATFORM=platform,
    )


def _spawn_worker(kind: str, n_procs: int, url: str, *extra: str):
    env = cpu_worker_env()
    return subprocess.Popen(
        [sys.executable, "-m", f"tpu_faas.worker.{kind}", str(n_procs), url]
        + list(extra),
        env=env,
        cwd=REPO,
        stdout=subprocess.DEVNULL,
        stderr=subprocess.DEVNULL,
    )


@contextmanager
def full_stack(
    mode: str,
    n_workers: int,
    n_procs: int,
    store_backend: str = "auto",
    time_to_expire: float = 10.0,
):
    """Spin up store + gateway + dispatcher + workers; yield (client, store)."""
    native_handle = None
    store_thread_handle = None
    if store_backend in ("auto", "native"):
        try:
            from tpu_faas.store.native import start_native_store

            native_handle = start_native_store()
            store_url = native_handle.url
        except Exception as exc:
            if store_backend == "native":
                raise
            log.info("native store unavailable (%s); using Python server", exc)
    if native_handle is None:
        store_thread_handle = start_store_thread()
        store_url = store_thread_handle.url

    gw = start_gateway_thread(make_store(store_url))
    admin_store = make_store(store_url)

    disp = None
    disp_thread = None
    workers: list[subprocess.Popen] = []
    local_equiv = None
    try:
        if mode == "local":
            from tpu_faas.dispatch.local import LocalDispatcher

            # local-equivalent sizing: one pool matching the whole remote
            # fleet (reference client_performance.py:211-218)
            local_equiv = n_workers * n_procs
            disp = LocalDispatcher(
                num_workers=local_equiv, store=make_store(store_url)
            )
            disp_thread = threading.Thread(target=disp.start, daemon=True)
            disp_thread.start()
        else:
            if mode == "pull":
                from tpu_faas.dispatch.pull import PullDispatcher

                disp = PullDispatcher(
                    ip="127.0.0.1", port=0, store=make_store(store_url)
                )
                worker_kind, extra = "pull_worker", ("--delay", "0.005")
            elif mode in ("push", "push-hb", "push-plb"):
                from tpu_faas.dispatch.push import PushDispatcher

                disp = PushDispatcher(
                    ip="127.0.0.1",
                    port=0,
                    store=make_store(store_url),
                    heartbeat=(mode == "push-hb"),
                    process_lb=(mode == "push-plb"),
                    time_to_expire=time_to_expire,
                )
                worker_kind = "push_worker"
                extra = (
                    ("--hb", "--hb-period", "0.5") if mode == "push-hb" else ()
                )
            elif mode == "tpu-push":
                from tpu_faas.dispatch.tpu_push import TpuPushDispatcher

                disp = TpuPushDispatcher(
                    ip="127.0.0.1",
                    port=0,
                    store=make_store(store_url),
                    time_to_expire=time_to_expire,
                )
                worker_kind = "push_worker"
                extra = ("--hb", "--hb-period", "0.5")
            else:
                raise ValueError(f"unknown mode {mode!r}")
            disp_thread = threading.Thread(target=disp.start, daemon=True)
            disp_thread.start()
            url = f"tcp://127.0.0.1:{disp.port}"
            workers = [
                _spawn_worker(worker_kind, n_procs, url, *extra)
                for _ in range(n_workers)
            ]
            time.sleep(1.0)  # let workers register
        yield FaaSClient(gw.url), admin_store
    finally:
        for w in workers:
            if w.poll() is None:
                w.kill()
                w.wait()
        if disp is not None:
            disp.stop()
        if disp_thread is not None:
            disp_thread.join(timeout=10)
        gw.stop()
        admin_store.close()
        if native_handle is not None:
            native_handle.stop()
        if store_thread_handle is not None:
            store_thread_handle.stop()


def _measure_once(
    client: FaaSClient,
    fn,
    params: list,
    expected: list,
    timeout: float,
) -> tuple[float, float, float, float]:
    """One simulation: returns (throughput, avg_latency, t_register,
    correctness_rate). ``expected`` is the precomputed local oracle (hoisted
    out of the sim loop — recomputing a sleep workload would serially sleep
    on the client); the function is (re-)registered here because the store
    is flushed between sims."""
    n_tasks = len(params)
    fid = client.register(fn)

    t0 = time.perf_counter()
    submit_at: dict[str, float] = {}
    handles = []
    for a, k in params:
        h = client.submit(fid, *a, **k)
        submit_at[h.task_id] = time.perf_counter()
        handles.append(h)
    t_register = time.perf_counter() - t0

    # rotating poll; throughput is measured over the POLL window only
    # (reference client_performance.py:119-139)
    from tpu_faas.core.serialize import deserialize

    todo = deque(enumerate(handles))
    done_at: dict[str, float] = {}
    ok = 0
    t_poll = time.perf_counter()
    deadline = t_poll + timeout
    while todo and time.perf_counter() < deadline:
        i, h = todo.popleft()
        status, payload = h.client.raw_result(h.task_id)
        if status in ("COMPLETED", "FAILED"):
            done_at[h.task_id] = time.perf_counter()
            if status == "COMPLETED" and deserialize(payload) == expected[i]:
                ok += 1
        else:
            todo.append((i, h))
    if todo:
        raise TimeoutError(f"{len(todo)} tasks unfinished after {timeout}s")
    window = time.perf_counter() - t_poll
    latencies = [done_at[tid] - submit_at[tid] for tid in done_at]
    return (
        n_tasks / window,
        float(np.mean(latencies)),
        t_register,
        ok / n_tasks,
    )


def measure_service(
    mode: str,
    n_workers: int = 8,
    n_procs: int = 4,
    tasks_per_worker: int = 10,
    workload: str = "arithmetic",
    size: int = 10_000,
    n_sims: int = 3,
    timeout: float = 300.0,
    store_backend: str = "auto",
) -> BenchResult:
    """Reference client_performance.py:98-148 equivalent: medians over sims."""
    n_tasks = tasks_per_worker * n_workers
    fn, params = make_workload(workload, n_tasks, size, seed=1)
    expected = [fn(*a, **k) for a, k in params]  # local oracle, once
    tps, lat, reg, corr = [], [], [], []
    with full_stack(mode, n_workers, n_procs, store_backend) as (client, store):
        for sim in range(n_sims):
            t, l, r, c = _measure_once(client, fn, params, expected, timeout)
            tps.append(t)
            lat.append(l)
            reg.append(r)
            corr.append(c)
            log.info(
                "sim %d/%d: %.1f tasks/s, %.4fs avg latency", sim + 1, n_sims, t, l
            )
            store.flush()  # reference flushes between sims (:253)
    return BenchResult(
        mode=mode,
        n_workers=n_workers,
        n_procs=n_procs,
        n_tasks=n_tasks,
        throughput_tps=float(np.median(tps)),
        avg_latency_s=float(np.median(lat)),
        time_to_register_s=float(np.median(reg)),
        correctness_rate=float(np.mean(corr)),
        sims=n_sims,
    )
