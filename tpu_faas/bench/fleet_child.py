"""One dispatcher of the fleet-throughput bench (config 14), as a real OS
process.

The federated control plane's scaling claim is about PROCESSES — N
dispatcher serve loops on N cores against N store shards — so the bench
cannot run its dispatchers as threads of the parent (the GIL would
serialize exactly the work being measured). This child builds a tpu-push
dispatcher over the (possibly sharded) store URL, registers config-9-style
mirror workers directly on its ROUTER (dispatch sends to never-connected
peers are dropped by ZMQ, isolating HOST dispatch cost: announce drain,
pipelined record fetch, device step, send loop, coalesced RUNNING flush),
compiles the device step outside the measured window, serves /stats +
/metrics, and runs the ordinary serve loop until SIGTERM.

The parent polls each child's ``/stats`` for ``workers_registered``
(readiness) and ``n_dispatched`` (progress), and scrapes ``/metrics``
against the strict exposition grammar mid-run.

Run: ``python -m tpu_faas.bench.fleet_child --store "resp://h0:p0;h1:p1"
--shard 0 --workers 1024 --procs 8 --stats-port 9100`` (the shard COUNT
comes from the sharded store URL; ``--shard`` picks the owned slice).
"""

from __future__ import annotations

import argparse
import os
import signal
import sys


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(
        description="fleet-throughput bench dispatcher child"
    )
    ap.add_argument("--store", required=True)
    ap.add_argument(
        "--shard", type=int, default=-1,
        help="shard index this dispatcher OWNS (-1 = own everything: the "
        "single-stack control leg, or an unsharded store url)",
    )
    ap.add_argument("--workers", type=int, required=True,
                    help="mirror workers to register")
    ap.add_argument("--procs", type=int, default=8,
                    help="process slots per mirror worker")
    ap.add_argument("--stats-port", type=int, required=True)
    ap.add_argument("--max-pending", type=int, default=8192)
    ap.add_argument("--max-inflight", type=int, default=65536)
    ap.add_argument(
        "--tte", type=float, default=3600.0,
        help="mirror workers never heartbeat: keep them alive for the "
        "whole run",
    )
    ns = ap.parse_args(argv)

    # persistent XLA compile cache + platform pin, same as the dispatcher
    # CLI: a cold-compiling child would bill tens of seconds of XLA time
    # to the readiness wait of every leg
    cache_dir = os.environ.get(
        "TPU_FAAS_COMPILE_CACHE",
        os.path.join(os.path.expanduser("~"), ".cache", "tpu_faas_xla"),
    )
    if cache_dir:
        import jax

        jax.config.update("jax_compilation_cache_dir", cache_dir)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)

    from tpu_faas.dispatch.tpu_push import TpuPushDispatcher
    from tpu_faas.store.launch import make_store
    from tpu_faas.worker import messages as m

    store = make_store(
        ns.store, owned_shards=[ns.shard] if ns.shard >= 0 else None
    )
    disp = TpuPushDispatcher(
        ip="127.0.0.1",
        port=0,
        store=store,
        max_workers=ns.workers,
        max_pending=ns.max_pending,
        max_inflight=ns.max_inflight,
        max_slots=ns.procs,
        time_to_expire=ns.tte,
        recover_queued=False,  # the parent feeds AFTER readiness: no
        # announce can be lost, and rescans must not perturb the window
    )
    prefix = f"mirror-{max(ns.shard, 0)}"
    for i in range(ns.workers):
        disp._handle(
            f"{prefix}-w{i}".encode(), m.REGISTER,
            {"num_processes": ns.procs},
        )
    disp.tick()  # compile the device step before the parent starts timing
    disp.serve_stats(ns.stats_port)

    def _stop(signum, frame):  # noqa: ARG001 (signal handler shape)
        disp.stop()

    signal.signal(signal.SIGTERM, _stop)
    signal.signal(signal.SIGINT, _stop)
    print("READY", flush=True)
    try:
        disp.start()
    finally:
        disp.socket.close(linger=0)
        disp.close()


if __name__ == "__main__":
    main()
