"""Benchmark CLI.

    python -m tpu_faas.bench -m push -w 8 -np 4 -t 10 -ns 3   # ad-hoc run
    python -m tpu_faas.bench --config 1                        # BASELINE config
    python -m tpu_faas.bench --config all

Prints one JSON line per measurement (reference client_performance.py's role;
units are honest seconds/ms — its ms-labeled-as-ns bug is not reproduced).
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv: list[str] | None = None) -> None:
    ap = argparse.ArgumentParser(description="tpu-faas benchmarks")
    ap.add_argument(
        "--config",
        help="benchmark config: 1-5 (BASELINE), 6 (batch register), "
        "7 (bid kernel), 8 (estimation), 9 (host dispatch throughput), "
        "10 (overload admission), 11 (payload plane), "
        "12 (latency closed-loop), 13 (task graphs), "
        "14 (fleet throughput: sharded control plane), "
        "15 (tick-latency trajectory: fused vs XLA tick), "
        "16 (tenant fairness: isolation + weighted shares), "
        "17 (batched data plane: TASK_BATCH/bundles vs per-task wire), "
        "18 (tail hedging: straggler speculation vs an injected sick "
        "worker), 19 (composed tail-SLO: every opt-in plane at once), "
        "20 (chaos scenario: seeded fault plane + health-scored "
        "quarantine), 21 (graph data locality: result blobs vs "
        "store-mediated deps), or 'all'",
    )
    ap.add_argument(
        "-m", "--mode", default="push",
        choices=["local", "pull", "push", "push-hb", "push-plb", "tpu-push"],
    )
    ap.add_argument("-w", "--workers", type=int, default=8)
    ap.add_argument("-np", "--procs", type=int, default=4)
    ap.add_argument("-t", "--tasks-per-worker", type=int, default=10)
    ap.add_argument("-ns", "--sims", type=int, default=3)
    ap.add_argument("--workload", default="arithmetic")
    ap.add_argument("--size", type=int, default=10_000)
    ap.add_argument("--store", default="auto", choices=["auto", "native", "python"])
    ns = ap.parse_args(argv)

    if ns.config:
        from tpu_faas.bench.configs import CONFIGS

        keys = list(CONFIGS) if ns.config == "all" else [ns.config]
        for key in keys:
            if key not in CONFIGS:
                sys.exit(f"unknown config {key!r}; choose from {list(CONFIGS)}")
            print(json.dumps(CONFIGS[key]()), flush=True)
        return

    from tpu_faas.bench.harness import measure_service

    res = measure_service(
        mode=ns.mode,
        n_workers=ns.workers,
        n_procs=ns.procs,
        tasks_per_worker=ns.tasks_per_worker,
        workload=ns.workload,
        size=ns.size,
        n_sims=ns.sims,
        store_backend=ns.store,
    )
    print(json.dumps(res.to_dict()), flush=True)


if __name__ == "__main__":
    main()
