"""Per-class tail-SLO attribution vocabulary (the composed-SLO plane).

Two ideas live here, both opt-in so the default exposition stays
byte-identical to the reference era:

**SLO classes.** Every task belongs to exactly one of a BOUNDED class
vocabulary (``interactive`` / ``batch`` / ``default``) — declared
explicitly at submit (``X-SLO-Class`` header, SDK ``slo_class=`` kwarg)
or derived from the priority sign (positive = interactive, negative =
batch). The vocabulary is closed for the same reason TenantTable's label
set is: classes become a Prometheus label on the latency histograms, and
an open vocabulary is an unbounded-cardinality series leak. With
``TPU_FAAS_OBS_CLASS`` unset the class label never appears anywhere —
histogram label sets, ``/slo`` output and the attribution counter family
are all byte-identical to the pre-attribution surface.

**Plane attribution.** Each opt-in plane (express result lane,
micro-batching, speculation, tenancy, columnar intake, admission)
already makes a per-task decision somewhere; this module gives those
sites ONE bounded counter family to fold the decision into:
``tpu_faas_task_attrib_total{plane, outcome, class}``. "Which plane
bought which percentile" then becomes a scrape — join the counter deltas
against the per-class histograms — instead of log archaeology.

**High-resolution buckets.** The default 18-bucket ladder cannot resolve
p999 (the top decades are whole-second wide). ``TPU_FAAS_OBS_HIRES_BUCKETS``
swaps the latency histograms onto a ~30-bucket log-spaced ladder
(1 ms → 60 s, ~1.45x ratio) — enough resolution that a p999 read off the
cumulative counts is meaningful. Off by default: the ladder changes
every ``le=`` line in the exposition, so it must be asked for.
"""

from __future__ import annotations

import math
import os

__all__ = [
    "SLO_CLASSES",
    "DEFAULT_CLASS",
    "CLASS_ENV",
    "HIRES_ENV",
    "ATTRIB_VOCAB",
    "class_label_enabled",
    "hires_enabled",
    "hires_buckets",
    "latency_buckets",
    "normalize_class",
    "class_of",
    "class_of_fields",
    "AttributionBook",
]

#: the CLOSED class vocabulary — a label value outside this set never
#: reaches a metric (unknown declarations degrade to ``default``)
SLO_CLASSES = ("interactive", "batch", "default")
DEFAULT_CLASS = "default"

#: env knob: truthy value turns the ``class`` label on (histograms, /slo,
#: attribution counters). Read at component construction, not per call.
CLASS_ENV = "TPU_FAAS_OBS_CLASS"
#: env knob: truthy value swaps latency histograms onto the hi-res ladder
HIRES_ENV = "TPU_FAAS_OBS_HIRES_BUCKETS"

_FALSY = ("", "0", "false", "no", "off")

#: the CLOSED (plane, outcome) vocabulary for
#: ``tpu_faas_task_attrib_total`` — every site that wants a new outcome
#: adds it HERE first (the conformance test walks this table), keeping
#: the family's cardinality |vocab| x |SLO_CLASSES| by construction.
ATTRIB_VOCAB: dict[str, tuple[str, ...]] = {
    # gateway result delivery: long-poll answered from the announce's
    # inline payload vs a store re-read
    "express": ("inline", "store"),
    # wire form the task reached its worker in
    "batch": ("bundle_rode", "solo"),
    # speculation plane: this task's first result came from a hedge
    # replica (won), or a resolved hedge's loser reported late (wasted)
    "speculation": ("hedged_won", "hedged_wasted"),
    # tenancy plane at dispatch: picked while its tenant was the
    # most-deficit row (boosted) vs dispatched with its tenant at/over
    # its inflight cap at tick start (held earlier that tick)
    "tenancy": ("fairness_boosted", "cap_held"),
    # columnar intake lane the record decoded into
    "columnar": ("arena", "fallback"),
    # tasks that never ran: gateway admission/brownout rejections and
    # dispatcher queue-deadline sheds
    "admission": ("shed",),
    "dispatch": ("shed_expired",),
}


def _truthy(env: str) -> bool:
    return os.environ.get(env, "").strip().lower() not in _FALSY


def class_label_enabled() -> bool:
    """Is the ``class`` label (and the attribution counter family) on?"""
    return _truthy(CLASS_ENV)


def hires_enabled() -> bool:
    return _truthy(HIRES_ENV)


def hires_buckets() -> tuple[float, ...]:
    """~30 log-spaced bucket uppers, 1 ms → 60 s (strictly increasing).

    Generated, not hand-typed: 30 points evenly spaced in log10 between
    1e-3 and 60, rounded to 4 significant digits (rounding cannot
    produce a duplicate at this spacing — ratio ~1.46 per step).
    """
    lo, hi, n = math.log10(0.001), math.log10(60.0), 30
    out = []
    for i in range(n):
        v = 10.0 ** (lo + (hi - lo) * i / (n - 1))
        # 4 significant digits keeps the exposition readable
        out.append(float(f"{v:.4g}"))
    return tuple(out)


def latency_buckets(default: tuple[float, ...]) -> tuple[float, ...]:
    """The ladder a latency histogram should use under the current env:
    the caller's default, unless hi-res buckets were asked for."""
    return hires_buckets() if hires_enabled() else default


def normalize_class(raw) -> str | None:
    """Validate a declared class against the closed vocabulary.

    Returns the canonical class for a valid declaration, None for
    anything else (missing, wrong type, unknown word) — callers decide
    whether None means "reject the request" (gateway header validation)
    or "fall through to derivation" (record-field reads).
    """
    if isinstance(raw, bytes):
        try:
            raw = raw.decode("utf-8")
        except UnicodeDecodeError:
            return None
    if not isinstance(raw, str):
        return None
    cls = raw.strip().lower()
    return cls if cls in SLO_CLASSES else None


def class_of(slo_class, priority) -> str:
    """Effective class: explicit valid declaration wins, else the
    priority sign (positive = interactive, negative = batch), else
    ``default``. Total — never raises, never returns an off-vocabulary
    value (garbage degrades, matching the store-field discipline)."""
    cls = normalize_class(slo_class)
    if cls is not None:
        return cls
    try:
        prio = int(priority) if priority is not None else 0
    except (TypeError, ValueError):
        prio = 0
    if prio > 0:
        return "interactive"
    if prio < 0:
        return "batch"
    return DEFAULT_CLASS


def class_of_fields(fields: dict) -> str:
    """Effective class of a store record / fields dict (gateway result
    path, dispatcher intake). Imports the field names lazily to keep
    obs/ free of a core dependency cycle."""
    from tpu_faas.core.task import FIELD_PRIORITY, FIELD_SLO_CLASS

    return class_of(fields.get(FIELD_SLO_CLASS), fields.get(FIELD_PRIORITY))


class AttributionBook:
    """The per-process ``tpu_faas_task_attrib_total`` family, or a no-op.

    Constructed by every metrics-owning component (gateway context,
    dispatcher); when the class label is off the family is NEVER
    registered and every ``note()`` is a cheap early return — the
    exposition stays byte-identical. When on, the full
    plane x outcome x class child set is pre-created so scrapes carry
    explicit zeros (the bounded-vocabulary discipline, and what lets the
    bench read "plane live" as a plain nonzero check).
    """

    def __init__(self, registry, enabled: bool | None = None) -> None:
        self.enabled = (
            class_label_enabled() if enabled is None else bool(enabled)
        )
        self._m = None
        if self.enabled:
            self._m = registry.counter(
                "tpu_faas_task_attrib_total",
                "Per-task plane-attribution bits, folded in where each "
                "plane decides (express delivery source, wire bundling, "
                "hedge wins/waste, tenancy boosts/holds, columnar lane, "
                "sheds) — join deltas against the class-labeled latency "
                "histograms to see which plane bought which percentile",
                ("plane", "outcome", "class"),
            )
            for plane, outcomes in ATTRIB_VOCAB.items():
                for outcome in outcomes:
                    for cls in SLO_CLASSES:
                        self._m.labels(plane, outcome, cls)

    def note(self, plane: str, outcome: str, cls: str, n: int = 1) -> None:
        """Count one attribution bit. Off-vocabulary planes/outcomes are
        a programming error and raise (the vocabulary is closed on
        purpose); off-vocabulary classes degrade to ``default``."""
        if self._m is None:
            return
        if outcome not in ATTRIB_VOCAB.get(plane, ()):
            raise ValueError(
                f"attribution outcome {plane}/{outcome} not in "
                f"ATTRIB_VOCAB — extend the closed vocabulary first"
            )
        if cls not in SLO_CLASSES:
            cls = DEFAULT_CLASS
        self._m.labels(plane, outcome, cls).inc(n)
