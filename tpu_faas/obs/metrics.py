"""Metrics registry + Prometheus text exposition.

Design constraints, in order:

- **Hot-path cheap.** ``Counter.inc`` / ``Histogram.observe`` sit inside
  the dispatcher's per-message drain and the store client's round-trip
  path. Each child owns one uncontended ``threading.Lock`` around a couple
  of float ops; histograms are fixed-bucket (one ``bisect`` + two adds),
  never per-sample lists — a saturated dispatcher records millions of
  samples without growing memory.
- **One name, one type.** A registry rejects re-registration of a name
  with a different type, help text, or label set: the gateway and the
  dispatcher cannot drift into exposing the same series two ways.
- **Standard exposition.** :func:`render` emits Prometheus text format
  (version 0.0.4): ``# HELP``/``# TYPE`` once per family, escaped label
  values, cumulative histogram buckets ending in ``+Inf`` with matching
  ``_sum``/``_count``. The strict parser in :mod:`tpu_faas.obs.expofmt`
  (used by the conformance tests and the CI bench scrape) holds this
  renderer to the grammar.

There is a process-global :data:`REGISTRY` for process-scoped series (the
store client's round-trip counter registers there), but components that
tests instantiate repeatedly — dispatchers, gateway apps — own a private
``MetricsRegistry`` and render it concatenated with the global one, so one
test's counters never bleed into the next scrape.
"""

from __future__ import annotations

import bisect
import math
import re
import threading
from typing import Iterable, Mapping, Sequence

#: MIME type for exposition replies.
CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"

#: Default latency buckets (seconds): sub-millisecond device ticks through
#: multi-second executions. Mirrors the prometheus client defaults with a
#: finer low end for the tick path.
LATENCY_BUCKETS = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0,
)

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")


def _escape_help(text: str) -> str:
    return text.replace("\\", r"\\").replace("\n", r"\n")


def _escape_label_value(text: str) -> str:
    return (
        text.replace("\\", r"\\").replace('"', r"\"").replace("\n", r"\n")
    )


def format_value(v: float) -> str:
    """Prometheus sample-value spelling: integral floats render without a
    fractional part (``17`` not ``17.0``), infinities as ``+Inf``/``-Inf``."""
    if math.isinf(v):
        return "+Inf" if v > 0 else "-Inf"
    if math.isnan(v):
        return "NaN"
    if float(v).is_integer() and abs(v) < 2**53:
        return str(int(v))
    return repr(float(v))


def format_le(upper: float) -> str:
    """Bucket-boundary spelling for the ``le`` label (``+Inf``, ``0.005``)."""
    if math.isinf(upper):
        return "+Inf"
    if float(upper).is_integer():
        return f"{upper:.1f}"
    return repr(float(upper))


class _Child:
    """One (metric, label-values) time series. Value ops take the child's
    own lock — uncontended in the common single-writer case."""

    __slots__ = ("_lock", "_value")

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._value = 0.0

    @property
    def value(self) -> float:
        return self._value

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def set(self, value: float) -> None:
        with self._lock:
            self._value = float(value)


class _HistogramChild:
    """Fixed-bucket histogram series: per-bucket counts + running sum.

    No per-sample storage — ``observe`` is a bisect into the (sorted)
    upper-bounds tuple plus two adds under the child lock. Bucket counts
    are stored NON-cumulative and accumulated at render time, so the
    hot-path write touches exactly one slot."""

    __slots__ = ("_lock", "_uppers", "_counts", "_sum")

    def __init__(self, uppers: tuple[float, ...]) -> None:
        self._lock = threading.Lock()
        self._uppers = uppers  # excludes +Inf; the overflow slot is last
        self._counts = [0] * (len(uppers) + 1)
        self._sum = 0.0

    def observe(self, value: float) -> None:
        idx = bisect.bisect_left(self._uppers, value)
        with self._lock:
            self._counts[idx] += 1
            self._sum += value

    def snapshot(self) -> tuple[list[int], float]:
        with self._lock:
            return list(self._counts), self._sum

    @property
    def count(self) -> int:
        return sum(self._counts)

    @property
    def sum(self) -> float:
        return self._sum


class _Metric:
    """A metric family: name, type, help, label names, and its children."""

    mtype = "untyped"

    def __init__(
        self, name: str, help: str, labelnames: Sequence[str] = ()
    ) -> None:
        if not _NAME_RE.match(name):
            raise ValueError(f"invalid metric name {name!r}")
        for ln in labelnames:
            if not _LABEL_RE.match(ln) or ln.startswith("__"):
                raise ValueError(f"invalid label name {ln!r} on {name}")
            if ln == "le" and self.mtype == "histogram":
                raise ValueError("'le' is reserved on histograms")
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self._lock = threading.Lock()
        self._children: dict[tuple[str, ...], object] = {}
        if not self.labelnames:
            # unlabeled metrics get their single child eagerly, so the
            # family renders (at zero) from the moment it is registered —
            # scrapes see the full catalog before any traffic
            self._children[()] = self._make_child()

    def _make_child(self):
        return _Child()

    def labels(self, *values: str, **kv: str) -> object:
        """The child for one label-value combination (created on first
        use). Positional values follow ``labelnames`` order."""
        if kv:
            if values:
                raise ValueError("pass labels positionally or by name, not both")
            try:
                values = tuple(str(kv[ln]) for ln in self.labelnames)
            except KeyError as exc:
                raise ValueError(f"missing label {exc} for {self.name}") from None
            if len(kv) != len(self.labelnames):
                raise ValueError(f"unexpected labels for {self.name}: {kv}")
        else:
            values = tuple(str(v) for v in values)
        if len(values) != len(self.labelnames):
            raise ValueError(
                f"{self.name} takes labels {self.labelnames}, got {values}"
            )
        child = self._children.get(values)
        if child is None:
            with self._lock:
                child = self._children.setdefault(values, self._make_child())
        return child

    def _default(self):
        if self.labelnames:
            raise ValueError(f"{self.name} is labeled; use .labels(...)")
        return self._children[()]

    def child_items(self) -> list[tuple[tuple[str, ...], object]]:
        with self._lock:
            return sorted(self._children.items())

    def _series(self, name: str, values: tuple[str, ...], extra: str = "") -> str:
        pairs = [
            f'{ln}="{_escape_label_value(lv)}"'
            for ln, lv in zip(self.labelnames, values)
        ]
        if extra:
            pairs.append(extra)
        return f"{name}{{{','.join(pairs)}}}" if pairs else name


class Counter(_Metric):
    mtype = "counter"

    def inc(self, amount: float = 1.0) -> None:
        if amount < 0:
            raise ValueError("counters only go up")
        self._default().inc(amount)

    @property
    def value(self) -> float:
        return self._default().value

    def render_into(self, out: list[str]) -> None:
        for values, child in self.child_items():
            out.append(
                f"{self._series(self.name, values)} {format_value(child.value)}"
            )


class Gauge(_Metric):
    mtype = "gauge"

    def set(self, value: float) -> None:
        self._default().set(value)

    def inc(self, amount: float = 1.0) -> None:
        self._default().inc(amount)

    def dec(self, amount: float = 1.0) -> None:
        self._default().inc(-amount)

    @property
    def value(self) -> float:
        return self._default().value

    render_into = Counter.render_into


class Histogram(_Metric):
    mtype = "histogram"

    def __init__(
        self,
        name: str,
        help: str,
        labelnames: Sequence[str] = (),
        buckets: Sequence[float] = LATENCY_BUCKETS,
    ) -> None:
        uppers = tuple(sorted(float(b) for b in buckets))
        if not uppers:
            raise ValueError("histogram needs at least one bucket")
        if any(
            a >= b for a, b in zip(uppers, uppers[1:])
        ) or math.isinf(uppers[-1]):
            raise ValueError("buckets must be strictly increasing and finite")
        self._uppers = uppers
        super().__init__(name, help, labelnames)

    def _make_child(self):
        return _HistogramChild(self._uppers)

    def observe(self, value: float) -> None:
        self._default().observe(value)

    def sum_counts(
        self, match: Sequence[str | None]
    ) -> tuple[tuple[float, ...], list[int]] | None:
        """(bucket uppers, summed per-bucket counts — overflow slot last)
        across every child whose label values equal ``match`` positionally
        (None = wildcard). None when nothing matches. The SLO trackers'
        shared data source: both the gateway e2e phases and the dispatcher
        stage histogram filter one label exactly and one to a terminal
        outcome."""
        total: list[int] | None = None
        for values, child in self.child_items():
            if any(
                want is not None and have != want
                for have, want in zip(values, match)
            ):
                continue
            counts, _ = child.snapshot()
            if total is None:
                total = counts
            else:
                total = [a + b for a, b in zip(total, counts)]
        if total is None:
            return None
        return self._uppers, total

    def render_into(self, out: list[str]) -> None:
        for values, child in self.child_items():
            counts, total = child.snapshot()
            acc = 0
            for upper, n in zip(self._uppers, counts):
                acc += n
                le = f'le="{format_le(upper)}"'
                out.append(
                    f"{self._series(self.name + '_bucket', values, le)} {acc}"
                )
            acc += counts[-1]
            inf_label = 'le="+Inf"'
            out.append(
                f"{self._series(self.name + '_bucket', values, inf_label)} {acc}"
            )
            out.append(
                f"{self._series(self.name + '_sum', values)} {format_value(total)}"
            )
            out.append(f"{self._series(self.name + '_count', values)} {acc}")


class MetricsRegistry:
    """Named metric families + render-time collector callbacks."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}
        self._collectors: list = []

    def _get_or_create(self, cls, name, help, labelnames, **kw) -> _Metric:
        with self._lock:
            existing = self._metrics.get(name)
            if existing is not None:
                if (
                    type(existing) is not cls
                    or existing.labelnames != tuple(labelnames)
                ):
                    raise ValueError(
                        f"metric {name!r} already registered with a "
                        f"different type or label set"
                    )
                return existing
            metric = cls(name, help, labelnames, **kw)
            self._metrics[name] = metric
            return metric

    def counter(self, name: str, help: str, labelnames=()) -> Counter:
        return self._get_or_create(Counter, name, help, labelnames)

    def gauge(self, name: str, help: str, labelnames=()) -> Gauge:
        return self._get_or_create(Gauge, name, help, labelnames)

    def histogram(
        self, name: str, help: str, labelnames=(), buckets=LATENCY_BUCKETS
    ) -> Histogram:
        return self._get_or_create(
            Histogram, name, help, labelnames, buckets=buckets
        )

    def register_collector(self, fn) -> None:
        """``fn()`` runs at the top of every render — the place to refresh
        gauges whose truth lives elsewhere (queue depths, fleet sizes)."""
        with self._lock:
            if fn not in self._collectors:
                self._collectors.append(fn)

    def unregister_collector(self, fn) -> None:
        with self._lock:
            if fn in self._collectors:
                self._collectors.remove(fn)

    def collect(self) -> list[_Metric]:
        with self._lock:
            collectors = list(self._collectors)
            metrics = list(self._metrics.values())
        for fn in collectors:
            fn()
        return sorted(metrics, key=lambda m: m.name)

    def render(self) -> str:
        return render([self])


def render(registries: Iterable[MetricsRegistry]) -> str:
    """Concatenated exposition over several registries (component-private +
    process-global). A metric name appearing in more than one registry is a
    hard error: duplicate families are invalid exposition, and silently
    merging them would hide a naming collision."""
    out: list[str] = []
    seen: dict[str, str] = {}
    for registry in registries:
        for metric in registry.collect():
            if metric.name in seen:
                raise ValueError(
                    f"metric {metric.name!r} registered in more than one "
                    "rendered registry"
                )
            seen[metric.name] = metric.mtype
            out.append(f"# HELP {metric.name} {_escape_help(metric.help)}")
            out.append(f"# TYPE {metric.name} {metric.mtype}")
            metric.render_into(out)
    return "\n".join(out) + "\n"


#: Process-global registry for series without a component owner (the store
#: client's round-trip counter, worker-pool counters). Component classes
#: that tests instantiate repeatedly keep PRIVATE registries instead.
REGISTRY = MetricsRegistry()
