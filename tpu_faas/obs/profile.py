"""Device-tick profiling hooks: recompile detection + jax.profiler capture.

The scheduler tick is jit-compiled against STATIC padded shapes
(``max_pending``/``max_workers``/``max_slots``/placement), so in steady
state every tick replays one cached executable — a recompile mid-serve
means a shape or trace-structure change leaked into the hot loop (the
exact regression class sched/state.py's packed calling convention exists
to prevent). :class:`TickProfiler` detects that from the host side: each
tick reports its shape signature, a signature never seen before counts as
a compile (``tpu_faas_jit_recompiles_total``), and the current padded dims
are exported as ``tpu_faas_tick_shape{dim=...}`` gauges. Where the running
JAX exposes per-function cache sizes (``jit(...)._cache_size()``), the
observed signature count is cross-checkable against the real cache.

Opt-in deep capture: set ``TPU_FAAS_JAX_PROFILE_DIR=/some/dir`` and the
first ``TPU_FAAS_JAX_PROFILE_TICKS`` device ticks (default 20) run inside
one ``jax.profiler`` trace, viewable in TensorBoard/Perfetto — the part of
this layer that transfers directly to a training or inference stack.
"""

from __future__ import annotations

import os
from contextlib import contextmanager

PROFILE_DIR_ENV = "TPU_FAAS_JAX_PROFILE_DIR"
PROFILE_TICKS_ENV = "TPU_FAAS_JAX_PROFILE_TICKS"


class TickProfiler:
    """Host-side tick instrumentation; one per dispatcher, registered into
    that dispatcher's private metrics registry."""

    def __init__(self, registry, log=None) -> None:
        self._log = log
        self._recompiles = registry.counter(
            "tpu_faas_jit_recompiles_total",
            "Device-tick shape signatures first seen after warmup — each "
            "is one jit cache miss (steady state: stays flat)",
        )
        self._shape = registry.gauge(
            "tpu_faas_tick_shape",
            "Padded device-tick dimensions (tasks x workers x slots)",
            ("dim",),
        )
        self._ticks = registry.counter(
            "tpu_faas_device_ticks_total", "Device scheduler ticks run"
        )
        self._dispatches_last = registry.gauge(
            "tpu_faas_tick_device_dispatches_last",
            "Compiled-callable dispatches issued by the last resident tick "
            "(fused steady state: exactly 1; each overflow flush adds 1)",
        )
        self._dispatches = registry.counter(
            "tpu_faas_tick_device_dispatches_total",
            "Compiled-callable dispatches issued by resident ticks",
        )
        self._seen: set[tuple] = set()
        self._trace_dir = os.environ.get(PROFILE_DIR_ENV) or None
        try:
            self._trace_left = (
                int(os.environ.get(PROFILE_TICKS_ENV, "20"))
                if self._trace_dir
                else 0
            )
        except ValueError:
            self._trace_left = 0
        self._tracing = False

    @property
    def n_signatures(self) -> int:
        return len(self._seen)

    def note_device_dispatches(self, n: int) -> None:
        """Record one resident tick's compiled-callable dispatch count
        (``ResidentScheduler.device_dispatches_last_tick``) — the
        observable form of the one-dispatch-per-tick contract."""
        self._dispatches_last.set(n)
        if n > 0:
            self._dispatches.inc(n)

    def observe_shape(
        self, *, tasks: int, workers: int, slots: int, signature: tuple
    ) -> bool:
        """Report one tick's padded dims + trace signature BEFORE the
        device call. Returns True when this signature is new (a compile).
        The signature must include everything that changes the jitted
        trace: padded dims, placement, and optional-lane presence (the
        priority vector being None vs an array retraces)."""
        self._shape.labels(dim="tasks").set(tasks)
        self._shape.labels(dim="workers").set(workers)
        self._shape.labels(dim="slots").set(slots)
        self._ticks.inc()
        if signature in self._seen:
            return False
        self._seen.add(signature)
        self._recompiles.inc()
        if self._log is not None and len(self._seen) > 1:
            # the first compile is warmup; later ones are the news
            self._log.info(
                "device tick recompiled (signature %r, %d total)",
                signature,
                len(self._seen),
            )
        return True

    @contextmanager
    def tick_capture(self):
        """Wrap one device tick; while the env-gated capture budget lasts,
        the tick runs inside a ``jax.profiler`` trace. No-op (and
        zero-cost) when ``TPU_FAAS_JAX_PROFILE_DIR`` is unset."""
        if self._trace_left <= 0:
            if self._tracing:
                self._stop_trace()
            yield
            return
        if not self._tracing:
            self._start_trace()
        self._trace_left -= 1
        try:
            yield
        finally:
            if self._trace_left <= 0 and self._tracing:
                self._stop_trace()

    def _start_trace(self) -> None:
        try:
            import jax

            jax.profiler.start_trace(self._trace_dir)
            self._tracing = True
            if self._log is not None:
                self._log.info(
                    "jax.profiler capture started -> %s", self._trace_dir
                )
        except Exception as exc:  # capture is best-effort observability
            self._trace_left = 0
            if self._log is not None:
                self._log.warning("jax.profiler capture unavailable: %s", exc)

    def _stop_trace(self) -> None:
        self._tracing = False
        try:
            import jax

            jax.profiler.stop_trace()
            if self._log is not None:
                self._log.info(
                    "jax.profiler capture written to %s", self._trace_dir
                )
        except Exception as exc:
            if self._log is not None:
                self._log.warning("jax.profiler stop_trace failed: %s", exc)

    def close(self) -> None:
        if self._tracing:
            self._stop_trace()
