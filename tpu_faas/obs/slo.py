"""Latency SLOs: configurable objectives + multi-window burn rates.

An :class:`Objective` says "at least ``target`` of events must land at or
under ``threshold_s`` seconds" — e.g. *p99 submit→result ≤ 250 ms* is
``Objective("submit_to_result", "total", 0.25, 0.99)``. The tracker
computes, per objective and per window (default 5 m and 1 h):

- the **good ratio** — the fraction of events inside the threshold over
  the window;
- the **burn rate** — ``(1 - good_ratio) / (1 - target)``: 1.0 burns the
  error budget exactly at the sustainable pace, 14.4 over 5 m is the
  classic page-now threshold (exhausts a 30-day budget in ~2 days).

The data source is the EXISTING fixed-bucket stage histograms
(``tpu_faas_task_stage_seconds`` on dispatchers, the gateway's e2e
histogram) — no per-event storage is added. Windowing works on a bounded
ring of cumulative-count snapshots taken at update time (scrapes and
``/slo`` hits both update), so sporadic scrapes degrade to a partial
window (reported as ``window_covered_s``) instead of lying.

Good events are counted at the largest bucket boundary ≤ the threshold —
a threshold between boundaries UNDERCOUNTS good events (conservative:
burn rates err toward alarming). Pick thresholds on bucket boundaries
(``LATENCY_BUCKETS``) to make the count exact.

Objectives are configurable via the ``TPU_FAAS_SLO`` environment variable:
``name=stage:threshold_s:target`` entries, comma-separated — e.g.
``TPU_FAAS_SLO="fast=total:0.25:0.99,queue=queue_wait:0.1:0.95"``.
Exposed as ``tpu_faas_slo_*`` gauges and the ``/slo`` endpoints.

**Per-class objectives** (the composed-SLO plane, obs/attribution.py):
``name=stage@class:threshold_s:target`` restricts the objective to one
SLO class — e.g. ``inter_p999=total@interactive:0.25:0.999``. The class
must be in the closed vocabulary (startup error otherwise), and the data
source must expose class-restricted reads (``TPU_FAAS_OBS_CLASS`` on):
against a class-blind source the objective honestly reports
``source_present=0`` instead of silently judging the aggregate.
"""

from __future__ import annotations

import bisect
import math
import os
import threading
import time
from collections import deque
from dataclasses import dataclass

from tpu_faas.obs.attribution import SLO_CLASSES

#: env var carrying operator objectives (see module docstring)
SLO_ENV = "TPU_FAAS_SLO"

#: (label, seconds) burn-rate windows, shortest first
WINDOWS: tuple[tuple[str, float], ...] = (("5m", 300.0), ("1h", 3600.0))


@dataclass(frozen=True)
class Objective:
    name: str
    #: which latency distribution to judge — a stage of the task timeline
    #: on dispatchers ("total", "queue_wait", "execution", ...) or an e2e
    #: phase on the gateway ("submit_to_finish", "submit_to_observe")
    stage: str
    threshold_s: float
    #: required good fraction, e.g. 0.99 for a p99 objective
    target: float
    #: None judges the whole distribution; a class from the closed
    #: vocabulary (obs/attribution.py) judges that class's slice only —
    #: the source must support class-restricted reads
    cls: str | None = None

    def __post_init__(self) -> None:
        if not (0.0 < self.target < 1.0):
            raise ValueError(f"target must be in (0, 1): {self.target}")
        if not (self.threshold_s > 0 and math.isfinite(self.threshold_s)):
            raise ValueError(f"threshold must be positive: {self.threshold_s}")
        if self.cls is not None and self.cls not in SLO_CLASSES:
            raise ValueError(
                f"objective class {self.cls!r} not in {SLO_CLASSES}"
            )


def parse_objectives(spec: str) -> list[Objective]:
    """``name=stage[@class]:threshold_s:target`` entries, comma-separated.
    Raises ValueError with the offending entry — a typo'd objective must
    fail loudly at startup, not silently monitor nothing."""
    out: list[Objective] = []
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        try:
            name, rest = entry.split("=", 1)
            stage, threshold, target = rest.split(":")
            stage = stage.strip()
            cls: str | None = None
            if "@" in stage:
                stage, cls = stage.split("@", 1)
                stage, cls = stage.strip(), cls.strip()
            out.append(
                Objective(
                    name.strip(), stage, float(threshold), float(target), cls
                )
            )
        except ValueError as exc:
            raise ValueError(
                f"bad {SLO_ENV} entry {entry!r} "
                "(want name=stage[@class]:threshold_s:target)"
            ) from exc
    return out


def objectives_from_env(default: list[Objective]) -> list[Objective]:
    spec = os.environ.get(SLO_ENV, "").strip()
    if not spec:
        return list(default)
    return parse_objectives(spec)


#: dispatcher defaults: the ROADMAP item-2 bar (p99 submit→result ≤ 250 ms
#: for sub-ms functions) plus the queue-wait share of it
DEFAULT_DISPATCHER_OBJECTIVES = [
    Objective("submit_to_result", "total", 0.25, 0.99),
    Objective("queue_wait", "queue_wait", 0.1, 0.99),
]

#: gateway defaults: end-to-end as the CLIENT experiences it — the observe
#: phase includes the poll gap the dispatcher-side total cannot see
DEFAULT_GATEWAY_OBJECTIVES = [
    Objective("submit_to_finish", "submit_to_finish", 0.25, 0.99),
    Objective("submit_to_observe", "submit_to_observe", 0.5, 0.99),
]


@dataclass
class _Snap:
    t: float
    good: int
    total: int


class SLOTracker:
    """Multi-window burn rates over histogram snapshots.

    ``source(stage)`` returns ``(uppers, counts)`` — the finite bucket
    upper bounds and the per-bucket NON-cumulative counts including the
    overflow slot last (the shape ``_HistogramChild.snapshot`` yields) —
    or None while the stage has no series yet."""

    #: minimum seconds between ring snapshots (a scrape storm must not
    #: flush the window resolution)
    MIN_SNAP_PERIOD = 2.0
    #: ring depth: at the min period this covers > the longest window
    _RING_CAP = 2048

    def __init__(
        self,
        registry,
        objectives: list[Objective],
        source,
        clock=time.monotonic,
    ) -> None:
        self.objectives = list(objectives)
        self._source = source
        self._clock = clock
        self._lock = threading.Lock()
        self._rings: dict[str, deque[_Snap]] = {
            o.name: deque(maxlen=self._RING_CAP) for o in self.objectives
        }
        # zero baseline at construction: a process younger than one window
        # reports every event since startup (window_covered_s says how
        # much of the window that really is) instead of reporting nothing
        # until two spaced snapshots exist
        t0 = self._clock()
        for ring in self._rings.values():
            ring.append(_Snap(t0, 0, 0))
        self.m_burn = registry.gauge(
            "tpu_faas_slo_burn_rate",
            "Error-budget burn rate per objective and window: 1.0 burns "
            "the budget at exactly the sustainable pace, higher is worse "
            "(14.4 over 5m ~ page); 0 with no traffic in the window",
            ("objective", "window"),
        )
        self.m_good = registry.gauge(
            "tpu_faas_slo_good_ratio",
            "Fraction of events at or under the objective's latency "
            "threshold over the window (1.0 with no traffic)",
            ("objective", "window"),
        )
        self.m_target = registry.gauge(
            "tpu_faas_slo_target_ratio",
            "The objective's required good fraction (configuration echo, "
            "so alert rules can compare against the live target)",
            ("objective",),
        )
        self.m_threshold = registry.gauge(
            "tpu_faas_slo_threshold_seconds",
            "The objective's latency threshold (configuration echo)",
            ("objective",),
        )
        self.m_source = registry.gauge(
            "tpu_faas_slo_source_present",
            "1 once the objective's stage MATCHES a histogram series in "
            "THIS process (pre-created series count — presence means the "
            "stage name is in this process's vocabulary, not that "
            "traffic has flowed; window event counts say that). A "
            "fleet-wide TPU_FAAS_SLO names stages from both vocabularies "
            "(gateway e2e phases vs dispatcher timeline stages), so an "
            "objective foreign to a process stays 0 here by design — "
            "but a stage-name TYPO stays 0 everywhere. Its burn/good "
            "gauges keep their idle values, so alert on "
            "(source_present == 1) AND burn_rate",
            ("objective",),
        )
        #: objectives whose stage has matched a histogram series at least
        #: once (vocabulary presence, not traffic)
        self._seen: dict[str, bool] = {o.name: False for o in self.objectives}
        for o in self.objectives:
            self.m_target.labels(objective=o.name).set(o.target)
            self.m_threshold.labels(objective=o.name).set(o.threshold_s)
            self.m_source.labels(objective=o.name).set(0.0)
            for label, _ in WINDOWS:
                self.m_burn.labels(objective=o.name, window=label)
                self.m_good.labels(objective=o.name, window=label).set(1.0)
        registry.register_collector(self.collect)

    # -- snapshotting ------------------------------------------------------
    def _cumulative(self, o: Objective) -> tuple[int, int] | None:
        """(good, total) cumulative counts for one objective, or None when
        its stage has no data source yet."""
        if o.cls is None:
            snap = self._source(o.stage)
        else:
            try:
                snap = self._source(o.stage, cls=o.cls)
            except TypeError:
                # class-blind source (custom wiring, class label off):
                # a per-class objective must NOT silently judge the
                # aggregate distribution — report source-absent instead
                snap = None
        if snap is None:
            return None
        uppers, counts = snap
        total = sum(counts)
        # buckets with upper bound <= threshold are provably good; the
        # bucket straddling the threshold is counted BAD (conservative)
        idx = bisect.bisect_right(uppers, o.threshold_s)
        good = sum(counts[:idx])
        return good, total

    def update(self, now: float | None = None) -> None:
        if now is None:
            now = self._clock()
        with self._lock:
            for o in self.objectives:
                ring = self._rings[o.name]
                if ring and now - ring[-1].t < self.MIN_SNAP_PERIOD:
                    continue
                cum = self._cumulative(o)
                if cum is None:
                    continue
                if not self._seen[o.name]:
                    self._seen[o.name] = True
                    self.m_source.labels(objective=o.name).set(1.0)
                ring.append(_Snap(now, *cum))

    # -- reporting ---------------------------------------------------------
    def _window_stats(
        self, ring: deque[_Snap], window_s: float
    ) -> tuple[int, int, float]:
        """(good, total, covered_s) of the newest window over the ring."""
        if not ring:
            return 0, 0, 0.0
        latest = ring[-1]
        base = ring[0]
        horizon = latest.t - window_s
        for snap in ring:
            # the NEWEST snapshot at or before the horizon anchors the
            # window; all-younger rings degrade to the oldest (partial)
            if snap.t <= horizon:
                base = snap
            else:
                break
        return (
            latest.good - base.good,
            latest.total - base.total,
            latest.t - base.t,
        )

    def collect(self) -> None:
        """Registry collector: refresh the gauges at scrape time."""
        self.update()
        with self._lock:
            for o in self.objectives:
                ring = self._rings[o.name]
                for label, window_s in WINDOWS:
                    good, total, _cov = self._window_stats(ring, window_s)
                    ratio = 1.0 if total <= 0 else good / total
                    burn = (1.0 - ratio) / (1.0 - o.target)
                    self.m_good.labels(objective=o.name, window=label).set(
                        ratio
                    )
                    self.m_burn.labels(objective=o.name, window=label).set(
                        burn
                    )

    def snapshot(self) -> dict:
        """The ``/slo`` endpoint body."""
        self.update()
        with self._lock:
            out: dict = {"objectives": []}
            for o in self.objectives:
                ring = self._rings[o.name]
                windows = {}
                for label, window_s in WINDOWS:
                    good, total, cov = self._window_stats(ring, window_s)
                    ratio = 1.0 if total <= 0 else good / total
                    windows[label] = {
                        "events": total,
                        "good_ratio": round(ratio, 6),
                        "burn_rate": round(
                            (1.0 - ratio) / (1.0 - o.target), 4
                        ),
                        "window_covered_s": round(cov, 1),
                    }
                obj = {
                    "name": o.name,
                    "stage": o.stage,
                    "threshold_s": o.threshold_s,
                    "target": o.target,
                    "source_present": self._seen[o.name],
                    "windows": windows,
                }
                if o.cls is not None:
                    # keyed only when set: class-free configs keep their
                    # pre-attribution /slo body
                    obj["class"] = o.cls
                out["objectives"].append(obj)
            return out
