"""Strict Prometheus text-exposition parser (validation, not ingestion).

Consumed by the conformance tests and by the bench smoke job's mid-run
``/metrics`` scrape: both need to FAIL on exposition our renderer (or a
future backend) could plausibly get wrong — HELP/TYPE ordering, label
escaping, histogram bucket monotonicity, the ``+Inf``/``_sum``/``_count``
invariants — rather than shrug like a lenient scraper would.

:func:`parse_exposition` raises :class:`ExpositionError` on the first
violation and otherwise returns ``{family_name: Family}``.
"""

from __future__ import annotations

import math
import re
from dataclasses import dataclass, field

_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
_LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
#: one label pair inside the braces: name="escaped value"
_LABEL_PAIR_RE = re.compile(r'([a-zA-Z_][a-zA-Z0-9_]*)="((?:[^"\\]|\\.)*)"')

TYPES = ("counter", "gauge", "histogram", "summary", "untyped")


class ExpositionError(ValueError):
    """A violation of the exposition grammar or of a type invariant."""


@dataclass
class Sample:
    name: str
    labels: dict[str, str]
    value: float


@dataclass
class Family:
    name: str
    mtype: str
    help: str
    samples: list[Sample] = field(default_factory=list)


def _unescape_label(raw: str, lineno: int) -> str:
    out: list[str] = []
    i = 0
    while i < len(raw):
        c = raw[i]
        if c == "\\":
            if i + 1 >= len(raw):
                raise ExpositionError(f"line {lineno}: dangling backslash")
            nxt = raw[i + 1]
            if nxt == "\\":
                out.append("\\")
            elif nxt == '"':
                out.append('"')
            elif nxt == "n":
                out.append("\n")
            else:
                raise ExpositionError(
                    f"line {lineno}: invalid escape \\{nxt} in label value"
                )
            i += 2
        else:
            out.append(c)
            i += 1
    return "".join(out)


def _parse_value(raw: str, lineno: int) -> float:
    if raw == "+Inf":
        return math.inf
    if raw == "-Inf":
        return -math.inf
    if raw == "NaN":
        return math.nan
    try:
        return float(raw)
    except ValueError:
        raise ExpositionError(f"line {lineno}: bad sample value {raw!r}") from None


def _parse_sample(line: str, lineno: int) -> Sample:
    rest = line
    brace = rest.find("{")
    labels: dict[str, str] = {}
    if brace >= 0:
        name = rest[:brace]
        close = rest.rfind("}")
        if close < brace:
            raise ExpositionError(f"line {lineno}: unbalanced braces")
        body = rest[brace + 1 : close]
        tail = rest[close + 1 :]
        pos = 0
        while pos < len(body):
            m = _LABEL_PAIR_RE.match(body, pos)
            if m is None:
                raise ExpositionError(
                    f"line {lineno}: malformed label pair near {body[pos:]!r}"
                )
            lname = m.group(1)
            if lname in labels:
                raise ExpositionError(
                    f"line {lineno}: duplicate label {lname!r}"
                )
            labels[lname] = _unescape_label(m.group(2), lineno)
            pos = m.end()
            if pos < len(body):
                if body[pos] != ",":
                    raise ExpositionError(
                        f"line {lineno}: expected ',' between labels"
                    )
                pos += 1
    else:
        parts = rest.split(None, 1)
        if len(parts) != 2:
            raise ExpositionError(f"line {lineno}: sample without value")
        name, tail = parts[0], " " + parts[1]
    if not _NAME_RE.match(name):
        raise ExpositionError(f"line {lineno}: invalid sample name {name!r}")
    tail = tail.strip()
    fields = tail.split()
    if len(fields) not in (1, 2):  # optional trailing timestamp
        raise ExpositionError(f"line {lineno}: trailing garbage {tail!r}")
    return Sample(name, labels, _parse_value(fields[0], lineno))


def _strip_suffix(name: str) -> tuple[str, str]:
    for suffix in ("_bucket", "_sum", "_count"):
        if name.endswith(suffix):
            return name[: -len(suffix)], suffix
    return name, ""


def _check_histogram(fam: Family) -> None:
    """Bucket monotonicity + the +Inf/_sum/_count invariants, per child."""
    by_child: dict[tuple, dict] = {}

    def child_key(labels: dict[str, str]) -> tuple:
        return tuple(
            sorted((k, v) for k, v in labels.items() if k != "le")
        )

    for s in fam.samples:
        base, suffix = _strip_suffix(s.name)
        entry = by_child.setdefault(
            child_key(s.labels), {"buckets": [], "sum": None, "count": None}
        )
        if suffix == "_bucket":
            if "le" not in s.labels:
                raise ExpositionError(
                    f"{fam.name}: histogram bucket without an 'le' label"
                )
            le = s.labels["le"]
            upper = math.inf if le == "+Inf" else _parse_value(le, 0)
            entry["buckets"].append((upper, s.value))
        elif suffix == "_sum":
            entry["sum"] = s.value
        elif suffix == "_count":
            entry["count"] = s.value
        else:
            raise ExpositionError(
                f"{fam.name}: unexpected histogram sample {s.name!r}"
            )
    for key, entry in by_child.items():
        buckets = entry["buckets"]
        if not buckets:
            raise ExpositionError(f"{fam.name}{dict(key)}: no buckets")
        uppers = [u for u, _ in buckets]
        if uppers != sorted(uppers):
            raise ExpositionError(
                f"{fam.name}{dict(key)}: 'le' bounds not sorted"
            )
        if len(set(uppers)) != len(uppers):
            raise ExpositionError(
                f"{fam.name}{dict(key)}: duplicate 'le' bound"
            )
        if not math.isinf(uppers[-1]):
            raise ExpositionError(
                f"{fam.name}{dict(key)}: missing le=\"+Inf\" bucket"
            )
        counts = [c for _, c in buckets]
        if any(b < a for a, b in zip(counts, counts[1:])):
            raise ExpositionError(
                f"{fam.name}{dict(key)}: bucket counts not cumulative"
            )
        if entry["count"] is None or entry["sum"] is None:
            raise ExpositionError(
                f"{fam.name}{dict(key)}: missing _count or _sum"
            )
        if entry["count"] != counts[-1]:
            raise ExpositionError(
                f"{fam.name}{dict(key)}: _count != +Inf bucket count"
            )


def parse_exposition(text: str) -> dict[str, Family]:
    """Parse + validate one exposition body. Raises ExpositionError on:

    - a sample appearing before its family's ``# HELP``/``# TYPE`` pair,
      HELP/TYPE out of order, or either repeated for one family;
    - invalid metric/label names, malformed or unescaped label values,
      duplicate labels in one sample, unparseable values;
    - a sample name that doesn't belong to the declared family (histogram
      suffix rules included);
    - histogram invariants: sorted unique ``le`` bounds ending in
      ``+Inf``, cumulative bucket counts, ``_count`` equal to the ``+Inf``
      bucket, ``_sum``/``_count`` present;
    - counters with negative values;
    - a duplicate (name, labels) series within the body.
    """
    families: dict[str, Family] = {}
    current: Family | None = None
    pending_help: tuple[str, str] | None = None
    seen_series: set[tuple[str, tuple]] = set()
    lines = text.split("\n")
    if not text.endswith("\n"):
        raise ExpositionError("exposition must end with a newline")
    for lineno, line in enumerate(lines, start=1):
        if line == "":
            continue
        if line.startswith("# HELP "):
            parts = line[len("# HELP ") :].split(" ", 1)
            name = parts[0]
            if not _NAME_RE.match(name):
                raise ExpositionError(f"line {lineno}: bad HELP name {name!r}")
            if name in families or (pending_help and pending_help[0] == name):
                raise ExpositionError(
                    f"line {lineno}: repeated HELP for {name!r}"
                )
            if pending_help is not None:
                raise ExpositionError(
                    f"line {lineno}: HELP for {name!r} while "
                    f"{pending_help[0]!r} still lacks a TYPE"
                )
            pending_help = (name, parts[1] if len(parts) > 1 else "")
            continue
        if line.startswith("# TYPE "):
            parts = line[len("# TYPE ") :].split()
            if len(parts) != 2 or parts[1] not in TYPES:
                raise ExpositionError(f"line {lineno}: malformed TYPE line")
            name, mtype = parts
            if pending_help is None or pending_help[0] != name:
                raise ExpositionError(
                    f"line {lineno}: TYPE for {name!r} without a preceding "
                    "HELP (HELP must come first)"
                )
            if name in families:
                raise ExpositionError(
                    f"line {lineno}: repeated TYPE for {name!r}"
                )
            current = Family(name, mtype, pending_help[1])
            families[name] = current
            pending_help = None
            continue
        if line.startswith("#"):
            continue  # plain comment
        sample = _parse_sample(line, lineno)
        base, suffix = _strip_suffix(sample.name)
        if current is None:
            raise ExpositionError(
                f"line {lineno}: sample before any HELP/TYPE declaration"
            )
        if current.mtype == "histogram":
            if base != current.name or suffix == "":
                raise ExpositionError(
                    f"line {lineno}: sample {sample.name!r} outside its "
                    f"declared family {current.name!r}"
                )
        elif sample.name != current.name:
            raise ExpositionError(
                f"line {lineno}: sample {sample.name!r} outside its "
                f"declared family {current.name!r}"
            )
        for lname in sample.labels:
            if not _LABEL_NAME_RE.match(lname):
                raise ExpositionError(
                    f"line {lineno}: invalid label name {lname!r}"
                )
        series = (sample.name, tuple(sorted(sample.labels.items())))
        if series in seen_series:
            raise ExpositionError(
                f"line {lineno}: duplicate series {sample.name} "
                f"{sample.labels}"
            )
        seen_series.add(series)
        if current.mtype == "counter" and sample.value < 0:
            raise ExpositionError(
                f"line {lineno}: counter {sample.name} is negative"
            )
        current.samples.append(sample)
    if pending_help is not None:
        raise ExpositionError(f"HELP for {pending_help[0]!r} without a TYPE")
    for fam in families.values():
        if fam.mtype == "histogram":
            _check_histogram(fam)
    return families


def require_series(
    families: dict[str, Family], names: list[str]
) -> list[str]:
    """Missing family names out of ``names`` (empty list = all present) —
    the bench smoke scrape's required-series check."""
    return [n for n in names if n not in families]
