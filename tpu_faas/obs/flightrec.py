"""Fleet flight recorder: a bounded per-process ring of structured events.

A p999 outlier's histogram bucket tells you *that* it happened; the
flight recorder tells you what the process was doing *around* it. Each
metrics-owning process (gateway, dispatcher) keeps one bounded,
lock-cheap ring of small structured events — tick records (pending /
inflight / dispatched counts, device dispatch count, solver backend),
hedge decisions with their scores, tenant deficit snapshots, express-gate
verdicts, admission/brownout sheds, columnar arena fallbacks — each
stamped with a wall-clock time and, where the emitting site has one, the
task/trace id, so an assembled ``/trace`` timeline joins back to its
tick-local context.

Design constraints, in order:

- **emit() must be hot-loop cheap.** One short lock, one deque append,
  no serialization, no clock syscalls beyond the one stamp. Sites emit
  from the dispatcher tick and the gateway result path; a recorder that
  costs anything measurable there would distort the thing it records.
- **Bounded, always.** ``deque(maxlen=capacity)`` — the ring can never
  grow past capacity regardless of emit rate; overwritten events are
  counted (``dropped``) not silent.
- **Readable while written.** ``snapshot()`` copies under the same lock
  (capacity is small, the copy is microseconds) so HTTP scrapes race
  cleanly against emitters; a ``since`` cursor makes polling
  incremental.

Served as ``GET /flightrec?since=N`` on the gateway and the dispatcher
stats server, and dumped to the log on SIGTERM (``install_sigterm``) so
a killed process leaves its last seconds behind.
"""

from __future__ import annotations

import json
import signal
import threading
import time
from collections import deque

__all__ = ["FlightRecorder", "install_sigterm"]

#: default ring capacity (events); ~200 bytes/event keeps the worst-case
#: resident cost around 1 MB per process
DEFAULT_CAPACITY = 4096


class FlightRecorder:
    """One process's bounded event ring. Thread-safe; emit is O(1)."""

    def __init__(self, capacity: int = DEFAULT_CAPACITY, clock=time.time):
        if capacity < 1:
            raise ValueError("flight recorder capacity must be >= 1")
        self.capacity = int(capacity)
        self.clock = clock
        self._buf: deque[tuple[int, float, str, dict]] = deque(
            maxlen=self.capacity
        )
        self._seq = 0
        self._lock = threading.Lock()
        #: operator hint: a disabled recorder (capacity 1 via env, say)
        #: still answers /flightrec honestly
        self.enabled = True

    # -- write side --------------------------------------------------------
    def emit(self, kind: str, **fields) -> int:
        """Append one event; returns its sequence number. ``fields`` must
        already be JSON-representable scalars/short lists — emit does NOT
        serialize or validate (hot-loop budget), /flightrec does."""
        t = self.clock()
        with self._lock:
            self._seq += 1
            seq = self._seq
            self._buf.append((seq, t, kind, fields))
        return seq

    # -- read side ---------------------------------------------------------
    def snapshot(self, since: int = 0, limit: int = 0) -> dict:
        """Events with seq > ``since`` (oldest first), plus cursor state.

        ``cursor`` is the newest seq (pass it back as ``since`` to poll
        incrementally); ``dropped`` counts events overwritten before any
        reader saw the ring this deep. ``limit`` > 0 truncates to the
        NEWEST that many matching events (post-mortems want the end).
        """
        with self._lock:
            cursor = self._seq
            events = list(self._buf)
        oldest_held = events[0][0] if events else cursor + 1
        out = [e for e in events if e[0] > since]
        truncated = 0
        if limit and limit > 0 and len(out) > limit:
            truncated = len(out) - limit
            out = out[-limit:]
        return {
            "cursor": cursor,
            "capacity": self.capacity,
            # events emitted but no longer held (ring overwrote them)
            "dropped": max(0, oldest_held - 1),
            "truncated": truncated,
            "events": [
                {"seq": seq, "t": round(t, 6), "kind": kind, **fields}
                for (seq, t, kind, fields) in out
            ],
        }

    def dump_json(self, since: int = 0) -> str:
        """The snapshot as compact JSON (HTTP body / SIGTERM dump)."""
        return json.dumps(
            self.snapshot(since=since), separators=(",", ":"), default=str
        )


def install_sigterm(recorder: FlightRecorder, log) -> bool:
    """Dump the ring through ``log.warning`` on SIGTERM, then chain to the
    previous handler (or re-raise the default die). Returns False without
    touching handlers when not on the main thread (signal.signal raises
    there) or on platforms without SIGTERM — callers treat the dump as
    best-effort."""
    try:
        prev = signal.getsignal(signal.SIGTERM)
    except (ValueError, AttributeError, OSError):
        return False

    def _on_term(signum, frame):
        try:
            log.warning("flightrec SIGTERM dump: %s", recorder.dump_json())
        except Exception:
            pass  # dying anyway; the dump must never block the exit
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
            prev(signum, frame)
        else:
            signal.signal(signal.SIGTERM, signal.SIG_DFL)
            signal.raise_signal(signal.SIGTERM)

    try:
        signal.signal(signal.SIGTERM, _on_term)
    except (ValueError, OSError):
        # not the main thread (tests, embedded use): skip quietly
        return False
    return True
