"""Distributed trace context + the store-backed span plane.

The 9-event timelines of :mod:`tpu_faas.obs.trace` are assembled inside ONE
dispatcher process; this module is the cross-process half, Dapper-style:

- **Context**: every traced task carries a ``trace_id`` (lowercase hex,
  minted by the SDK, or by the gateway for legacy clients) plus an optional
  parent span id. The id rides the task record (``FIELD_TRACE_ID``), the
  TASK/RESULT worker frames (capability-gated — reference-era workers never
  see the field), and ``log_ctx`` so JSON logs correlate fleet-wide.
- **Span records**: each process emits ``(process, stage, t_start, t_end,
  attrs)`` records into the store under ``trace:<trace_id>`` hashes, one
  field per span named ``<process>:<stage>``. Writes are FIRST-WRITE-WINS
  (``hsetnx_many``): a replayed announce after a store failover, a zombie's
  duplicate RESULT, or a repeated /result poll can re-emit a span, and the
  first stamp must stand — duplicates are counted into
  ``tpu_faas_trace_duplicate_events_total`` instead of corrupting deltas.
- **Assembly**: :func:`assemble_timeline` reads the task record plus its
  trace hash and produces the ordered cross-process timeline — SDK submit
  → gateway admit → store create → dispatcher intake/queue/dispatch →
  worker exec → dispatcher finalize → client observe — including the
  poll-gap segment (``gateway:observe``) the dispatcher-local view
  structurally cannot see.

Span timestamps are epoch seconds: gateway and worker stamps are
``time.time()``-family, dispatcher stamps are monotonic-anchored
(:func:`tpu_faas.obs.trace.anchored_now`), so cross-process spans compare
up to host clock sync — same contract as the 9-event timeline.

The span namespace is bounded on both ends: each :class:`SpanSink` buffer
is capped (overflow drops the OLDEST records and counts them), the span
catalog per trace is a fixed small set of fields, and the gateway's
result-TTL sweeper ages ``trace:`` hashes out by their ``t0`` stamp
exactly like terminal task records — with no sweeper configured, spans
accumulate like task records do (the reference's grow-until-FLUSHDB
contract, unchanged).
"""

from __future__ import annotations

import json
import re
import threading
import time
import uuid
from dataclasses import dataclass, field

#: Store namespace of the span plane: one hash per trace id.
TRACE_PREFIX = "trace:"
#: Epoch stamp of the trace hash's first span write — the TTL sweeper's
#: aging field (trace hashes have no status; without this they would be
#: invisible to every sweep and leak forever).
TRACE_AT_FIELD = "t0"
#: Task id the trace belongs to, written beside the stamp: the sweeper
#: uses it to SKIP aged hashes whose task is still live — a task queued
#: or running past the result TTL must not lose its early spans
#: mid-flight. Hashes without it (older producers) age by stamp alone.
TRACE_TASK_FIELD = "task"

#: Wire/body field names shared by the SDKs and the gateway.
TRACE_ID_KEY = "trace_id"
PARENT_SPAN_KEY = "parent_span"

_TRACE_ID_RE = re.compile(r"^[0-9a-f]{8,64}$")


def new_trace_id() -> str:
    return uuid.uuid4().hex


def new_span_id() -> str:
    return uuid.uuid4().hex[:16]


def valid_trace_id(value: object) -> bool:
    """Client-supplied trace ids are untrusted input that becomes a store
    KEY: lowercase hex only, bounded length — anything else is rejected at
    the gateway (400) instead of letting a caller mint arbitrary keys."""
    return isinstance(value, str) and bool(_TRACE_ID_RE.match(value))


def trace_key(trace_id: str) -> str:
    return TRACE_PREFIX + trace_id


def span_field(process: str, stage: str) -> str:
    return f"{process}:{stage}"


def encode_span(t_start: float, t_end: float, attrs: dict | None) -> str:
    """Compact JSON value of one span field: ``[t_start, t_end, attrs]``."""
    return json.dumps(
        [round(float(t_start), 6), round(float(t_end), 6), attrs or {}],
        separators=(",", ":"),
    )


def decode_span(
    process_stage: str, raw: str
) -> tuple[str, str, float, float, dict] | None:
    """(process, stage, t_start, t_end, attrs), or None for anything
    unparseable — a foreign producer's field must not 500 the assembly."""
    if ":" not in process_stage:
        return None
    process, stage = process_stage.split(":", 1)
    try:
        t_start, t_end, attrs = json.loads(raw)
        t_start, t_end = float(t_start), float(t_end)
    except (ValueError, TypeError):
        return None
    if not isinstance(attrs, dict):
        attrs = {}
    return process, stage, t_start, t_end, attrs


@dataclass
class _PendingSpan:
    trace_id: str
    field: str
    value: str
    stamp: str
    task_id: str | None = None


@dataclass
class SpanSink:
    """Buffered, first-write-wins span writer for one process.

    ``emit`` is hot-path cheap (list append under a lock); ``flush`` pays
    the store round trip — serve loops call it periodically, the gateway
    runs it from a background task. A flush that hits a store outage keeps
    the buffer (bounded) and retries on the next call: spans are telemetry,
    they degrade, they never wedge dispatch."""

    store: object
    process: str
    registry: object | None = None
    max_buffer: int = 4096
    _lock: threading.Lock = field(default_factory=threading.Lock)
    _buf: list[_PendingSpan] = field(default_factory=list)

    def __post_init__(self) -> None:
        self.n_written = 0
        self.n_duplicates = 0
        self.n_dropped = 0
        #: TTL stamps (field dicts: t0 + optional task id) whose write
        #: failed AFTER their spans landed — retried on the next flush (an
        #: unstamped trace hash would be invisible to the sweeper forever;
        #: re-queueing the SPANS instead would fabricate duplicate counts
        #: on retry)
        self._pending_stamps: dict[str, dict[str, str]] = {}
        self._m_dup = self._m_drop = None
        if self.registry is not None:
            self._m_dup = self.registry.counter(
                "tpu_faas_trace_duplicate_events_total",
                "Trace event/span stamps suppressed by first-write-wins "
                "recording, by event — replay storms (announce replay "
                "after failover, zombie duplicate RESULTs) surface here "
                "instead of silently corrupting stage deltas",
                ("event",),
            )
            self._m_drop = self.registry.counter(
                "tpu_faas_trace_spans_dropped_total",
                "Span records dropped because the sink buffer overflowed "
                "(sustained store outage or a span burst beyond the "
                "flush cadence)",
            )

    def emit(
        self,
        trace_id: str,
        stage: str,
        t_start: float,
        t_end: float,
        task_id: str | None = None,
        **attrs: object,
    ) -> None:
        """Buffer one span of this sink's process. Never blocks on the
        store; overflow drops the OLDEST buffered spans (counted).
        ``task_id`` (when the caller knows it) rides into the trace
        hash's ``task`` field so the sweeper can check task liveness."""
        self.emit_as(
            self.process,
            trace_id,
            stage,
            t_start,
            t_end,
            task_id=task_id,
            **attrs,
        )

    def emit_as(
        self,
        process: str,
        trace_id: str,
        stage: str,
        t_start: float,
        t_end: float,
        task_id: str | None = None,
        **attrs: object,
    ) -> None:
        """``emit`` under an explicit process name — for spans this
        process persists ON BEHALF of another (the dispatcher writes the
        worker's exec window: the stamps are worker-measured, but workers
        have no store access)."""
        span = _PendingSpan(
            trace_id,
            span_field(process, stage),
            encode_span(t_start, t_end, attrs),
            repr(t_start),
            task_id,
        )
        with self._lock:
            self._buf.append(span)
            overflow = len(self._buf) - self.max_buffer
            if overflow > 0:
                del self._buf[:overflow]
                self.n_dropped += overflow
                if self._m_drop is not None:
                    self._m_drop.inc(overflow)

    def __len__(self) -> int:
        return len(self._buf)

    @property
    def dirty(self) -> bool:
        """True while a flush would do work: buffered spans OR TTL stamps
        whose write failed after their spans landed. Flush-gating on the
        buffer alone would strand those stamps whenever traffic stops —
        and an unstamped trace hash is invisible to the sweeper forever."""
        return bool(self._buf or self._pending_stamps)

    def flush(self) -> int:
        """Write every buffered span in one pipelined first-write-wins
        round (+ one stamp round). Returns spans written; on ANY store
        failure the batch is restored (bounded) and the error swallowed —
        the next flush retries."""
        with self._lock:
            batch, self._buf = self._buf, []
            stamps = self._pending_stamps
            self._pending_stamps = {}
        if not batch and not stamps:
            return 0
        try:
            created = self.store.hsetnx_many(
                [(trace_key(s.trace_id), s.field, s.value) for s in batch]
            )
        except Exception:
            # the spans never landed: restore them (bounded) AND the
            # carried-over stamps, retry on the next flush
            with self._lock:
                self._buf = batch + self._buf
                overflow = len(self._buf) - self.max_buffer
                if overflow > 0:
                    del self._buf[:overflow]
                    self.n_dropped += overflow
                    if self._m_drop is not None:
                        self._m_drop.inc(overflow)
                self._pending_stamps = {**stamps, **self._pending_stamps}
            return 0
        n = 0
        for s, won in zip(batch, created):
            if won:
                n += 1
            else:
                self.n_duplicates += 1
                if self._m_dup is not None:
                    self._m_dup.labels(event=s.field).inc()
        self.n_written += n
        # TTL stamp (+ task id when known), last-write-wins (hset):
        # refreshed per flush so an active trace never ages out under its
        # own spans. The spans above ALREADY landed — a failure here must
        # NOT restore them (the retry would re-HSETNX them all and
        # fabricate a batch-sized duplicate-count spike), so only the
        # stamps carry over to the next flush.
        for s in batch:
            entry = stamps.setdefault(trace_key(s.trace_id), {})
            entry[TRACE_AT_FIELD] = s.stamp
            if s.task_id:
                entry.setdefault(TRACE_TASK_FIELD, s.task_id)
        try:
            self.store.hset_many(list(stamps.items()))
        except Exception:
            with self._lock:
                self._pending_stamps = {**stamps, **self._pending_stamps}
                # bounded like the span buffer: drop the OLDEST stamps
                while len(self._pending_stamps) > self.max_buffer:
                    self._pending_stamps.pop(
                        next(iter(self._pending_stamps))
                    )
        return n


def assemble_timeline(store, task_id: str) -> dict | None:
    """The full cross-process timeline of one task, assembled from its
    record + its ``trace:<trace_id>`` span hash. None when the task is
    unknown. Tasks without a trace id (legacy producers, tracing off)
    assemble to their record status with zero spans — the endpoint stays
    truthful instead of 404ing a real task."""
    from tpu_faas.core.task import (
        FIELD_STATUS,
        FIELD_SUBMITTED_AT,
        FIELD_TRACE_ID,
        FIELD_TRACE_PARENT,
    )

    fields = store.hgetall(task_id)
    if not fields or FIELD_STATUS not in fields:
        return None
    trace_id = fields.get(FIELD_TRACE_ID)
    spans: list[dict] = []
    if trace_id:
        raw = store.hgetall(trace_key(trace_id))
        for name, value in raw.items():
            if name in (TRACE_AT_FIELD, TRACE_TASK_FIELD):
                continue
            parsed = decode_span(name, value)
            if parsed is None:
                continue
            process, stage, t_start, t_end, attrs = parsed
            spans.append(
                {
                    "process": process,
                    "stage": stage,
                    "t_start": round(t_start, 6),
                    "t_end": round(t_end, 6),
                    "duration_s": round(max(0.0, t_end - t_start), 6),
                    "attrs": attrs,
                }
            )
    spans.sort(key=lambda s: (s["t_start"], s["t_end"]))
    processes: list[str] = []
    for s in spans:
        if s["process"] not in processes:
            processes.append(s["process"])
    out: dict = {
        "task_id": task_id,
        "trace_id": trace_id,
        "parent_span": fields.get(FIELD_TRACE_PARENT),
        "status": fields.get(FIELD_STATUS),
        "submitted_at": fields.get(FIELD_SUBMITTED_AT),
        "processes": processes,
        "n_stages": len(spans),
        "spans": spans,
    }
    if spans:
        t0 = min(s["t_start"] for s in spans)
        t1 = max(s["t_end"] for s in spans)
        out["t_start"] = round(t0, 6)
        out["total_s"] = round(max(0.0, t1 - t0), 6)
        # the poll gap and any other uncovered wall time between spans:
        # sorted sweep over the merged intervals
        covered = 0.0
        cursor = t0
        for s in spans:
            if s["t_end"] <= cursor:
                continue
            covered += s["t_end"] - max(s["t_start"], cursor)
            cursor = s["t_end"]
        out["uncovered_s"] = round(max(0.0, (t1 - t0) - covered), 6)
    return out


def sweep_stale_traces(
    store, all_keys: list[str], ttl: float, now: float | None = None
) -> list[str]:
    """Trace hashes whose ``t0`` stamp aged past ``ttl`` — the gateway's
    result-TTL sweeper deletes them alongside terminal task records (the
    span plane must not outlive the records it describes by more than one
    TTL). Unparseable or missing stamps are never collected, and an aged
    hash whose ``task`` field points at a still-live (non-terminal) task
    record is SKIPPED: the stamp only refreshes when new spans flush, so
    a task queued or running past the TTL would otherwise lose its early
    spans mid-flight. Hashes without a task field (older producers) age
    by stamp alone."""
    from tpu_faas.core.task import FIELD_STATUS, TaskStatus

    now_f = now if now is not None else time.time()
    keys = [k for k in all_keys if k.startswith(TRACE_PREFIX)]
    if not keys:
        return []
    aged: list[str] = []
    for key, stamp in zip(keys, store.hget_many(keys, TRACE_AT_FIELD)):
        if not isinstance(stamp, str):
            continue
        try:
            if now_f - float(stamp) > ttl:
                aged.append(key)
        except ValueError:
            continue
    if not aged:
        return []
    task_ids = store.hget_many(aged, TRACE_TASK_FIELD)
    with_task = [
        (k, t) for k, t in zip(aged, task_ids) if isinstance(t, str) and t
    ]
    live: set[str] = set()
    if with_task:
        statuses = store.hget_many([t for _, t in with_task], FIELD_STATUS)
        for (key, _), status in zip(with_task, statuses):
            # a record that exists with a non-terminal status is live;
            # missing records (already swept) and terminal ones collect
            if status is not None and not TaskStatus.terminal_str(
                status, unknown=True
            ):
                live.add(key)
    return [k for k in aged if k not in live]
