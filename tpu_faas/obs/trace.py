"""Per-task lifecycle tracing: where did this task's latency go?

Every task record accumulates up to nine event stamps on its way through
the system::

    submitted -> announced -> intake -> scheduled -> sent
        -> exec_start -> exec_end -> result_received -> finished

``submitted`` is stamped by the gateway onto the task hash
(``FIELD_SUBMITTED_AT``); ``exec_start``/``exec_end`` are measured in the
worker's pool child and ride the RESULT message (``started_at`` +
``elapsed``); everything else is stamped by the dispatcher as the task
passes each boundary. Dispatcher-side stamps are *monotonic-anchored*:
:func:`anchored_now` returns ``time.monotonic()`` shifted by a
process-start anchor onto the epoch, so intra-process deltas are immune to
wall-clock steps while cross-process stamps (gateway, worker — raw
``time.time()``) remain comparable up to host clock sync.

On ``finished`` the timeline is closed: per-stage deltas are observed into
the ``tpu_faas_task_stage_seconds{stage=...,terminal=...}`` histogram of
the owning registry (the scrapeable aggregate; ``terminal`` carries the
closing outcome so shed/cancelled populations don't pollute the COMPLETED
latency distribution), and the full timeline moves into a bounded ring of
recent completions plus a bounded slowest-task list — the raw material
behind the dispatcher's ``/trace/<task_id>`` and ``/trace`` debug
endpoints. No per-task storage survives beyond those rings; an optional
``on_close`` callback hands each closed record to the cross-process span
plane (obs/tracectx.py).

Recording is FIRST-WRITE-WINS: a duplicate stamp of an already-present
event (replayed announce after a store failover, a re-dispatch, a
zombie's late RESULT) keeps the original and is counted into
``tpu_faas_trace_duplicate_events_total{event}`` — replay storms become
visible instead of silently corrupting stage deltas.
"""

from __future__ import annotations

import heapq
import itertools
import threading
import time
from collections import deque

from tpu_faas.obs.attribution import (
    DEFAULT_CLASS,
    SLO_CLASSES,
    class_label_enabled,
    latency_buckets,
)
from tpu_faas.obs.metrics import LATENCY_BUCKETS

#: Canonical event order (also the order ``timeline()`` reports).
EVENTS = (
    "submitted",
    "announced",
    "intake",
    "scheduled",
    "sent",
    "exec_start",
    "exec_end",
    "result_received",
    "finished",
)

#: stage -> (from_event, to_event). Stages whose endpoints are both present
#: on a closing timeline are observed into the stage histogram.
STAGES = {
    # gateway write + announce-bus latency
    "submit_to_announce": ("submitted", "announced"),
    # graph children only: WAITING stretch from create to the promotion
    # plane's WAITING -> QUEUED flip ("promoted" is stamped at intake of
    # the promoted record, or at the frontier's in-tick readiness) — both
    # endpoints absent on flat tasks, so the stage never observes there
    "dep_wait": ("submitted", "promoted"),
    # waiting in the pending structures for a placement decision
    "queue_wait": ("announced", "scheduled"),
    # device-schedule latency: placement decision -> task on the wire
    "device_schedule": ("scheduled", "sent"),
    # wire + worker pool queueing before the child picks it up
    "dispatch_to_start": ("sent", "exec_start"),
    # the user function itself (measured in the pool child)
    "execution": ("exec_start", "exec_end"),
    # result's trip back over the wire into the dispatcher drain
    "result_return": ("exec_end", "result_received"),
    # terminal store write landing after the result arrived
    "finalize": ("result_received", "finished"),
    # speculation plane (tpu_faas/spec): hedge replica launched for a
    # straggling execution -> first result resolved the race. Both
    # endpoints absent on unhedged tasks, so the stage never observes
    # there — the hedged population's detection-to-resolution window.
    "hedge_window": ("hedge_launched", "hedge_resolved"),
    # end to end
    "total": ("submitted", "finished"),
}

_ANCHOR = time.time() - time.monotonic()


def anchored_now() -> float:
    """Epoch seconds sampled via the monotonic clock: comparable across
    processes on one host, immune to wall-clock steps within a process."""
    return _ANCHOR + time.monotonic()


class TaskTraceBook:
    """Bounded event-timeline store + stage-histogram aggregation.

    Thread-safety: one lock around the dicts/rings — ``note`` is a dict
    probe plus an insert, cheap enough for the dispatcher's drain loops,
    and the stats thread snapshots under the same lock.
    """

    def __init__(
        self,
        registry,
        active_cap: int = 65536,
        recent_cap: int = 256,
        slowest_cap: int = 32,
        class_enabled: bool | None = None,
    ) -> None:
        self._lock = threading.Lock()
        self._active: dict[str, dict[str, float]] = {}
        #: task_id -> trace id (distributed trace context), carried beside
        #: the float-valued event dicts and popped with them — the closed
        #: record hands it to ``on_close`` so the span plane can key its
        #: cross-process writes
        self._trace_ids: dict[str, str] = {}
        #: TPU_FAAS_OBS_CLASS: the stage histogram grows a ``class`` label
        #: (obs/attribution.py vocabulary). Off (default) keeps labelnames,
        #: child set and exposition byte-identical to the two-label form.
        self.class_enabled = (
            class_label_enabled() if class_enabled is None else class_enabled
        )
        #: task_id -> SLO class, same lifecycle as ``_trace_ids`` (popped
        #: at finish/discard/eviction); only ever populated when the class
        #: label is on
        self._classes: dict[str, str] = {}
        self._recent: deque[dict] = deque(maxlen=recent_cap)
        self._completed: dict[str, dict] = {}
        self._active_cap = active_cap
        self._slowest_cap = slowest_cap
        #: (total_seconds, seq, timeline) min-heap of the slowest closures
        self._slowest: list[tuple[float, int, dict]] = []
        self._seq = itertools.count()
        self.n_completed = 0
        #: optional callback(record) invoked OUTSIDE the book lock for
        #: every closed timeline — the dispatcher wires the cross-process
        #: span emission here; exceptions are the caller's problem to
        #: avoid (span sinks never raise)
        self.on_close = None
        self._hist = registry.histogram(
            "tpu_faas_task_stage_seconds",
            "Per-stage task lifecycle latency (seconds), aggregated from "
            "the nine-event task timelines; 'terminal' is the closing "
            "outcome (COMPLETED/FAILED/CANCELLED/EXPIRED and the "
            "dispatcher-side drop reasons), so shed populations don't "
            "pollute the completed-latency distribution",
            ("stage", "terminal", "class")
            if self.class_enabled
            else ("stage", "terminal"),
            buckets=latency_buckets(LATENCY_BUCKETS),
        )
        self._m_dup = registry.counter(
            "tpu_faas_trace_duplicate_events_total",
            "Trace event/span stamps suppressed by first-write-wins "
            "recording, by event — replay storms (announce replay "
            "after failover, zombie duplicate RESULTs) surface here "
            "instead of silently corrupting stage deltas",
            ("event",),
        )
        # pre-create every stage child (for the common outcome): the scrape
        # shows the full stage catalog (at zero) before the first task
        # completes. With the class label on, the catalog spans the closed
        # class vocabulary too — explicit zeros per class.
        for stage in STAGES:
            if self.class_enabled:
                for cls in SLO_CLASSES:
                    self._hist.labels(stage, "COMPLETED", cls)
            else:
                self._hist.labels(stage=stage, terminal="COMPLETED")

    def stage_snapshot(
        self,
        stage: str,
        terminal: str | None = "COMPLETED",
        cls: str | None = None,
    ) -> tuple[tuple[float, ...], list[int]] | None:
        """(bucket uppers, per-bucket counts) for one stage — the SLO
        tracker's data source. COMPLETED outcomes only by default: shed
        (EXPIRED) and cancelled populations must not burn the latency
        error budget — shedding under overload is intended behavior, and
        counting quick cancels as "good" would dilute real violations.
        ``terminal=None`` sums across every outcome. None for an unknown
        stage with no series yet.

        ``cls`` restricts to one SLO class. With the class label OFF a
        class-restricted read returns None — ``sum_counts`` matches
        positionally against however many labels a child carries, so a
        three-element match against two-label children would silently
        match EVERY class; None keeps per-class objectives honestly
        reporting source-absent instead of lying with aggregate counts.
        """
        if cls is not None:
            if not self.class_enabled:
                return None
            return self._hist.sum_counts((stage, terminal, cls))
        return self._hist.sum_counts((stage, terminal))

    # -- recording ---------------------------------------------------------
    def note(
        self,
        task_id: str,
        event: str,
        ts: float | None = None,
        open_new: bool = True,
        count_dup: bool = True,
    ) -> None:
        """Stamp ``event`` on the task's timeline (first stamp wins: a
        re-dispatched task keeps its original ``sent``, and the retry is
        visible as ``retries`` on the closed record instead).

        ``open_new=False`` stamps ONLY an already-open timeline: events
        that can arrive after a task finished — a zombie worker's late
        second RESULT — must not resurrect the closed trace as a fresh
        (then duplicate-completed) one.

        ``count_dup=False`` suppresses the duplicate-counter tick for a
        re-stamp the CALLER knows is routine — the scheduled/sent stamps
        of a reclaimed task's redispatch are normal at-least-once
        operation (already visible as ``retries``), and counting them
        would page operators reading the counter as the replay-storm
        signal it is documented to be."""
        if ts is None:
            ts = anchored_now()
        with self._lock:
            events = self._active.get(task_id)
            if events is None:
                if not open_new:
                    return
                if len(self._active) >= self._active_cap:
                    # drop the oldest open timeline (dict preserves insert
                    # order): an abandoned trace must never grow memory
                    evicted = next(iter(self._active))
                    self._active.pop(evicted)
                    self._trace_ids.pop(evicted, None)
                    self._classes.pop(evicted, None)
                events = self._active[task_id] = {}
            duplicate = event in events
            events.setdefault(event, ts)
        if duplicate and count_dup:
            # first write wins; the suppressed stamp is counted so replay
            # storms (failover announce replay re-entering intake) are
            # operator-visible instead of silent
            self._m_dup.labels(event=event).inc()

    def note_trace(self, task_id: str, trace_id: str | None) -> None:
        """Attach the distributed trace id to an open (or about-to-open)
        timeline; first write wins, same as event stamps."""
        if not trace_id:
            return
        with self._lock:
            if task_id in self._active:
                self._trace_ids.setdefault(task_id, trace_id)

    def note_class(self, task_id: str, cls: str | None) -> None:
        """Attach the task's SLO class to an open timeline (first write
        wins). A no-op when the class label is off or the value is
        outside the closed vocabulary — off-vocabulary garbage must never
        become a label value."""
        if not self.class_enabled or cls not in SLO_CLASSES:
            return
        with self._lock:
            if task_id in self._active:
                self._classes.setdefault(task_id, cls)

    def note_retry(self, task_id: str) -> None:
        with self._lock:
            events = self._active.get(task_id)
            if events is not None:
                events["retries"] = events.get("retries", 0.0) + 1.0

    def finish(
        self, task_id: str, outcome: str, ts: float | None = None
    ) -> None:
        """Close the timeline: stamp ``finished``, observe stage deltas,
        move the record to the recent/slowest rings. Unknown task ids are
        ignored (a foreign producer's task finishing through this
        dispatcher has no open timeline)."""
        if ts is None:
            ts = anchored_now()
        with self._lock:
            events = self._active.pop(task_id, None)
            trace_id = self._trace_ids.pop(task_id, None)
            cls = self._classes.pop(task_id, DEFAULT_CLASS)
            if events is None:
                return
            already_closed = task_id in self._completed
        if already_closed:
            # FIRST COMPLETION WINS: a replayed announce (store-failover
            # re-arm) or a zombie's duplicate RESULT opened a stub
            # timeline for a task whose rich closed record still sits in
            # the ring — discard the stub instead of clobbering the
            # record, double-counting the completion, and polluting the
            # recent ring. Counted like any other suppressed replay.
            self._m_dup.labels(event="finished").inc()
            return
        with self._lock:
            events.setdefault("finished", ts)
            retries = int(events.pop("retries", 0))
            stages: dict[str, float] = {}
            for stage, (a, b) in STAGES.items():
                if a in events and b in events:
                    delta = events[b] - events[a]
                    if delta >= 0:
                        stages[stage] = delta
        # histogram observes OUTSIDE the book lock (the child has its own)
        for stage, delta in stages.items():
            if self.class_enabled:
                child = self._hist.labels(stage, str(outcome), cls)
            else:
                child = self._hist.labels(stage=stage, terminal=str(outcome))
            child.observe(delta)
        record = {
            "task_id": task_id,
            "trace_id": trace_id,
            "outcome": outcome,
            "retries": retries,
            "events": dict(sorted(events.items(), key=lambda kv: kv[1])),
            "stages": {k: round(v, 6) for k, v in stages.items()},
            "complete": all(e in events for e in EVENTS),
        }
        if self.class_enabled:
            record["slo_class"] = cls
        with self._lock:
            self.n_completed += 1
            if len(self._recent) == self._recent.maxlen:
                evicted = self._recent[0]
                self._completed.pop(evicted["task_id"], None)
            self._recent.append(record)
            self._completed[record["task_id"]] = record
            total = stages.get("total", stages.get("execution", 0.0))
            entry = (total, next(self._seq), record)
            if len(self._slowest) < self._slowest_cap:
                heapq.heappush(self._slowest, entry)
            elif total > self._slowest[0][0]:
                heapq.heapreplace(self._slowest, entry)
        on_close = self.on_close
        if on_close is not None:
            on_close(record)

    def discard(self, task_id: str) -> None:
        """Forget an open timeline without closing it (task claimed by a
        sibling dispatcher — its lifecycle belongs to them)."""
        with self._lock:
            self._active.pop(task_id, None)
            self._trace_ids.pop(task_id, None)
            self._classes.pop(task_id, None)

    # -- inspection --------------------------------------------------------
    def timeline(self, task_id: str) -> dict | None:
        """The task's timeline: the closed record if it finished recently,
        else a snapshot of the open (partial) one."""
        with self._lock:
            done = self._completed.get(task_id)
            if done is not None:
                return done
            events = self._active.get(task_id)
            if events is None:
                return None
            snap = {k: v for k, v in events.items() if k != "retries"}
            return {
                "task_id": task_id,
                "trace_id": self._trace_ids.get(task_id),
                "outcome": None,
                "retries": int(events.get("retries", 0)),
                "events": dict(sorted(snap.items(), key=lambda kv: kv[1])),
                "stages": {},
                "complete": False,
            }

    def recent(self, n: int = 32) -> list[dict]:
        with self._lock:
            return list(self._recent)[-n:]

    def slowest(self) -> list[dict]:
        with self._lock:
            entries = sorted(self._slowest, reverse=True)
        return [rec for _, _, rec in entries]

    def stats(self) -> dict:
        with self._lock:
            return {
                "active": len(self._active),
                "completed": self.n_completed,
            }
