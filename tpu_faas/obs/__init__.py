"""Unified telemetry: metrics registry, Prometheus exposition, task tracing.

The reference system has no observability beyond commented-out prints
(SURVEY §5.5); before this package, our replacements were ad hoc — each
dispatcher hand-rolled a ``stats()`` dict, the gateway exposed a disjoint
JSON ``/metrics``, and ``TickTracer`` percentiles lived in an in-memory
ring nobody could scrape. Three pillars replace that:

- :mod:`tpu_faas.obs.metrics` — process-wide ``Counter``/``Gauge``/
  ``Histogram`` primitives with label support, lock-cheap hot-path
  recording (fixed-bucket histograms, no per-sample storage), and a
  Prometheus text-exposition renderer. Every number in the system has one
  name, one type, one scrape path.
- :mod:`tpu_faas.obs.trace` — per-task lifecycle timelines: nine
  monotonic-anchored event stamps from submit to finish, aggregated into
  per-stage latency histograms and kept in a bounded ring for
  slowest-task inspection (``/trace/<task_id>`` on the dispatcher).
- :mod:`tpu_faas.obs.profile` — device-tick profiling hooks: jit-recompile
  counters (cache-miss detection per tick shape), tick-shape gauges, and
  an opt-in ``jax.profiler`` capture gated by ``TPU_FAAS_JAX_PROFILE_DIR``.

:mod:`tpu_faas.obs.expofmt` is the strict exposition-format parser the
conformance tests and the bench smoke scrape share.
"""

from __future__ import annotations

from tpu_faas.obs.metrics import (
    CONTENT_TYPE,
    LATENCY_BUCKETS,
    REGISTRY,
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    render,
)
from tpu_faas.obs.slo import Objective, SLOTracker
from tpu_faas.obs.trace import EVENTS, STAGES, TaskTraceBook, anchored_now
from tpu_faas.obs.tracectx import (
    SpanSink,
    assemble_timeline,
    new_trace_id,
    valid_trace_id,
)

__all__ = [
    "CONTENT_TYPE",
    "Counter",
    "EVENTS",
    "Gauge",
    "Histogram",
    "LATENCY_BUCKETS",
    "MetricsRegistry",
    "Objective",
    "REGISTRY",
    "SLOTracker",
    "STAGES",
    "SpanSink",
    "TaskTraceBook",
    "anchored_now",
    "assemble_timeline",
    "new_trace_id",
    "render",
    "valid_trace_id",
]
